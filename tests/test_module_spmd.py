"""Module(context=[N devices]) → one SPMD program over a dp mesh.

The reference ran one executor per GPU and sliced every batch in Python
(/root/reference/python/mxnet/module/executor_group.py:296-378,
module.py:751), reducing gradients through KVStore.  The TPU-native Module
instead dp-shards the whole batch into ONE compiled step; these tests assert
(a) shards actually land on all devices, (b) the multi-device run is
numerically identical to single-device, and (c) `--kv-store device` keeps
working unmodified on top of it.
"""
import numpy as np
import jax
import pytest

import mxnet_tpu as mx


def _problem(n=256, d=16, k=4, seed=3):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    W = rng.randn(d, k).astype(np.float32)
    Y = (X @ W).argmax(1).astype(np.float32)
    return X, Y


def _mlp():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _fit(ctx, X, Y, batch_size=64, num_epoch=3, kv="device"):
    np.random.seed(42)
    mx.random.seed(42)
    train = mx.io.NDArrayIter(X, Y, batch_size=batch_size)
    mod = mx.mod.Module(_mlp(), context=ctx)
    mod.fit(train, optimizer="sgd", kvstore=kv,
            optimizer_params={"learning_rate": 0.5, "momentum": 0.9},
            initializer=mx.init.Xavier(rnd_type="gaussian", factor_type="in",
                                       magnitude=2),
            num_epoch=num_epoch)
    return mod


def test_spmd_shards_on_all_devices():
    assert jax.device_count() >= 8, "conftest must force 8 CPU devices"
    X, Y = _problem()
    ctx = [mx.cpu(i) for i in range(8)]
    train = mx.io.NDArrayIter(X, Y, batch_size=64)
    mod = mx.mod.Module(_mlp(), context=ctx)
    mod.bind(data_shapes=train.provide_data,
             label_shapes=train.provide_label)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(kvstore="device", optimizer="sgd")
    batch = next(iter(train))
    mod.forward_backward(batch)
    mod.update()

    # the batch input is dp-sharded across all 8 devices...
    data_arr = mod._exec.arg_dict["data"]._data
    assert len(data_arr.sharding.device_set) == 8
    # ...one shard per device, 1/8th of the batch each
    shard_shapes = {s.data.shape for s in data_arr.addressable_shards}
    assert shard_shapes == {(8, 16)}
    # parameters + their gradients are replicated over the same mesh
    w = mod._exec.arg_dict["fc1_weight"]._data
    g = mod._exec.grad_dict["fc1_weight"]._data
    assert len(w.sharding.device_set) == 8
    assert len(g.sharding.device_set) == 8
    assert w.sharding.is_fully_replicated
    assert g.sharding.is_fully_replicated


def test_spmd_matches_single_device():
    X, Y = _problem()
    mod1 = _fit(mx.cpu(0), X, Y)
    mod8 = _fit([mx.cpu(i) for i in range(8)], X, Y)
    args1, _ = mod1.get_params()
    args8, _ = mod8.get_params()
    for name in args1:
        np.testing.assert_allclose(args1[name].asnumpy(),
                                   args8[name].asnumpy(),
                                   rtol=2e-5, atol=2e-5,
                                   err_msg="param %s diverged" % name)
    score = mod8.score(mx.io.NDArrayIter(X, Y, batch_size=64), "acc")
    assert score[0][1] > 0.9


def test_spmd_batch_not_divisible_raises():
    X, Y = _problem(n=60)
    train = mx.io.NDArrayIter(X, Y, batch_size=60)
    mod = mx.mod.Module(_mlp(), context=[mx.cpu(i) for i in range(8)])
    with pytest.raises(mx.base.MXNetError, match="not divisible"):
        mod.bind(data_shapes=train.provide_data,
                 label_shapes=train.provide_label)


def test_spmd_duplicate_context_raises():
    X, Y = _problem()
    train = mx.io.NDArrayIter(X, Y, batch_size=64)
    mod = mx.mod.Module(_mlp(), context=[mx.cpu(0), mx.cpu(0)])
    with pytest.raises(mx.base.MXNetError, match="duplicate"):
        mod.bind(data_shapes=train.provide_data,
                 label_shapes=train.provide_label)


def test_spmd_grad_req_add():
    X, Y = _problem()
    train = mx.io.NDArrayIter(X, Y, batch_size=64)
    mod = mx.mod.Module(_mlp(), context=[mx.cpu(i) for i in range(8)])
    mod.bind(data_shapes=train.provide_data,
             label_shapes=train.provide_label, grad_req="add")
    mod.init_params(mx.init.Xavier())
    batch = next(iter(train))
    mod.forward_backward(batch)
    g1 = mod._exec.grad_dict["fc1_weight"].asnumpy().copy()
    mod.forward_backward(batch)
    g2 = mod._exec.grad_dict["fc1_weight"].asnumpy()
    np.testing.assert_allclose(g2, 2 * g1, rtol=1e-5, atol=1e-6)


def test_spmd_forward_only_inference():
    X, Y = _problem()
    ctx = [mx.cpu(i) for i in range(8)]
    mod8 = _fit(ctx, X, Y, num_epoch=1)
    val = mx.io.NDArrayIter(X, None, batch_size=64)
    preds = mod8.predict(val)
    assert preds.shape == (256, 4)


# ---------------------------------------------------------------------------
# Mesh-native fused step: partition rules + ZeRO-1 sharded weight update
# ---------------------------------------------------------------------------

def _fit_steps(ctx, steps=10, optimizer="sgd",
               opt_params={"learning_rate": 0.5, "momentum": 0.9},
               symbol=None):
    """Deterministic fit_step loop (same seeds, same batch order) so the
    dp=8 ZeRO-1 run and the single-device fused run see identical data."""
    np.random.seed(42)
    mx.random.seed(42)
    X, Y = _problem()
    train = mx.io.NDArrayIter(X, Y, batch_size=64)
    mod = mx.mod.Module(symbol if symbol is not None else _mlp(),
                        context=ctx)
    mod.bind(data_shapes=train.provide_data,
             label_shapes=train.provide_label)
    mod.init_params(mx.init.Xavier(rnd_type="gaussian", factor_type="in",
                                   magnitude=2))
    mod.init_optimizer(kvstore=None, optimizer=optimizer,
                       optimizer_params=opt_params)
    it = iter(train)
    n = 0
    while n < steps:
        try:
            batch = next(it)
        except StopIteration:
            train.reset()
            it = iter(train)
            continue
        mod.fit_step(batch)
        n += 1
    return mod


def _state_leaves(mod):
    out = {}
    for name, sub in mod._fused["state"].items():
        out[name] = jax.tree_util.tree_leaves(sub)
    return out


def test_zero1_opt_state_sharded(monkeypatch):
    """MXTPU_ZERO=1 on a dp=8 mesh: every shardable optimizer-state leaf
    holds 1/8 per device; the indivisible fc2_bias (4,) falls back to
    replication and is COUNTED, not silent."""
    from mxnet_tpu import telemetry
    monkeypatch.setenv("MXTPU_ZERO", "1")
    mod = _fit_steps([mx.cpu(i) for i in range(8)], steps=2)
    leaves = _state_leaves(mod)
    # fc1_weight (32,16) momentum: dim0 sharded 8 ways, (4,16) per device
    (mom,) = leaves["fc1_weight"]
    assert len(mom.addressable_shards) == 8
    assert {s.data.shape for s in mom.addressable_shards} == {(4, 16)}
    assert not mom.sharding.is_fully_replicated
    # fc2_weight (4,32): dim0 indivisible, dim1 sharded -> (4,4) shards
    (mom2,) = leaves["fc2_weight"]
    assert {s.data.shape for s in mom2.addressable_shards} == {(4, 4)}
    # fc2_bias (4,): nothing divides 8 -> replicated fallback
    (momb,) = leaves["fc2_bias"]
    assert momb.sharding.is_fully_replicated
    # params themselves stay replicated (ZeRO-1, not FSDP)
    w = mod._exec.arg_dict["fc1_weight"]._data
    assert w.sharding.is_fully_replicated
    # the fallback is visible on the telemetry counter, and the gauges
    # carry the 1/N economics the BENCH_MODE=spmd probe asserts
    rep = telemetry.report()
    assert rep["counters"].get("sharding.fallbacks", 0) >= 1
    assert rep["gauges"].get("sharding.zero_stage") == 1
    total = sum(
        int(np.prod(l.shape)) * l.dtype.itemsize
        for leaves in _state_leaves(mod).values() for l in leaves)
    per_dev = rep["gauges"]["sharding.opt_state_bytes_per_device"]
    # fc2_bias (16 bytes) is replicated; everything else is 1/8
    assert per_dev < total / 4


@pytest.mark.parametrize("optimizer,opt_params", [
    ("sgd", {"learning_rate": 0.5, "momentum": 0.9}),
    ("adam", {"learning_rate": 0.05}),
])
def test_zero1_matches_single_device(monkeypatch, optimizer, opt_params):
    """10 ZeRO-1 steps on the dp=8 host mesh track the single-device
    fused step bit-tolerantly (reduce-scatter + sharded update +
    all-gather reassociates float sums, so exact bitwise equality is not
    the contract — 1e-5 relative is)."""
    mod1 = _fit_steps(mx.cpu(0), optimizer=optimizer,
                      opt_params=opt_params)
    monkeypatch.setenv("MXTPU_ZERO", "1")
    mod8 = _fit_steps([mx.cpu(i) for i in range(8)], optimizer=optimizer,
                      opt_params=opt_params)
    args1, _ = mod1.get_params()
    args8, _ = mod8.get_params()
    for name in args1:
        np.testing.assert_allclose(
            args1[name].asnumpy(), args8[name].asnumpy(),
            rtol=1e-5, atol=1e-6,
            err_msg="param %s diverged under ZeRO-1 (%s)"
                    % (name, optimizer))


def test_zero1_one_dispatch_per_step(monkeypatch):
    """The sharded update stays INSIDE the one donated program: steady
    state is exactly 1 dispatch and 0 compiles per step on the dp=8
    mesh."""
    from mxnet_tpu import profiler
    monkeypatch.setenv("MXTPU_ZERO", "1")
    mod = _fit_steps([mx.cpu(i) for i in range(8)], steps=2)  # warm
    X, Y = _problem()
    train = mx.io.NDArrayIter(X, Y, batch_size=64)
    batches = list(train)
    profiler.reset_step_stats()
    for b in batches:
        mod.fit_step(b)
    stats = profiler.step_stats()
    # profiler steps count INTERVALS (first note_step arms the clock);
    # the dispatch contract is per fit_step call
    assert stats["dispatch_count"] == len(batches)
    assert stats["dispatch_count"] / len(batches) == 1.0
    assert stats["compile_count"] == 0


def test_zero1_divergence_guard_inside_sharded_program(monkeypatch):
    """A NaN batch under ZeRO-1 skips tree-wide: params and sharded
    opt-state pass through unchanged, skipped_steps ticks, t rolls
    back — same contract as the single-device guard, same one
    program."""
    from mxnet_tpu import profiler
    monkeypatch.setenv("MXTPU_ZERO", "1")
    mod = _fit_steps([mx.cpu(i) for i in range(8)], steps=3)
    args_before, _ = mod.get_params()
    args_before = {k: v.asnumpy().copy() for k, v in args_before.items()}
    mom_before = {k: np.asarray(v[0]) for k, v in
                  _state_leaves(mod).items()}
    t_before = dict(mod._optimizer._index_update_count)
    X, Y = _problem()
    X[:] = np.nan
    bad = mx.io.NDArrayIter(X, Y, batch_size=64)
    skipped0 = profiler.step_stats()["skipped_steps"]
    mod.fit_step(next(iter(bad)))
    assert profiler.step_stats()["skipped_steps"] == skipped0 + 1
    assert dict(mod._optimizer._index_update_count) == t_before
    args_after, _ = mod.get_params()
    for name in args_before:
        np.testing.assert_array_equal(args_before[name],
                                      args_after[name].asnumpy())
    for name, m0 in mom_before.items():
        np.testing.assert_array_equal(
            m0, np.asarray(_state_leaves(mod)[name][0]))


def test_zero1_save_reshard_load_roundtrip(monkeypatch, tmp_path):
    """save(ZeRO-1, dp=8) -> manifest carries the sharding stamp, the
    .states payload is full-size (all-gathered) -> a fresh dp=8 module
    reshards it back onto 1/N slices at load and training state is
    preserved exactly."""
    import json
    monkeypatch.setenv("MXTPU_ZERO", "1")
    ctx = [mx.cpu(i) for i in range(8)]
    mod = _fit_steps(ctx, steps=5)
    prefix = str(tmp_path / "zck")
    mod.save_checkpoint(prefix, 1, save_optimizer_states=True)
    manifest = json.loads(
        (tmp_path / "zck-0001.manifest.json").read_text())
    stamp = manifest["sharding"]
    assert stamp["zero_stage"] == 1
    assert stamp["mesh"]["dp"] == 8
    assert stamp["opt_state"] == "gathered"
    assert "fc1_weight" in stamp["specs"]
    mom_saved = {k: np.asarray(v[0]) for k, v in
                 _state_leaves(mod).items()}

    mod2 = mx.mod.Module.load(prefix, 1, load_optimizer_states=True,
                              context=ctx)
    X, Y = _problem()
    train = mx.io.NDArrayIter(X, Y, batch_size=64)
    mod2.bind(data_shapes=train.provide_data,
              label_shapes=train.provide_label)
    mod2.init_params()
    mod2.init_optimizer(kvstore=None, optimizer="sgd",
                        optimizer_params={"learning_rate": 0.5,
                                          "momentum": 0.9})
    mod2.fit_step(next(iter(train)))  # forces _fused_setup + reshard
    leaves = _state_leaves(mod2)
    (mom,) = leaves["fc1_weight"]
    assert {s.data.shape for s in mom.addressable_shards} == {(4, 16)}
    # loaded momentum must be the SAVED momentum advanced by exactly the
    # one post-load step; cheaper and tighter: compare the pre-step
    # loaded state by reloading into a module we don't step
    mod3 = mx.mod.Module.load(prefix, 1, load_optimizer_states=True,
                              context=ctx)
    mod3.bind(data_shapes=train.provide_data,
              label_shapes=train.provide_label)
    mod3.init_params()
    mod3.init_optimizer(kvstore=None, optimizer="sgd",
                        optimizer_params={"learning_rate": 0.5,
                                          "momentum": 0.9})
    fused = mod3._fused_setup()
    for name, m0 in mom_saved.items():
        got = np.asarray(jax.tree_util.tree_leaves(fused["state"][name])[0])
        np.testing.assert_array_equal(m0, got,
                                      err_msg="state %s changed across "
                                              "save->reshard->load" % name)


def test_zero1_aot_cache_mesh_keyed(monkeypatch, tmp_path):
    """The AOT key is mesh-keyed and the CPU SPMD-deserialize hazard is
    quarantined: (a) a same-process module rebuild warm-starts from the
    in-process memo with 0 foreground compiles; (b) the SAME model on a
    dp=4 mesh over the same device pool gets its own key (compiles,
    never collides with dp=8 — the PR-6 topology-clobber class of bug);
    (c) NO mesh entry is written to disk on this backend — a replayed
    (deserialized) SPMD executable flakily corrupts the heap or
    deadlocks its collectives even donation-free (ROBUSTNESS.md §8), so
    cross-process CPU mesh restarts pay one compile by design while the
    memo covers rebinds/reconfigs.  On TPU-class backends the disk path
    stays on (deserialized_spmd_safe)."""
    from mxnet_tpu import aot_cache, profiler, telemetry
    monkeypatch.setenv("MXTPU_ZERO", "1")
    monkeypatch.setenv("MXTPU_AOT_CACHE_DIR", str(tmp_path))
    sym = _mlp()

    def build(ctx):
        np.random.seed(42)
        mx.random.seed(42)
        X, Y = _problem()
        train = mx.io.NDArrayIter(X, Y, batch_size=64)
        mod = mx.mod.Module(sym, context=ctx)
        mod.bind(data_shapes=train.provide_data,
                 label_shapes=train.provide_label)
        mod.init_params(mx.init.Xavier())
        mod.init_optimizer(kvstore=None, optimizer="sgd",
                           optimizer_params={"learning_rate": 0.5,
                                             "momentum": 0.9})
        return mod, next(iter(train))

    ctx8 = [mx.cpu(i) for i in range(8)]
    mod, batch = build(ctx8)
    mod.fit_step(batch)
    assert aot_cache.drain(60)
    # hazard quarantine: nothing on disk for a CPU mesh program
    assert not [p for p in tmp_path.iterdir()
                if p.suffix == ".aotx"], \
        "CPU mesh fused step must never be serialized to disk"

    # warm rebuild in-process: memo tier, zero foreground compiles
    memo0 = telemetry.report()["counters"].get("aot.memo_hits", 0)
    mod2, batch2 = build(ctx8)
    profiler.reset_step_stats()
    mod2.fit_step(batch2)
    mod2.fit_step(batch2)
    stats = profiler.step_stats()
    assert stats["compile_count"] == 0, "warm mesh rebuild compiled"
    assert stats["dispatch_count"] == 2
    assert telemetry.report()["counters"]["aot.memo_hits"] == memo0 + 1

    # same devices, different mesh shape: MUST be a different key —
    # dp=4 compiles its own program instead of hitting dp=8's memo
    mod4, batch4 = build([mx.cpu(i) for i in range(4)])
    profiler.reset_step_stats()
    mod4.fit_step(batch4)
    assert profiler.step_stats()["compile_count"] == 1

    # ...and dp=8 still hits its own memo afterwards
    mod8b, batch8b = build(ctx8)
    profiler.reset_step_stats()
    mod8b.fit_step(batch8b)
    assert profiler.step_stats()["compile_count"] == 0


def test_partition_rules_thread_through_bind():
    """Executor._build_shardings resolves the bind's partition rules over
    the named arg/aux tree (match_partition_rules) — batch names get
    batch_spec, ruled params their spec, everything else replicated."""
    from mxnet_tpu.parallel.sharding import PartitionRule
    from jax.sharding import PartitionSpec as P
    X, Y = _problem()
    train = mx.io.NDArrayIter(X, Y, batch_size=64)
    sym = _mlp()
    ctx = [mx.cpu(i) for i in range(8)]
    from mxnet_tpu.parallel.mesh import dp_mesh_from_ctx
    mesh = dp_mesh_from_ctx(ctx)
    from mxnet_tpu.executor import Executor
    exe = sym.simple_bind(
        ctx[0], grad_req="write", mesh=mesh,
        batch_names=["data", "softmax_label"],
        partition_rules=[PartitionRule(r"fc\d_weight$", P("dp", None), 2)],
        data=(64, 16), softmax_label=(64,))
    assert exe.param_spec("fc1_weight") == P("dp", None)
    assert exe.param_spec("fc1_bias") == P()
    assert exe.param_spec("data") == P("dp", None)


def test_partition_rules_unknown_axis_falls_back():
    """The SCALING.md cookbook shares one rule set across mesh shapes:
    a tp rule on a dp-only Module bind must replicate (counted +
    warned), never KeyError at bind."""
    from mxnet_tpu.parallel.sharding import PartitionRule
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu import telemetry
    X, Y = _problem()
    train = mx.io.NDArrayIter(X, Y, batch_size=64)
    before = telemetry.report()["counters"].get("sharding.fallbacks", 0)
    mod = mx.mod.Module(
        _mlp(), context=[mx.cpu(i) for i in range(8)],
        partition_rules=[(r"fc\d_weight$", P("tp", None), 2)])
    mod.bind(data_shapes=train.provide_data,
             label_shapes=train.provide_label)
    assert mod._exec.param_spec("fc1_weight") == P()
    assert telemetry.report()["counters"]["sharding.fallbacks"] > before
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(kvstore=None, optimizer="sgd")
    mod.fit_step(next(iter(train)))  # trains, just unsharded


def test_spmd_with_gradient_compression():
    """SPMD Module + 2-bit gradient compression (the --gpus + --gc-type
    combination fit.py now wires): the quantized update rule applies on
    the mesh-replicated merged gradients and training still learns."""
    X, Y = _problem()
    ctx = [mx.cpu(i) for i in range(4)]
    np.random.seed(42)
    mx.random.seed(42)
    train = mx.io.NDArrayIter(X, Y, batch_size=64)
    mod = mx.mod.Module(_mlp(), context=ctx)
    kv = mx.kv.create("device")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.2})
    mod.fit(train, optimizer="sgd", kvstore=kv,
            optimizer_params={"learning_rate": 0.5},
            initializer=mx.init.Xavier(rnd_type="gaussian",
                                       factor_type="in", magnitude=2),
            num_epoch=20)
    score = mod.score(mx.io.NDArrayIter(X, Y, batch_size=64),
                      mx.metric.Accuracy())
    acc = dict(score)["accuracy"]
    assert acc > 0.5, acc  # 4 classes; compressed training must learn
    # the compressor really ran: residuals exist only after quantization
    assert kv._compressor is not None and kv._compressor._residuals
