"""Train the GPT flagship as a character-level language model.

The 2017 reference's language-model example was a bucketing LSTM on PTB
(/root/reference/example/rnn/lstm_bucketing.py); the TPU-native flagship
is the decoder transformer (gluon/model_zoo/gpt.py) trained by the
standard Gluon loop.  Zero-egress environment: the corpus is generated
text with learnable structure (so convergence is meaningful and
checkable) instead of a download.

Usage:
    python train_gpt.py                   # tiny config, CPU-friendly
    python train_gpt.py --config small --seq-len 2048   # the MFU config
    python train_gpt.py --dp 2 --tp 2    # SPMD mesh (Megatron dp x tp)
    python train_gpt.py --dp 2 --sp 2    # long context: ring attention
    python train_gpt.py --pp 2 --dp 2    # 1F1B pipeline (+ --tp for 3-D)
    python train_gpt.py --moe-experts 4 --ep 2 --dp 2   # MoE over ep
"""
import argparse
import logging
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon


def make_corpus(n_chars=20000, seed=0):
    """Deterministic pseudo-English: sampled sentences over a small
    vocabulary with strong bigram structure a causal LM can learn."""
    rng = np.random.RandomState(seed)
    words = ["the", "tpu", "runs", "fast", "mesh", "shards", "compile",
             "kernel", "tensor", "flows", "ring", "attends"]
    text = []
    while sum(len(w) + 1 for w in text) < n_chars:
        k = rng.randint(3, 8)
        text.extend(words[i] for i in rng.randint(0, len(words), k))
        text.append(".")
    raw = " ".join(text)[:n_chars]
    chars = sorted(set(raw))
    stoi = {c: i for i, c in enumerate(chars)}
    return np.array([stoi[c] for c in raw], np.int32), chars


def batches(tokens, seq_len, batch_size, rng):
    n = (len(tokens) - 1) // seq_len
    starts = rng.permutation(n)[: (n // batch_size) * batch_size]
    for i in range(0, len(starts), batch_size):
        idx = starts[i:i + batch_size] * seq_len
        x = np.stack([tokens[j:j + seq_len] for j in idx])
        y = np.stack([tokens[j + 1:j + seq_len + 1] for j in idx])
        yield x, y


def sample(net, stoi_chars, prompt_ids, n_new, max_len, temperature=0.8,
           seed=0):
    """KV-cache generation (gpt.generate): one jitted scan, O(T) per new
    token.  Out-of-vocab MXU-padding tokens (possible at high
    temperature early in training) render as '?'."""
    from mxnet_tpu.gluon.model_zoo import gpt as gpt_mod
    prompt = np.asarray(prompt_ids, np.int32)[None]
    # fit the request into the model window, prompt first: keep the
    # whole (recent) prompt, then generate as many tokens as still fit
    keep = min(prompt.shape[1], max_len - 1)
    prompt = prompt[:, -keep:]
    n_new = min(n_new, max_len - keep)
    out = gpt_mod.generate(net, prompt, n_new, temperature=temperature,
                           seed=seed)[0]
    return "".join(stoi_chars[i] if i < len(stoi_chars) else "?"
                   for i in out)


def _finish(net, chars, tokens, losses, seq_len):
    """Shared reporting epilogue — the tests grep the final-loss line."""
    final_loss = float(np.mean(losses[-20:]))
    text = sample(net, chars, tokens[:16], 80, seq_len)
    print("final-loss=%.3f" % final_loss)
    print("sample: %r" % text)
    return final_loss


def train_mesh(args, net, tokens, chars):
    """SPMD training over a dp x tp x sp mesh, or a 1F1B pipeline when
    --pp > 1 — the same recipes the parallel/ tests pin, driven from a
    user-facing script.  SGD(+momentum) rather than the single-device
    path's adam: the point here is the parallelism recipe."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from mxnet_tpu import parallel as par
    from mxnet_tpu.parallel import gpt_spmd
    from mxnet_tpu.gluon.block import functionalize

    rng = np.random.RandomState(1)
    if args.pp > 1:
        if args.sp > 1:
            raise SystemExit("--sp does not compose with --pp here: the "
                             "pipeline path shards pp/dp/tp (use ring "
                             "attention inside stages via the library "
                             "API if you need both)")
        return _train_pp(args, net, tokens, chars, rng)

    mesh = par.make_mesh(dp=args.dp, tp=args.tp, sp=args.sp,
                         ep=args.ep)
    dp_n = dict(mesh.shape).get("dp", 1)
    if args.sp > 1:
        net.sequence_parallel(
            mesh, batch_axis="dp" if dp_n > 1 else None)
    if args.ep > 1:
        if not args.moe_experts:
            raise SystemExit("--ep needs --moe-experts")
        net.expert_parallel(mesh,
                            batch_axis="dp" if dp_n > 1 else None)
    xb0, yb0 = next(batches(tokens, args.seq_len, args.batch_size, rng))
    fn, params = functionalize(net, jnp.asarray(xb0), train=True)
    init_fn, step_fn = gpt_spmd.make_train_step(fn, mesh, lr=args.lr)
    data_spec = P("dp" if dp_n > 1 else None,
                  "sp" if args.sp > 1 else None)

    def place(a):
        return jax.device_put(jnp.asarray(a), NamedSharding(mesh,
                                                            data_spec))

    step = 0
    with mesh:
        ps, opt = init_fn(params)
        for epoch in range(args.epochs):
            t0 = time.time()
            losses = []
            for xb, yb in batches(tokens, args.seq_len, args.batch_size,
                                  rng):
                batch = {"x": place(xb), "y": place(yb.astype(np.int32))}
                ps, opt, loss = step_fn(ps, opt, batch,
                                        jax.random.PRNGKey(step))
                losses.append(float(loss))
                step += 1
            tok_s = len(losses) * args.batch_size * args.seq_len \
                / max(time.time() - t0, 1e-9)
            logging.info("Epoch[%d] loss=%.3f (%d steps, %.0f tok/s, "
                         "mesh %s)", epoch, float(np.mean(losses[-20:])),
                         step, tok_s, dict(mesh.shape))
    # trained weights back into the net so sampling uses them
    by_name = net.collect_params()
    for name, val in ps.items():
        by_name[name].set_data(np.asarray(val))
    net.sequence_parallel(None)
    if args.moe_experts:
        net.expert_parallel(None)
    return _finish(net, chars, tokens, losses, args.seq_len)


def _train_pp(args, net, tokens, chars, rng):
    import jax
    import jax.numpy as jnp
    from mxnet_tpu import parallel as par
    from mxnet_tpu.parallel import gpt_pp

    mesh = par.make_mesh(pp=args.pp, dp=args.dp, tp=args.tp)
    n_micro = 2 * args.pp
    if args.batch_size % (n_micro * max(args.dp, 1)):
        raise SystemExit("--batch-size must divide into %d microbatches "
                         "x dp=%d" % (n_micro, args.dp))
    mb = args.batch_size // n_micro
    stage_params, stage_fns, wire, names = gpt_pp.make_gpt_stages(
        net, args.pp, mb // args.dp, args.seq_len)
    inner = (gpt_pp.gpt_stage_tp_specs(stage_params, names)
             if args.tp > 1 else None)
    shardings = par.stage_param_shardings(stage_params, mesh)
    stage_params = jax.tree_util.tree_map(jax.device_put, stage_params,
                                          shardings)

    def ce(logits, y):
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        return -jnp.take_along_axis(lp, y[..., None], -1).sum()

    denom = args.batch_size * args.seq_len
    lr = args.lr / denom          # summed loss -> per-token step size
    step = 0
    for epoch in range(args.epochs):
        t0 = time.time()
        losses = []
        for xb, yb in batches(tokens, args.seq_len, args.batch_size,
                              rng):
            toks = jnp.asarray(xb.reshape(n_micro, mb, args.seq_len))
            tgts = jnp.asarray(
                yb.astype(np.int32).reshape(n_micro, mb, args.seq_len))
            loss, grads = par.pipeline_apply_1f1b_het(
                stage_params, toks, tgts, stage_fns, ce, wire,
                mesh=mesh, batch_axis="dp" if args.dp > 1 else None,
                param_inner_specs=inner)
            g_wte = gpt_pp.tie_wte_grad(grads)
            old_e = stage_params["embed"]["wte"][0]
            old_h = stage_params["head"]["wte"][-1]
            stage_params = jax.tree_util.tree_map(
                lambda p, g: p - lr * g, stage_params, grads)
            # tied embedding: both slots take the summed-grad update
            stage_params["embed"]["wte"] = \
                stage_params["embed"]["wte"].at[0].set(old_e - lr * g_wte)
            stage_params["head"]["wte"] = \
                stage_params["head"]["wte"].at[-1].set(old_h - lr * g_wte)
            losses.append(float(loss) / denom)
            step += 1
        tok_s = len(losses) * denom / max(time.time() - t0, 1e-9)
        logging.info("Epoch[%d] loss=%.3f (%d steps, %.0f tok/s, "
                     "pp=%d dp=%d tp=%d)", epoch,
                     float(np.mean(losses[-20:])), step, tok_s, args.pp,
                     args.dp, args.tp)
    gpt_pp.write_back(net, stage_params, names)
    return _finish(net, chars, tokens, losses, args.seq_len)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--config", default="tiny",
                   choices=["tiny", "small", "medium"])
    p.add_argument("--seq-len", type=int, default=64)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--corpus-chars", type=int, default=20000)
    p.add_argument("--dp", type=int, default=1,
                   help="data-parallel mesh axis")
    p.add_argument("--tp", type=int, default=1,
                   help="tensor-parallel (Megatron) mesh axis")
    p.add_argument("--sp", type=int, default=1,
                   help="sequence-parallel axis: ring attention")
    p.add_argument("--pp", type=int, default=1,
                   help="pipeline stages (1F1B; layers %% pp == 0)")
    p.add_argument("--ep", type=int, default=1,
                   help="expert-parallel axis (needs --moe-experts)")
    p.add_argument("--moe-experts", type=int, default=0,
                   help="experts per block (0 = dense MLP)")
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    tokens, chars = make_corpus(args.corpus_chars)
    vocab = len(chars)
    logging.info("corpus: %d chars, vocab %d", len(tokens), vocab)

    from mxnet_tpu.gluon.model_zoo import gpt
    factory = {"tiny": gpt.gpt2_tiny, "small": gpt.gpt2_small,
               "medium": gpt.gpt2_medium}[args.config]
    net = factory(vocab_size=vocab, max_len=args.seq_len,
                  moe_experts=args.moe_experts)
    net.initialize(mx.init.Xavier())

    if args.dp * args.tp * args.sp * args.pp * args.ep > 1:
        return train_mesh(args, net, tokens, chars)
    if args.moe_experts:
        # MoE blocks train through functionalize (the imperative tape
        # cannot record the expert dispatch) — reuse the mesh path,
        # data-parallel over every visible device
        args.dp = -1
        return train_mesh(args, net, tokens, chars)

    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss(axis=-1,
                                                 sparse_label=True)

    rng = np.random.RandomState(1)
    step = 0
    for epoch in range(args.epochs):
        t0 = time.time()
        losses = []
        for xb, yb in batches(tokens, args.seq_len, args.batch_size, rng):
            x = mx.nd.array(xb, dtype="int32")
            y = mx.nd.array(yb.astype(np.float32))
            with autograd.record():
                loss = loss_fn(net(x), y).mean()
            loss.backward()
            trainer.step(xb.shape[0])
            losses.append(float(loss.asnumpy()))
            step += 1
        tok_s = len(losses) * args.batch_size * args.seq_len \
            / max(time.time() - t0, 1e-9)
        logging.info("Epoch[%d] loss=%.3f (%d steps, %.0f tok/s)",
                     epoch, float(np.mean(losses[-20:])), step, tok_s)

    return _finish(net, chars, tokens, losses, args.seq_len)


if __name__ == "__main__":
    main()
