"""Train the GPT flagship as a character-level language model.

The 2017 reference's language-model example was a bucketing LSTM on PTB
(/root/reference/example/rnn/lstm_bucketing.py); the TPU-native flagship
is the decoder transformer (gluon/model_zoo/gpt.py) trained by the
standard Gluon loop.  Zero-egress environment: the corpus is generated
text with learnable structure (so convergence is meaningful and
checkable) instead of a download.

Usage:
    python train_gpt.py                   # tiny config, CPU-friendly
    python train_gpt.py --config small --seq-len 2048   # the MFU config
"""
import argparse
import logging
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon


def make_corpus(n_chars=20000, seed=0):
    """Deterministic pseudo-English: sampled sentences over a small
    vocabulary with strong bigram structure a causal LM can learn."""
    rng = np.random.RandomState(seed)
    words = ["the", "tpu", "runs", "fast", "mesh", "shards", "compile",
             "kernel", "tensor", "flows", "ring", "attends"]
    text = []
    while sum(len(w) + 1 for w in text) < n_chars:
        k = rng.randint(3, 8)
        text.extend(words[i] for i in rng.randint(0, len(words), k))
        text.append(".")
    raw = " ".join(text)[:n_chars]
    chars = sorted(set(raw))
    stoi = {c: i for i, c in enumerate(chars)}
    return np.array([stoi[c] for c in raw], np.int32), chars


def batches(tokens, seq_len, batch_size, rng):
    n = (len(tokens) - 1) // seq_len
    starts = rng.permutation(n)[: (n // batch_size) * batch_size]
    for i in range(0, len(starts), batch_size):
        idx = starts[i:i + batch_size] * seq_len
        x = np.stack([tokens[j:j + seq_len] for j in idx])
        y = np.stack([tokens[j + 1:j + seq_len + 1] for j in idx])
        yield x, y


def sample(net, stoi_chars, prompt_ids, n_new, max_len, temperature=0.8,
           seed=0):
    """KV-cache generation (gpt.generate): one jitted scan, O(T) per new
    token.  Out-of-vocab MXU-padding tokens (possible at high
    temperature early in training) render as '?'."""
    from mxnet_tpu.gluon.model_zoo import gpt as gpt_mod
    prompt = np.asarray(prompt_ids, np.int32)[None]
    # fit the request into the model window, prompt first: keep the
    # whole (recent) prompt, then generate as many tokens as still fit
    keep = min(prompt.shape[1], max_len - 1)
    prompt = prompt[:, -keep:]
    n_new = min(n_new, max_len - keep)
    out = gpt_mod.generate(net, prompt, n_new, temperature=temperature,
                           seed=seed)[0]
    return "".join(stoi_chars[i] if i < len(stoi_chars) else "?"
                   for i in out)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--config", default="tiny",
                   choices=["tiny", "small", "medium"])
    p.add_argument("--seq-len", type=int, default=64)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--corpus-chars", type=int, default=20000)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    tokens, chars = make_corpus(args.corpus_chars)
    vocab = len(chars)
    logging.info("corpus: %d chars, vocab %d", len(tokens), vocab)

    from mxnet_tpu.gluon.model_zoo import gpt
    factory = {"tiny": gpt.gpt2_tiny, "small": gpt.gpt2_small,
               "medium": gpt.gpt2_medium}[args.config]
    net = factory(vocab_size=vocab, max_len=args.seq_len)
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss(axis=-1,
                                                 sparse_label=True)

    rng = np.random.RandomState(1)
    step = 0
    for epoch in range(args.epochs):
        t0 = time.time()
        losses = []
        for xb, yb in batches(tokens, args.seq_len, args.batch_size, rng):
            x = mx.nd.array(xb, dtype="int32")
            y = mx.nd.array(yb.astype(np.float32))
            with autograd.record():
                loss = loss_fn(net(x), y).mean()
            loss.backward()
            trainer.step(xb.shape[0])
            losses.append(float(loss.asnumpy()))
            step += 1
        tok_s = len(losses) * args.batch_size * args.seq_len \
            / max(time.time() - t0, 1e-9)
        logging.info("Epoch[%d] loss=%.3f (%d steps, %.0f tok/s)",
                     epoch, float(np.mean(losses[-20:])), step, tok_s)

    final_loss = float(np.mean(losses[-20:]))
    text = sample(net, chars, tokens[:16], 80, args.seq_len)
    print("final-loss=%.3f" % final_loss)
    print("sample: %r" % text)
    return final_loss


if __name__ == "__main__":
    main()
