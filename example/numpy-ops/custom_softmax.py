"""Custom numpy operator (reference example/numpy-ops/custom_softmax.py
shape): a softmax output head written as a mx.operator.CustomOp — python
forward/backward over numpy running inside the compiled graph via host
callback — trained on a synthetic problem through the Module API.

Usage: python custom_softmax.py --steps 60
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx


class NumpySoftmax(mx.operator.CustomOp):
    # callbacks run on the HOST inside the compiled program: everything
    # here is numpy (in_data/out_data are host views, .asnumpy() is free)
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        e = np.exp(x - x.max(axis=1, keepdims=True))
        self.assign(out_data[0], req[0], e / e.sum(axis=1, keepdims=True))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        # SoftmaxOutput semantics: gradient is (p - onehot(label))
        p = out_data[0].asnumpy().copy()
        y = in_data[1].asnumpy().astype(int)
        p[np.arange(y.shape[0]), y] -= 1.0
        self.assign(in_grad[0], req[0], p / y.shape[0])


@mx.operator.register("numpy_softmax")
class NumpySoftmaxProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["data", "label"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        return [in_shape[0], (in_shape[0][0],)], [in_shape[0]], []

    def create_operator(self, ctx, shapes, dtypes):
        return NumpySoftmax()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--batch-size", type=int, default=32)
    args = ap.parse_args()

    rng = np.random.RandomState(0)
    W = rng.randn(8, 3).astype(np.float32)
    X = rng.randn(args.batch_size * 4, 8).astype(np.float32)
    Y = (X @ W).argmax(axis=1).astype(np.float32)

    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    fc = mx.sym.FullyConnected(data, name="fc", num_hidden=3)
    net = mx.sym.Custom(fc, label, op_type="numpy_softmax", name="softmax")

    train_iter = mx.io.NDArrayIter(X, Y, args.batch_size, shuffle=True,
                                   label_name="softmax_label")
    mod = mx.mod.Module(net, data_names=["data"],
                        label_names=["softmax_label"])
    mod.fit(train_iter, num_epoch=max(1, args.steps // 4),
            optimizer="sgd", optimizer_params={"learning_rate": 0.5},
            eval_metric="acc",
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 2))
    score = mod.score(train_iter, mx.metric.Accuracy())
    acc = dict(score)["accuracy"]
    print("final train accuracy %.3f" % acc)
    assert acc > 0.8, acc
    print("custom numpy softmax done")


if __name__ == "__main__":
    main()
