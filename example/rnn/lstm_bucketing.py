#!/usr/bin/env python
"""PTB-style LSTM language model with BucketingModule — BASELINE
config #3.

Port of /root/reference/example/rnn/lstm_bucketing.py: FusedRNNCell (the
lax.scan fused RNN) unrolled per bucket; each bucket length is one
static-shape XLA program in the jit cache.  Without --data-train it
generates a synthetic corpus with learnable bigram structure.
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(os.path.expanduser(__file__))), "..", ".."))
import mxnet_tpu as mx  # noqa: E402

parser = argparse.ArgumentParser(
    description="Train an LSTM language model with bucketing",
    formatter_class=argparse.ArgumentDefaultsHelpFormatter)
parser.add_argument("--data-train", type=str, default=None,
                    help="tokenized text file (one sentence per line); "
                    "synthetic corpus when absent")
parser.add_argument("--num-hidden", type=int, default=200)
parser.add_argument("--num-embed", type=int, default=200)
parser.add_argument("--num-layers", type=int, default=2)
parser.add_argument("--num-epochs", type=int, default=25)
parser.add_argument("--lr", type=float, default=0.01)
parser.add_argument("--optimizer", type=str, default="adam")
parser.add_argument("--mom", type=float, default=0.0)
parser.add_argument("--wd", type=float, default=1e-5)
parser.add_argument("--batch-size", type=int, default=32)
parser.add_argument("--disp-batches", type=int, default=50)
parser.add_argument("--kv-store", type=str, default="device")
parser.add_argument("--buckets", type=str, default="10,20,30,40")


def synthetic_corpus(n_sent=2000, vocab=200, seed=0):
    """Markov-chain sentences: token t+1 = (2*t + noise) mod vocab."""
    rng = np.random.RandomState(seed)
    sents = []
    for _ in range(n_sent):
        L = rng.randint(5, 40)
        s = [rng.randint(1, vocab)]
        for _ in range(L - 1):
            s.append((2 * s[-1] + rng.randint(0, 3)) % (vocab - 1) + 1)
        sents.append(s)
    return sents, vocab


def tokenize_text(fname, vocab=None, invalid_label=-1, start_label=0):
    with open(fname) as f:
        lines = [line.split() for line in f]
    return mx.rnn.encode_sentences(lines, vocab=vocab,
                                   invalid_label=invalid_label,
                                   start_label=start_label)


if __name__ == "__main__":
    import logging
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)-15s %(message)s")
    args = parser.parse_args()
    buckets = [int(b) for b in args.buckets.split(",")]
    invalid_label = 0
    if args.data_train and os.path.exists(args.data_train):
        sentences, vocab = tokenize_text(args.data_train, start_label=1)
        vocab_size = len(vocab) + 1
    else:
        sentences, vocab_size = synthetic_corpus()

    data_train = mx.rnn.BucketSentenceIter(
        sentences, args.batch_size, buckets=buckets,
        invalid_label=invalid_label)

    cell = mx.rnn.FusedRNNCell(args.num_hidden,
                               num_layers=args.num_layers, mode="lstm",
                               prefix="lstm_")

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data=data, input_dim=vocab_size,
                                 output_dim=args.num_embed, name="embed")
        cell.reset()
        outputs, states = cell.unroll(seq_len, inputs=embed,
                                      merge_outputs=True, layout="NTC")
        pred = mx.sym.Reshape(outputs, shape=(-1, args.num_hidden))
        pred = mx.sym.FullyConnected(data=pred, num_hidden=vocab_size,
                                     name="pred")
        label_r = mx.sym.Reshape(label, shape=(-1,))
        pred = mx.sym.SoftmaxOutput(data=pred, label=label_r,
                                    name="softmax")
        return pred, ("data",), ("softmax_label",)

    model = mx.mod.BucketingModule(
        sym_gen=sym_gen,
        default_bucket_key=data_train.default_bucket_key,
        context=mx.tpu() if mx.num_gpus() > 0 else mx.cpu())

    model.fit(
        train_data=data_train,
        eval_metric=mx.metric.Perplexity(invalid_label),
        kvstore=args.kv_store,
        optimizer=args.optimizer,
        optimizer_params={"learning_rate": args.lr, "wd": args.wd},
        initializer=mx.init.Xavier(factor_type="in", magnitude=2.34),
        num_epoch=args.num_epochs,
        batch_end_callback=mx.callback.Speedometer(args.batch_size,
                                                   args.disp_batches))

    score = model.score(data_train,
                        mx.metric.Perplexity(invalid_label))
    print("final train perplexity: %.3f" % dict(score)["perplexity"])
