"""Matrix factorization recommender (reference example/recommenders/
demo shape): user/item embeddings -> dot product -> rating regression,
trained with Module.fit on synthetic low-rank ratings.

Usage: python matrix_fact.py --num-epochs 8
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx


def build_symbol(num_users, num_items, factor):
    user = mx.sym.Variable("user")
    item = mx.sym.Variable("item")
    score = mx.sym.Variable("score_label")
    u = mx.sym.Embedding(user, input_dim=num_users, output_dim=factor,
                         name="user_embed")
    i = mx.sym.Embedding(item, input_dim=num_items, output_dim=factor,
                         name="item_embed")
    pred = mx.sym.sum(u * i, axis=1)
    return mx.sym.LinearRegressionOutput(pred, score, name="lro")


def synthetic_ratings(num_users, num_items, factor, n, rng):
    """Low-rank ground truth + noise."""
    U = rng.randn(num_users, factor).astype(np.float32) * 0.7
    V = rng.randn(num_items, factor).astype(np.float32) * 0.7
    users = rng.randint(0, num_users, n)
    items = rng.randint(0, num_items, n)
    scores = (U[users] * V[items]).sum(1) + 0.05 * rng.randn(n)
    return (users.astype(np.float32), items.astype(np.float32),
            scores.astype(np.float32))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--num-users", type=int, default=200)
    ap.add_argument("--num-items", type=int, default=150)
    ap.add_argument("--factor", type=int, default=8)
    ap.add_argument("--lr", type=float, default=0.1)
    args = ap.parse_args()

    np.random.seed(0)       # NDArrayIter shuffle draws from the global rng
    mx.random.seed(0)
    rng = np.random.RandomState(0)
    users, items, scores = synthetic_ratings(
        args.num_users, args.num_items, args.factor, 6000, rng)

    train = mx.io.NDArrayIter(
        {"user": users[:5000], "item": items[:5000]},
        {"score_label": scores[:5000]}, args.batch_size, shuffle=True)
    val = mx.io.NDArrayIter(
        {"user": users[5000:], "item": items[5000:]},
        {"score_label": scores[5000:]}, args.batch_size)

    sym = build_symbol(args.num_users, args.num_items, args.factor)
    mod = mx.mod.Module(sym, data_names=["user", "item"],
                        label_names=["score_label"])
    mod.fit(train, eval_data=val, num_epoch=args.num_epochs,
            optimizer="adam", optimizer_params={"learning_rate": args.lr},
            initializer=mx.init.Normal(0.1), eval_metric="rmse",
            batch_end_callback=mx.callback.Speedometer(args.batch_size,
                                                       40))
    rmse = dict(mod.score(val, mx.metric.RMSE()))["rmse"]
    print("validation rmse %.4f" % rmse)
    # rank-8 truth with 0.05 noise: scores have std ~1.4, an unfit
    # model sits there; adam at lr 0.1 is what actually generalizes in
    # 10 epochs on this synthetic set (seeded run lands at ~0.62 —
    # lr 0.05 stalls at ~1.04, lr 0.02 at ~1.08)
    assert rmse < 0.75, rmse
    print("matrix factorization done")


if __name__ == "__main__":
    main()
