"""DCGAN on synthetic image data (reference example/gan/dcgan.py shape).

Two Gluon networks trained adversarially — exercises alternating
generator/discriminator updates, transposed convolutions, BatchNorm in
both train and inference modes, and custom per-network Trainers.

Usage: python dcgan.py --steps 30 --batch-size 8 --image-size 32
"""
import argparse
import sys
import os

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon import nn, Trainer


def build_generator(ngf, nc):
    net = nn.Sequential(prefix="gen_")
    with net.name_scope():
        # z (B, nz, 1, 1) -> (B, nc, 32, 32)
        net.add(nn.Conv2DTranspose(ngf * 4, 4, 1, 0, use_bias=False))
        net.add(nn.BatchNorm())
        net.add(nn.Activation("relu"))
        net.add(nn.Conv2DTranspose(ngf * 2, 4, 2, 1, use_bias=False))
        net.add(nn.BatchNorm())
        net.add(nn.Activation("relu"))
        net.add(nn.Conv2DTranspose(ngf, 4, 2, 1, use_bias=False))
        net.add(nn.BatchNorm())
        net.add(nn.Activation("relu"))
        net.add(nn.Conv2DTranspose(nc, 4, 2, 1, use_bias=False))
        net.add(nn.Activation("tanh"))
    return net


def build_discriminator(ndf):
    net = nn.Sequential(prefix="disc_")
    with net.name_scope():
        net.add(nn.Conv2D(ndf, 4, 2, 1, use_bias=False))
        net.add(nn.LeakyReLU(0.2))
        net.add(nn.Conv2D(ndf * 2, 4, 2, 1, use_bias=False))
        net.add(nn.BatchNorm())
        net.add(nn.LeakyReLU(0.2))
        net.add(nn.Conv2D(ndf * 4, 4, 2, 1, use_bias=False))
        net.add(nn.BatchNorm())
        net.add(nn.LeakyReLU(0.2))
        net.add(nn.Conv2D(1, 4, 1, 0, use_bias=False))
        net.add(nn.Flatten())
    return net


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--image-size", type=int, default=32)
    ap.add_argument("--nz", type=int, default=16)
    ap.add_argument("--ngf", type=int, default=16)
    ap.add_argument("--ndf", type=int, default=16)
    ap.add_argument("--lr", type=float, default=2e-4)
    args = ap.parse_args()

    assert args.image_size == 32, "this config generates 32x32"
    mx.random.seed(0)
    rng = np.random.RandomState(0)
    nc = 1

    gen = build_generator(args.ngf, nc)
    disc = build_discriminator(args.ndf)
    gen.collect_params().initialize(
        mx.init.Normal(0.02), ctx=mx.current_context())
    disc.collect_params().initialize(
        mx.init.Normal(0.02), ctx=mx.current_context())
    trainer_g = Trainer(gen.collect_params(), "adam",
                        {"learning_rate": args.lr, "beta1": 0.5})
    trainer_d = Trainer(disc.collect_params(), "adam",
                        {"learning_rate": args.lr, "beta1": 0.5})
    sce = mx.gluon.loss.SigmoidBinaryCrossEntropyLoss()

    # "real" data: blobs with structure (centered gaussians)
    def real_batch():
        yy, xx = np.mgrid[0:32, 0:32].astype(np.float32)
        c = rng.uniform(8, 24, (args.batch_size, 2)).astype(np.float32)
        img = np.exp(-(((xx - c[:, :1, None]) ** 2 +
                        (yy - c[:, 1:, None]) ** 2) / 40.0))
        return nd.array(img[:, None] * 2 - 1)

    real_label = nd.ones((args.batch_size,))
    fake_label = nd.zeros((args.batch_size,))
    dl_hist, gl_hist = [], []
    for step in range(args.steps):
        z = nd.array(rng.randn(args.batch_size, args.nz, 1, 1)
                     .astype(np.float32))
        data = real_batch()
        # -- discriminator: real up, fake down
        with mx.autograd.record():
            out_real = disc(data)
            loss_real = sce(out_real, real_label)
            fake = gen(z)
            out_fake = disc(fake.detach())
            loss_fake = sce(out_fake, fake_label)
            loss_d = loss_real + loss_fake
        loss_d.backward()
        trainer_d.step(args.batch_size)
        # -- generator: make fakes read as real
        with mx.autograd.record():
            fake = gen(z)
            out = disc(fake)
            loss_g = sce(out, real_label)
        loss_g.backward()
        trainer_g.step(args.batch_size)
        dl_hist.append(float(loss_d.mean().asnumpy()))
        gl_hist.append(float(loss_g.mean().asnumpy()))
        if step % 10 == 0 or step == args.steps - 1:
            print("step %d  loss_d %.4f  loss_g %.4f"
                  % (step, dl_hist[-1], gl_hist[-1]))

    sample = gen(nd.array(rng.randn(2, args.nz, 1, 1).astype(np.float32)))
    print("generated sample shape", sample.shape)
    assert sample.shape == (2, nc, 32, 32)
    assert np.isfinite(dl_hist).all() and np.isfinite(gl_hist).all()
    print("dcgan done")


if __name__ == "__main__":
    main()
