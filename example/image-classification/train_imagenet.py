#!/usr/bin/env python
"""Train on ImageNet RecordIO — BASELINE config #2
(`--kv-store device` unmodified).

Port of /root/reference/example/image-classification/train_imagenet.py
(:58 is the entry the north-star call stack names).  `--benchmark 1`
feeds synthetic batches (throughput mode, no dataset needed).
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(os.path.expanduser(__file__))), "..", ".."))
from common import data, fit  # noqa: E402


def parse_args():
    parser = argparse.ArgumentParser(
        description="train imagenet-1k",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    fit.add_fit_args(parser)
    data.add_data_args(parser)
    data.add_data_aug_args(parser)
    parser.set_defaults(
        network="resnet", num_layers=50,
        image_shape="3,224,224", num_classes=1000,
        num_examples=1281167,
        num_epochs=80, lr_step_epochs="30,60",
        batch_size=128)
    return parser.parse_args()


if __name__ == "__main__":
    args = parse_args()
    from importlib import import_module
    net = import_module("symbols." + args.network).get_symbol(
        num_classes=args.num_classes, num_layers=args.num_layers,
        image_shape=args.image_shape)
    fit.fit(args, net, data.get_rec_iter)
