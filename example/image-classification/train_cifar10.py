#!/usr/bin/env python
"""Train on CIFAR-10 RecordIO (reference example/image-classification/
train_cifar10.py; the ≥0.93 top-1 CI gate lives on this script,
Jenkinsfile:476)."""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(os.path.expanduser(__file__))), "..", ".."))
from common import data, fit  # noqa: E402


def parse_args():
    parser = argparse.ArgumentParser(
        description="train cifar10",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    fit.add_fit_args(parser)
    data.add_data_args(parser)
    data.add_data_aug_args(parser)
    parser.set_defaults(
        network="resnet", num_layers=110,
        image_shape="3,28,28", pad_size=4,
        num_classes=10, num_examples=50000,
        num_epochs=300, lr=0.05, lr_step_epochs="200,250",
        batch_size=128)
    return parser.parse_args()


if __name__ == "__main__":
    args = parse_args()
    from importlib import import_module
    net = import_module("symbols." + args.network).get_symbol(
        num_classes=args.num_classes, num_layers=args.num_layers,
        image_shape=args.image_shape)
    fit.fit(args, net, data.get_rec_iter)
