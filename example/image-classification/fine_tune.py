"""Fine-tune a checkpointed model on a new task — the Caltech-256
workflow (/root/reference/example/image-classification/README.md:198-208
and the fine-tune recipe it links): load a reference-format checkpoint,
cut the head off at the last feature layer, attach a fresh
FullyConnected for the new label set, freeze everything below, and train
only the head.

Usage:
    python fine_tune.py --pretrained-prefix model --pretrained-epoch 5 \
        --num-classes 10 --layer-name flatten

Without --pretrained-prefix the script first trains a small conv net on
synthetic data, checkpoints it, then fine-tunes from its own checkpoint —
a self-contained demonstration (and what tests/test_finetune.py runs).
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np
import mxnet_tpu as mx


def build_base(num_classes=4):
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8,
                             pad=(1, 1), name="conv1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max", name="pool1")
    net = mx.sym.Flatten(net, name="flatten")
    net = mx.sym.FullyConnected(net, num_hidden=num_classes, name="fc_out")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def get_fine_tune_model(symbol, arg_params, num_classes,
                        layer_name="flatten"):
    """The reference recipe's surgery: keep everything up to
    ``layer_name``, attach a fresh head, drop head weights from the
    loaded params so the new ones initialize."""
    all_layers = symbol.get_internals()
    net = all_layers[layer_name + "_output"]
    net = mx.sym.FullyConnected(net, num_hidden=num_classes, name="fc_new")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    keep = set(net.list_arguments())
    new_args = {k: v for k, v in arg_params.items()
                if k in keep and not k.startswith("fc_new")}
    return net, new_args


def synthetic_problem(num_classes, n=256, edge=8, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, 3, edge, edge).astype(np.float32) - 0.5
    # label depends on channel means — learnable by a tiny conv net
    Y = (X.mean(axis=(2, 3)) @ rng.randn(3, num_classes)).argmax(1) \
        .astype(np.float32)
    return X, Y


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--pretrained-prefix", default=None)
    p.add_argument("--pretrained-epoch", type=int, default=1)
    p.add_argument("--num-classes", type=int, default=3)
    p.add_argument("--layer-name", default="flatten")
    p.add_argument("--epochs", type=int, default=10)
    p.add_argument("--lr", type=float, default=0.5)
    p.add_argument("--out-prefix", default="/tmp/mxtpu_finetune")
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    if args.pretrained_prefix is None:
        # self-contained: pretrain on task A, checkpoint in the
        # reference binary format
        Xa, Ya = synthetic_problem(4, seed=0)
        it = mx.io.NDArrayIter(Xa, Ya, batch_size=32)
        mod = mx.mod.Module(build_base(4))
        mod.fit(it, optimizer="sgd",
                optimizer_params={"learning_rate": 0.2}, num_epoch=3,
                initializer=mx.init.Xavier())
        mod.save_checkpoint(args.out_prefix, args.pretrained_epoch)
        args.pretrained_prefix = args.out_prefix

    sym, arg_params, aux_params = mx.model.load_checkpoint(
        args.pretrained_prefix, args.pretrained_epoch)
    net, new_args = get_fine_tune_model(sym, arg_params,
                                        args.num_classes, args.layer_name)

    # freeze every loaded layer: only the new head trains
    fixed = sorted(new_args)
    Xb, Yb = synthetic_problem(args.num_classes, seed=1)
    it = mx.io.NDArrayIter(Xb, Yb, batch_size=32)
    mod = mx.mod.Module(net, fixed_param_names=fixed)
    metric = mx.metric.Accuracy()
    mod.fit(it, optimizer="sgd",
            optimizer_params={"learning_rate": args.lr},
            arg_params=new_args, aux_params=aux_params,
            allow_missing=True, num_epoch=args.epochs,
            initializer=mx.init.Xavier(), eval_metric=metric)
    it.reset()
    score = mod.score(it, mx.metric.Accuracy())
    acc = dict(score)["accuracy"]
    print("fine-tune accuracy=%.3f (head-only training, %d frozen params)"
          % (acc, len(fixed)))
    return acc


if __name__ == "__main__":
    main()
