#!/usr/bin/env python
"""Train mlp/lenet on MNIST — BASELINE config #1, runs unmodified on
ctx=tpu.

Port of /root/reference/example/image-classification/train_mnist.py.
Reads idx-format MNIST from --data-dir when present; zero-egress
environments fall back to a deterministic synthetic digit set (drawn
digit strokes, still a real 10-class image problem).
"""
import argparse
import gzip
import logging
import os
import struct
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(os.path.expanduser(__file__))), "..", ".."))
import mxnet_tpu as mx  # noqa: E402
from common import fit  # noqa: E402


def read_data(label_path, image_path):
    with gzip.open(label_path) as flbl:
        struct.unpack(">II", flbl.read(8))
        label = np.frombuffer(flbl.read(), dtype=np.int8)
    with gzip.open(image_path) as fimg:
        _, num, rows, cols = struct.unpack(">IIII", fimg.read(16))
        image = np.frombuffer(fimg.read(), dtype=np.uint8)
        image = image.reshape(len(label), rows, cols)
    return (label, image)


def _synthetic_digits(n, seed=0):
    """Deterministic 10-class 'digit' images: class k = k bright bars at
    distinct row positions + noise.  Linearly separable enough for an
    MLP, conv-friendly for LeNet."""
    rng = np.random.RandomState(seed)
    y = rng.randint(0, 10, n)
    x = rng.uniform(0, 0.15, (n, 28, 28)).astype(np.float32)
    for i, cls in enumerate(y):
        rows = (np.arange(cls + 1) * 28) // 10
        for r in rows:
            x[i, r:r + 2, 4:24] += 0.8
    return y.astype(np.float32), np.clip(x, 0, 1)


def to4d(img):
    return img.reshape(img.shape[0], 1, 28, 28).astype(np.float32) / 255


def get_mnist_iter(args, kv):
    data_dir = args.data_dir
    files = ["train-labels-idx1-ubyte.gz", "train-images-idx3-ubyte.gz",
             "t10k-labels-idx1-ubyte.gz", "t10k-images-idx3-ubyte.gz"]
    if data_dir and all(os.path.exists(os.path.join(data_dir, f))
                        for f in files):
        (train_lbl, train_img) = read_data(
            os.path.join(data_dir, files[0]), os.path.join(data_dir,
                                                           files[1]))
        (val_lbl, val_img) = read_data(
            os.path.join(data_dir, files[2]), os.path.join(data_dir,
                                                           files[3]))
        train_img, val_img = to4d(train_img), to4d(val_img)
    else:
        logging.warning("MNIST files not found under %r; using the "
                        "synthetic digit set", data_dir)
        train_lbl, timg = _synthetic_digits(args.num_examples, seed=0)
        val_lbl, vimg = _synthetic_digits(args.num_examples // 6, seed=1)
        train_img = timg[:, None, :, :]
        val_img = vimg[:, None, :, :]
    train = mx.io.NDArrayIter(train_img, train_lbl, args.batch_size,
                              shuffle=True)
    val = mx.io.NDArrayIter(val_img, val_lbl, args.batch_size)
    return (train, val)


def parse_args():
    parser = argparse.ArgumentParser(
        description="train an image classifier on mnist",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("--num-classes", type=int, default=10)
    parser.add_argument("--num-examples", type=int, default=60000)
    parser.add_argument("--data-dir", type=str, default="mnist_data")
    parser.add_argument("--add_stn", action="store_true")
    fit.add_fit_args(parser)
    parser.set_defaults(network="mlp", num_epochs=10,
                        lr=0.05, lr_step_epochs="10", batch_size=64,
                        disp_batches=100)
    return parser.parse_args()


if __name__ == "__main__":
    args = parse_args()
    # the synthetic data is seeded but weight init was not: an unlucky
    # entropy-seeded Xavier draw occasionally misses the test suite's
    # 0.95 accuracy threshold on the 2-epoch run.  Seed both RNG planes
    # so the example is run-to-run deterministic.
    np.random.seed(0)
    mx.random.seed(0)
    from importlib import import_module
    net = import_module("symbols." + args.network).get_symbol(
        num_classes=args.num_classes, num_layers=args.num_layers or 2,
        image_shape="1,28,28", add_stn=args.add_stn)
    fit.fit(args, net, get_mnist_iter)
