"""Data iterators for the image-classification examples.

Port of /root/reference/example/image-classification/common/data.py:
ImageRecordIter pipelines from --data-train/--data-val .rec files, plus
the synthetic benchmark iterator (`SyntheticDataIter`) the reference used
for --benchmark runs.
"""
import numpy as np

import mxnet_tpu as mx


def add_data_args(parser):
    data = parser.add_argument_group("Data", "the input images")
    data.add_argument("--data-train", type=str, help="the training data")
    data.add_argument("--data-val", type=str, help="the validation data")
    data.add_argument("--rgb-mean", type=str, default="123.68,116.779,103.939",
                      help="a tuple of size 3 for the mean rgb")
    data.add_argument("--pad-size", type=int, default=0,
                      help="padding size before random crop")
    data.add_argument("--image-shape", type=str, default="3,224,224",
                      help="the image shape feed into the network")
    data.add_argument("--num-classes", type=int, default=1000,
                      help="the number of classes")
    data.add_argument("--num-examples", type=int, default=1281167,
                      help="the number of training examples")
    data.add_argument("--data-nthreads", type=int, default=4,
                      help="number of threads for data decoding")
    data.add_argument("--benchmark", type=int, default=0,
                      help="if 1, then feed the network with synthetic "
                      "data")
    return data


def add_data_aug_args(parser):
    aug = parser.add_argument_group(
        "Image augmentations", "implemented in the decode pipeline")
    aug.add_argument("--random-crop", type=int, default=1)
    aug.add_argument("--random-mirror", type=int, default=1)
    aug.add_argument("--max-random-h", type=int, default=0)
    aug.add_argument("--max-random-s", type=int, default=0)
    aug.add_argument("--max-random-l", type=int, default=0)
    aug.add_argument("--max-random-aspect-ratio", type=float, default=0)
    aug.add_argument("--max-random-rotate-angle", type=int, default=0)
    aug.add_argument("--max-random-shear-ratio", type=float, default=0)
    aug.add_argument("--max-random-scale", type=float, default=1)
    aug.add_argument("--min-random-scale", type=float, default=1)
    return aug


class SyntheticDataIter(mx.io.DataIter):
    """Deterministic random batches entirely on the host — the reference's
    benchmark-mode iterator; removes IO from throughput measurements."""

    def __init__(self, num_classes, data_shape, max_iter, dtype="float32"):
        super().__init__(data_shape[0])
        self.batch_size = data_shape[0]
        self.cur_iter = 0
        self.max_iter = max_iter
        self.dtype = dtype
        # seeded: the benchmark replays one fixed batch, and the test
        # suite asserts a memorization threshold on it — determinism
        # keeps that threshold meaningful across runs
        rng = np.random.RandomState(0)
        label = rng.randint(0, num_classes, [self.batch_size])
        data = rng.uniform(-1, 1, data_shape).astype(dtype)
        self.data = mx.nd.array(data)
        self.label = mx.nd.array(label.astype(np.float32))
        self.provide_data = [mx.io.DataDesc("data", data_shape, dtype)]
        self.provide_label = [mx.io.DataDesc("softmax_label",
                                             (self.batch_size,))]

    def next(self):
        self.cur_iter += 1
        if self.cur_iter > self.max_iter:
            raise StopIteration
        return mx.io.DataBatch(data=[self.data], label=[self.label],
                               pad=0, index=None,
                               provide_data=self.provide_data,
                               provide_label=self.provide_label)

    def reset(self):
        self.cur_iter = 0


def get_rec_iter(args, kv=None):
    """ImageRecordIter pair from --data-train/--data-val, or synthetic
    when --benchmark (reference common/data.py:get_rec_iter)."""
    image_shape = tuple(int(x) for x in args.image_shape.split(","))
    if args.benchmark or not args.data_train:
        data_shape = (args.batch_size,) + image_shape
        train = SyntheticDataIter(args.num_classes, data_shape,
                                  max(1, args.num_examples //
                                      args.batch_size))
        return (train, None)
    rgb_mean = [float(i) for i in args.rgb_mean.split(",")]
    rank, nworker = (kv.rank, kv.num_workers) if kv else (0, 1)
    train = mx.io.ImageRecordIter(
        path_imgrec=args.data_train,
        data_shape=image_shape,
        batch_size=args.batch_size,
        preprocess_threads=args.data_nthreads,
        shuffle=True,
        rand_crop=bool(args.random_crop),
        rand_mirror=bool(args.random_mirror),
        mean_r=rgb_mean[0], mean_g=rgb_mean[1], mean_b=rgb_mean[2])
    if not args.data_val:
        return (train, None)
    val = mx.io.ImageRecordIter(
        path_imgrec=args.data_val,
        data_shape=image_shape,
        batch_size=args.batch_size,
        preprocess_threads=args.data_nthreads,
        shuffle=False,
        rand_crop=False, rand_mirror=False,
        mean_r=rgb_mean[0], mean_g=rgb_mean[1], mean_b=rgb_mean[2])
    return (train, val)
