"""Shared training harness for the image-classification examples.

Port of /root/reference/example/image-classification/common/fit.py
(:214 is the fit() entry the north-star call stack names): builds the
kvstore, lr schedule, Module, and drives BaseModule.fit with
checkpointing + Speedometer.  `--kv-store device|dist_sync` works
unmodified (BASELINE config #2): 'device' merges gradients in-process,
'dist_*' all-reduces over the jax.distributed process mesh.
"""
import logging
import os
import time

import mxnet_tpu as mx


def _get_lr_scheduler(args, kv):
    if "lr_factor" not in args or args.lr_factor >= 1:
        return (args.lr, None)
    epoch_size = args.num_examples // args.batch_size
    if "dist" in args.kv_store:
        epoch_size //= kv.num_workers
    begin_epoch = args.load_epoch if args.load_epoch else 0
    step_epochs = [int(l) for l in args.lr_step_epochs.split(",")]
    lr = args.lr
    for s in step_epochs:
        if begin_epoch >= s:
            lr *= args.lr_factor
    if lr != args.lr:
        logging.info("Adjust learning rate to %e for epoch %d",
                     lr, begin_epoch)
    steps = [epoch_size * (x - begin_epoch) for x in step_epochs
             if x - begin_epoch > 0]
    if not steps:
        return (lr, None)
    return (lr, mx.lr_scheduler.MultiFactorScheduler(
        step=steps, factor=args.lr_factor))


def _load_model(args, rank=0):
    if "load_epoch" not in args or args.load_epoch is None:
        return (None, None, None)
    assert args.model_prefix is not None
    model_prefix = args.model_prefix
    if rank > 0 and os.path.exists("%s-%d-symbol.json"
                                   % (model_prefix, rank)):
        model_prefix += "-%d" % rank
    sym, arg_params, aux_params = mx.model.load_checkpoint(
        model_prefix, args.load_epoch)
    logging.info("Loaded model %s_%04d.params", model_prefix,
                 args.load_epoch)
    return (sym, arg_params, aux_params)


def _save_model(args, rank=0):
    if args.model_prefix is None:
        return None
    dst_dir = os.path.dirname(args.model_prefix)
    if dst_dir and not os.path.isdir(dst_dir):
        os.makedirs(dst_dir, exist_ok=True)
    return mx.callback.do_checkpoint(
        args.model_prefix if rank == 0 else
        "%s-%d" % (args.model_prefix, rank))


def add_fit_args(parser):
    """Shared CLI (reference common/fit.py:add_fit_args)."""
    train = parser.add_argument_group("Training", "model training")
    train.add_argument("--network", type=str,
                       help="the neural network to use")
    train.add_argument("--num-layers", type=int,
                       help="number of layers in the neural network")
    train.add_argument("--gpus", type=str,
                       help="list of gpus to run, e.g. 0 or 0,2,5; "
                       "on this framework each id maps to a local "
                       "accelerator device")
    train.add_argument("--kv-store", type=str, default="device",
                       help="key-value store type")
    train.add_argument("--num-epochs", type=int, default=100,
                       help="max num of epochs")
    train.add_argument("--lr", type=float, default=0.1,
                       help="initial learning rate")
    train.add_argument("--lr-factor", type=float, default=0.1,
                       help="the ratio to reduce lr on each step")
    train.add_argument("--lr-step-epochs", type=str, default="30,60",
                       help="the epochs to reduce the lr, e.g. 30,60")
    train.add_argument("--optimizer", type=str, default="sgd",
                       help="the optimizer type")
    train.add_argument("--mom", type=float, default=0.9,
                       help="momentum for sgd")
    train.add_argument("--wd", type=float, default=0.0001,
                       help="weight decay for sgd")
    train.add_argument("--batch-size", type=int, default=128,
                       help="the batch size")
    train.add_argument("--disp-batches", type=int, default=20,
                       help="show progress for every n batches")
    train.add_argument("--model-prefix", type=str,
                       help="model prefix for checkpoints")
    train.add_argument("--load-epoch", type=int,
                       help="load the model on an epoch using the "
                       "model-prefix")
    train.add_argument("--top-k", type=int, default=0,
                       help="report the top-k accuracy. 0 means no report")
    train.add_argument("--test-io", type=int, default=0,
                       help="1 means test reading speed without training")
    train.add_argument("--dtype", type=str, default="float32",
                       help="precision: float32 or float16/bfloat16")
    train.add_argument("--monitor", dest="monitor", type=int, default=0,
                       help="log network parameters every N iters if "
                       "larger than 0")
    train.add_argument("--gc-type", type=str, default="none",
                       help="gradient compression: none or 2bit")
    train.add_argument("--gc-threshold", type=float, default=0.5,
                       help="2bit gradient compression threshold")
    return train


def fit(args, network, data_loader, **kwargs):
    """Train `network` on `data_loader(args, kv)` (reference fit.py:214)."""
    kv = mx.kv.create(args.kv_store)
    if getattr(args, "gc_type", "none") != "none":
        kv.set_gradient_compression({"type": args.gc_type,
                                     "threshold": args.gc_threshold})
    head = "%(asctime)-15s Node[" + str(kv.rank) + "] %(message)s"
    logging.basicConfig(level=logging.DEBUG, format=head)
    logging.info("start with arguments %s", args)

    (train, val) = data_loader(args, kv)
    if args.test_io:
        tic = time.time()
        for i, batch in enumerate(train):
            for j in batch.data:
                j.wait_to_read()
            if (i + 1) % args.disp_batches == 0:
                logging.info("Batch [%d]\tSpeed: %.2f samples/sec", i,
                             args.disp_batches * args.batch_size /
                             (time.time() - tic))
                tic = time.time()
        return

    sym, arg_params, aux_params = _load_model(args, kv.rank)
    if sym is not None:
        assert sym.tojson() == network.tojson()

    checkpoint = _save_model(args, kv.rank)

    if args.gpus:
        devs = [mx.tpu(int(i)) for i in args.gpus.split(",")]
    else:
        devs = mx.tpu() if mx.num_gpus() > 0 else mx.cpu()

    lr, lr_scheduler = _get_lr_scheduler(args, kv)

    model = mx.mod.Module(context=devs, symbol=network)

    optimizer_params = {
        "learning_rate": lr,
        "wd": args.wd,
        "lr_scheduler": lr_scheduler}
    if args.optimizer in ("sgd", "nag"):
        optimizer_params["momentum"] = args.mom

    monitor = mx.Monitor(args.monitor, pattern=".*") if args.monitor > 0 \
        else None

    initializer = mx.init.Xavier(rnd_type="gaussian", factor_type="in",
                                 magnitude=2)
    eval_metrics = ["accuracy"]
    if args.top_k > 0:
        eval_metrics.append(mx.metric.create("top_k_accuracy",
                                             top_k=args.top_k))

    batch_end_callbacks = [mx.callback.Speedometer(args.batch_size,
                                                   args.disp_batches)]
    if "batch_end_callback" in kwargs:
        cbs = kwargs["batch_end_callback"]
        batch_end_callbacks += cbs if isinstance(cbs, list) else [cbs]

    model.fit(train,
              begin_epoch=args.load_epoch if args.load_epoch else 0,
              num_epoch=args.num_epochs,
              eval_data=val,
              eval_metric=eval_metrics,
              kvstore=kv,
              optimizer=args.optimizer,
              optimizer_params=optimizer_params,
              initializer=initializer,
              arg_params=arg_params,
              aux_params=aux_params,
              batch_end_callback=batch_end_callbacks,
              epoch_end_callback=checkpoint,
              allow_missing=True,
              monitor=monitor)
    return model
