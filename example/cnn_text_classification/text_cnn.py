"""CNN for sentence classification (reference
example/cnn_text_classification/text_cnn.py shape — the Kim-2014
architecture): embedding -> parallel convolutions of widths 3/4/5 over
the token axis -> max-over-time pooling -> concat -> dropout -> softmax.
Trained on a synthetic keyword-detection task through the Module API.

Usage: python text_cnn.py --num-epochs 3
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx


def build_symbol(vocab_size, num_embed, seq_len, filter_sizes, num_filter,
                 num_classes, dropout):
    data = mx.sym.Variable("data")            # (B, seq_len) token ids
    label = mx.sym.Variable("softmax_label")
    embed = mx.sym.Embedding(data, input_dim=vocab_size,
                             output_dim=num_embed, name="embed")
    # (B, 1, seq_len, num_embed): the token axis is the conv height
    conv_in = mx.sym.Reshape(embed, shape=(0, 1, seq_len, num_embed))
    pooled = []
    for fs in filter_sizes:
        conv = mx.sym.Convolution(conv_in, kernel=(fs, num_embed),
                                  num_filter=num_filter,
                                  name="conv%d" % fs)
        act = mx.sym.Activation(conv, act_type="relu")
        pool = mx.sym.Pooling(act, pool_type="max",
                              kernel=(seq_len - fs + 1, 1))
        pooled.append(pool)
    concat = mx.sym.Concat(*pooled, dim=1)
    h = mx.sym.Reshape(concat,
                       shape=(0, num_filter * len(filter_sizes)))
    if dropout > 0:
        h = mx.sym.Dropout(h, p=dropout)
    fc = mx.sym.FullyConnected(h, num_hidden=num_classes, name="fc")
    return mx.sym.SoftmaxOutput(fc, label, name="softmax")


def synthetic_sentences(n, vocab_size, seq_len, num_classes, rng):
    """Label = which of the class-keyword tokens appears in the
    sentence (token k is the keyword for class k)."""
    X = rng.randint(num_classes, vocab_size, size=(n, seq_len))
    y = rng.randint(0, num_classes, size=n)
    pos = rng.randint(0, seq_len, size=n)
    X[np.arange(n), pos] = y          # plant the keyword
    return X.astype(np.float32), y.astype(np.float32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--seq-len", type=int, default=20)
    ap.add_argument("--vocab-size", type=int, default=100)
    ap.add_argument("--num-embed", type=int, default=16)
    ap.add_argument("--num-filter", type=int, default=8)
    ap.add_argument("--num-classes", type=int, default=4)
    ap.add_argument("--dropout", type=float, default=0.25)
    ap.add_argument("--lr", type=float, default=0.05)
    args = ap.parse_args()

    rng = np.random.RandomState(0)
    X, y = synthetic_sentences(1024, args.vocab_size, args.seq_len,
                               args.num_classes, rng)
    Xv, yv = synthetic_sentences(256, args.vocab_size, args.seq_len,
                                 args.num_classes, rng)

    sym = build_symbol(args.vocab_size, args.num_embed, args.seq_len,
                       (3, 4, 5), args.num_filter, args.num_classes,
                       args.dropout)
    train = mx.io.NDArrayIter(X, y, args.batch_size, shuffle=True,
                              label_name="softmax_label")
    val = mx.io.NDArrayIter(Xv, yv, args.batch_size,
                            label_name="softmax_label")
    mod = mx.mod.Module(sym, data_names=["data"],
                        label_names=["softmax_label"])
    mod.fit(train, eval_data=val, num_epoch=args.num_epochs,
            optimizer="adam", optimizer_params={"learning_rate": args.lr},
            initializer=mx.init.Xavier(),
            batch_end_callback=mx.callback.Speedometer(args.batch_size,
                                                       16))
    acc = dict(mod.score(val, mx.metric.Accuracy()))["accuracy"]
    print("validation accuracy %.3f" % acc)
    assert acc > 0.6, acc
    print("text cnn done")


if __name__ == "__main__":
    main()
