"""Torch interop demo (reference example/torch/ shape, PyTorch era).

Three flows from mxnet_tpu.plugin:
1. a torch feature extractor as a Gluon block inside a mixed net,
   trained end-to-end by a Gluon Trainer;
2. a torch loss as the training criterion;
3. converting a torch state dict into framework params and running the
   equivalent Symbol net output-exact.

Usage: python torch_interop.py --steps 80
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

try:
    import torch
except ImportError:
    print("pytorch is not installed; torch interop demo skipped")
    sys.exit(0)

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.plugin import TorchBlock, TorchCriterion, convert_torch_module


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=80)
    ap.add_argument("--batch-size", type=int, default=32)
    args = ap.parse_args()

    torch.manual_seed(0)
    rng = np.random.RandomState(0)

    # -- 1+2: hybrid net + torch criterion -----------------------------
    tfeat = torch.nn.Sequential(torch.nn.Linear(6, 24), torch.nn.GELU())
    net = mx.gluon.nn.Sequential()
    with net.name_scope():
        net.add(TorchBlock(tfeat))
        net.add(mx.gluon.nn.Dense(3))
    net.collect_params().initialize(ctx=mx.cpu())
    trainer = mx.gluon.Trainer(net.collect_params(), "adam",
                               {"learning_rate": 0.02})
    crit = TorchCriterion(torch.nn.CrossEntropyLoss())

    W = rng.randn(6, 3).astype(np.float32)
    X = rng.randn(args.batch_size * 8, 6).astype(np.float32)
    Y = (X @ W).argmax(axis=1).astype(np.int32)

    losses = []
    for step in range(args.steps):
        idx = rng.randint(0, X.shape[0], args.batch_size)
        xb, yb = nd.array(X[idx]), nd.array(Y[idx], dtype=np.int32)
        with mx.autograd.record():
            logits = net(xb)
            loss = crit(logits, yb)
        loss.backward()
        trainer.step(args.batch_size)
        losses.append(float(loss.asnumpy()))
        if step % 20 == 0 or step == args.steps - 1:
            print("step %d  ce %.4f" % (step, losses[-1]))
    pred = net(nd.array(X)).asnumpy().argmax(axis=1)
    acc = (pred == Y).mean()
    print("hybrid net train accuracy %.3f" % acc)
    assert acc > 0.8, acc

    # -- 3: state-dict conversion --------------------------------------
    class TorchNet(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.fc1 = torch.nn.Linear(6, 10)
            self.fc2 = torch.nn.Linear(10, 3)

        def forward(self, x):
            return self.fc2(torch.tanh(self.fc1(x)))

    tnet = TorchNet().eval()
    arg_params, aux_params = convert_torch_module(tnet)
    data = mx.sym.Variable("data")
    y = mx.sym.FullyConnected(data, name="fc1", num_hidden=10)
    y = mx.sym.Activation(y, act_type="tanh")
    y = mx.sym.FullyConnected(y, name="fc2", num_hidden=3)
    exe = y.simple_bind(mx.cpu(), grad_req="null", data=(4, 6))
    exe.copy_params_from({k: nd.array(v) for k, v in arg_params.items()})
    x = rng.randn(4, 6).astype(np.float32)
    got = exe.forward(data=nd.array(x))[0].asnumpy()
    with torch.no_grad():
        want = tnet(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    print("state-dict conversion output-exact")
    print("torch interop done")


if __name__ == "__main__":
    main()
