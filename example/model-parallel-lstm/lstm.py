#!/usr/bin/env python
"""Model-parallel LSTM: layers placed on different devices —
BASELINE config #5.

Port of /root/reference/example/model-parallel-lstm/lstm.py:65-116: each
LSTM layer is built inside ``with mx.AttrScope(ctx_group='layer%d')`` and
bind maps groups to devices via ``group2ctx``.  TPU-native, the ctx_group
becomes a placement constraint inside ONE XLA program (executor.py) —
XLA partitions the program and inserts the transfers that the reference's
PlaceDevice pass expressed as _CrossDeviceCopy nodes.

Run on CPU with 8 virtual devices to see the partitioning:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python lstm.py --num-layers 4 --ngpu 4
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(os.path.expanduser(__file__))), "..", ".."))
import mxnet_tpu as mx  # noqa: E402


def lstm_unroll(num_layers, seq_len, input_size, num_hidden, num_embed,
                num_label, group_for_layer):
    """Unrolled multi-layer LSTM with per-layer ctx groups."""
    cells = []
    for i in range(num_layers):
        with mx.AttrScope(ctx_group=group_for_layer(i)):
            cells.append(mx.rnn.LSTMCell(num_hidden, prefix="l%d_" % i))

    with mx.AttrScope(ctx_group=group_for_layer(0)):
        data = mx.sym.Variable("data")
        embed = mx.sym.Embedding(data=data, input_dim=input_size,
                                 output_dim=num_embed, name="embed")
        inputs = mx.sym.SliceChannel(embed, num_outputs=seq_len, axis=1,
                                     squeeze_axis=1)

    states = [c.begin_state() for c in cells]
    hiddens = list(inputs)
    for i, cell in enumerate(cells):
        with mx.AttrScope(ctx_group=group_for_layer(i)):
            next_h = []
            for t in range(seq_len):
                h, states[i] = cell(hiddens[t], states[i])
                next_h.append(h)
            hiddens = next_h

    with mx.AttrScope(ctx_group=group_for_layer(num_layers - 1)):
        concat = mx.sym.Concat(*[mx.sym.expand_dims(h, axis=1)
                                 for h in hiddens], dim=1)
        pred = mx.sym.Reshape(concat, shape=(-1, num_hidden))
        pred = mx.sym.FullyConnected(data=pred, num_hidden=num_label,
                                     name="pred")
        label = mx.sym.Variable("softmax_label")
        label_r = mx.sym.Reshape(label, shape=(-1,))
        sm = mx.sym.SoftmaxOutput(data=pred, label=label_r, name="softmax")
    return sm


def main():
    parser = argparse.ArgumentParser(
        description="model-parallel LSTM (reference "
        "example/model-parallel-lstm)")
    parser.add_argument("--num-layers", type=int, default=4)
    parser.add_argument("--seq-len", type=int, default=16)
    parser.add_argument("--num-hidden", type=int, default=128)
    parser.add_argument("--num-embed", type=int, default=64)
    parser.add_argument("--vocab", type=int, default=100)
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--ngpu", type=int, default=2,
                        help="number of devices to spread layers over")
    parser.add_argument("--steps", type=int, default=300)
    parser.add_argument("--lr", type=float, default=0.7)
    parser.add_argument("--clip", type=float, default=5.0)
    args = parser.parse_args()

    import jax
    ndev = min(args.ngpu, len(jax.local_devices()))
    print("spreading %d layers over %d devices" % (args.num_layers, ndev))

    def group_for_layer(i):
        return "group%d" % (i * ndev // args.num_layers)

    sym = lstm_unroll(args.num_layers, args.seq_len, args.vocab,
                      args.num_hidden, args.num_embed, args.vocab,
                      group_for_layer)
    ctx = mx.tpu if mx.num_gpus() > 0 else mx.cpu
    group2ctx = {"group%d" % i: ctx(i) for i in range(ndev)}

    exe = sym.simple_bind(ctx=ctx(0), group2ctx=group2ctx,
                          data=(args.batch_size, args.seq_len),
                          softmax_label=(args.batch_size, args.seq_len),
                          grad_req="write")
    rng = np.random.RandomState(0)
    for name, arr in exe.arg_dict.items():
        if name not in ("data", "softmax_label"):
            arr[:] = rng.uniform(-0.08, 0.08, arr.shape)

    # synthetic next-token task: t+1 = (t + 1) % vocab
    x = np.zeros((args.batch_size, args.seq_len), np.float32)
    y = np.zeros((args.batch_size, args.seq_len), np.float32)
    for b in range(args.batch_size):
        start = rng.randint(0, args.vocab)
        seq = [(start + t) % args.vocab for t in range(args.seq_len + 1)]
        x[b] = seq[:-1]
        y[b] = seq[1:]
    exe.arg_dict["data"][:] = x
    exe.arg_dict["softmax_label"][:] = y

    import time
    for step in range(args.steps):
        t0 = time.time()
        exe.forward_backward()
        # global-norm gradient clipping, as the reference example's
        # training loop (model-parallel-lstm/lstm.py) did
        grads = {name: grad.asnumpy()
                 for name, grad in exe.grad_dict.items()
                 if grad is not None and
                 name not in ("data", "softmax_label")}
        gnorm = np.sqrt(sum(float((g * g).sum())
                            for g in grads.values()))
        scale = args.clip / max(gnorm, args.clip)
        for name, g in grads.items():
            exe.arg_dict[name][:] = \
                exe.arg_dict[name].asnumpy() - (args.lr * scale) * g
        if step % 10 == 0:
            out = exe.outputs[0].asnumpy()
            nll = -np.log(np.maximum(
                out[np.arange(out.shape[0]), y.reshape(-1).astype(int)],
                1e-9)).mean()
            print("step %d nll %.4f (%.3fs)" % (step, nll,
                                                time.time() - t0))
    print("final nll:", nll)
    if args.steps >= 200:
        assert nll < 2.5, "model-parallel LSTM failed to learn"
    print("MODEL PARALLEL LSTM OK")


if __name__ == "__main__":
    main()
