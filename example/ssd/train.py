#!/usr/bin/env python
"""Single-shot detector (SSD) — BASELINE config #4.

Port of /root/reference/example/ssd/: a conv backbone with multi-scale
heads wired through the contrib MultiBox trio —
MultiBoxPrior (anchors) → MultiBoxTarget (training targets) →
MultiBoxDetection (NMS'd detections at inference).

Runs on a synthetic shapes dataset (bright rectangles of 2 classes on
dark background) when no --data-train .rec is given, so the full
anchor/target/loss/detect pipeline exercises end to end with zero
downloads.
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(os.path.expanduser(__file__))), "..", ".."))
import mxnet_tpu as mx  # noqa: E402


def conv_act(data, name, num_filter, kernel=(3, 3), pad=(1, 1),
             stride=(1, 1)):
    c = mx.sym.Convolution(data=data, kernel=kernel, pad=pad,
                           stride=stride, num_filter=num_filter,
                           name=name)
    b = mx.sym.BatchNorm(data=c, name=name + "_bn")
    return mx.sym.Activation(data=b, act_type="relu", name=name + "_relu")


def multibox_layer(from_layers, num_classes, sizes, ratios):
    """Per-scale cls/loc heads + anchors (reference example/ssd/symbol/
    common.py:multibox_layer)."""
    cls_preds = []
    loc_preds = []
    anchors = []
    for i, layer in enumerate(from_layers):
        size = sizes[i]
        ratio = ratios[i]
        num_anchors = len(size) + len(ratio) - 1
        # location regression head
        loc = mx.sym.Convolution(data=layer, kernel=(3, 3), pad=(1, 1),
                                 num_filter=num_anchors * 4,
                                 name="loc_pred_%d" % i)
        loc = mx.sym.transpose(loc, axes=(0, 2, 3, 1))
        loc_preds.append(mx.sym.Flatten(loc))
        # class prediction head
        cls = mx.sym.Convolution(data=layer, kernel=(3, 3), pad=(1, 1),
                                 num_filter=num_anchors * (num_classes + 1),
                                 name="cls_pred_%d" % i)
        cls = mx.sym.transpose(cls, axes=(0, 2, 3, 1))
        cls_preds.append(mx.sym.Reshape(
            mx.sym.Flatten(cls), shape=(0, -1, num_classes + 1)))
        # anchors
        anc = mx.sym.contrib.MultiBoxPrior(
            layer, sizes=tuple(size), ratios=tuple(ratio), clip=True,
            name="anchors_%d" % i)
        anchors.append(anc)
    loc_preds = mx.sym.Concat(*loc_preds, dim=1, name="multibox_loc_pred")
    cls_preds = mx.sym.Concat(*cls_preds, dim=1, name="multibox_cls_pred")
    cls_preds = mx.sym.transpose(cls_preds, axes=(0, 2, 1))
    anchors = mx.sym.Concat(*anchors, dim=1, name="multibox_anchors")
    return [loc_preds, cls_preds, anchors]


def get_ssd_symbol(num_classes=2, mode="train"):
    """Small SSD: 3 scales over a 5-conv backbone."""
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("label")
    b1 = conv_act(data, "conv1", 16)
    p1 = mx.sym.Pooling(b1, pool_type="max", kernel=(2, 2), stride=(2, 2))
    b2 = conv_act(p1, "conv2", 32)
    p2 = mx.sym.Pooling(b2, pool_type="max", kernel=(2, 2), stride=(2, 2))
    b3 = conv_act(p2, "conv3", 64)          # stride 4 feature map
    p3 = mx.sym.Pooling(b3, pool_type="max", kernel=(2, 2), stride=(2, 2))
    b4 = conv_act(p3, "conv4", 64)          # stride 8
    p4 = mx.sym.Pooling(b4, pool_type="max", kernel=(2, 2), stride=(2, 2))
    b5 = conv_act(p4, "conv5", 64)          # stride 16

    sizes = [[0.2, 0.27], [0.37, 0.45], [0.54, 0.62]]
    ratios = [[1.0, 2.0, 0.5]] * 3
    loc_preds, cls_preds, anchors = multibox_layer(
        [b3, b4, b5], num_classes, sizes, ratios)

    if mode == "train":
        tmp = mx.sym.contrib.MultiBoxTarget(
            anchors, label, cls_preds, overlap_threshold=0.5,
            ignore_label=-1, negative_mining_ratio=3,
            minimum_negative_samples=0, negative_mining_thresh=0.5,
            variances=(0.1, 0.1, 0.2, 0.2), name="multibox_target")
        loc_target, loc_target_mask, cls_target = tmp[0], tmp[1], tmp[2]
        cls_prob = mx.sym.SoftmaxOutput(
            data=cls_preds, label=cls_target,
            ignore_label=-1, use_ignore=True,
            multi_output=True, normalization="valid",
            name="cls_prob")
        loc_diff = loc_target_mask * (loc_preds - loc_target)
        loc_loss_ = mx.sym.smooth_l1(data=loc_diff, scalar=1.0,
                                     name="loc_loss_")
        loc_loss = mx.sym.MakeLoss(loc_loss_, grad_scale=1.0,
                                   normalization="valid",
                                   name="loc_loss")
        cls_label = mx.sym.MakeLoss(data=cls_target, grad_scale=0,
                                    name="cls_label")
        det = mx.sym.contrib.MultiBoxDetection(
            cls_prob, loc_preds, anchors,
            name="detection", nms_threshold=0.45, force_suppress=False,
            variances=(0.1, 0.1, 0.2, 0.2), nms_topk=400)
        det = mx.sym.MakeLoss(data=det, grad_scale=0, name="det_out")
        return mx.sym.Group([cls_prob, loc_loss, cls_label, det])
    # inference
    cls_prob = mx.sym.softmax(data=cls_preds, axis=1)
    return mx.sym.contrib.MultiBoxDetection(
        cls_prob, loc_preds, anchors, name="detection",
        nms_threshold=0.45, variances=(0.1, 0.1, 0.2, 0.2), nms_topk=400)


def synthetic_batch(batch_size, size=64, max_obj=2, seed=0):
    """Images with 1-2 bright rectangles; label rows
    [cls, x1, y1, x2, y2] normalized, padded with -1."""
    rng = np.random.RandomState(seed)
    x = rng.uniform(0, 0.1, (batch_size, 3, size, size)).astype(np.float32)
    y = np.full((batch_size, max_obj, 5), -1.0, np.float32)
    for b in range(batch_size):
        for k in range(rng.randint(1, max_obj + 1)):
            w = rng.uniform(0.25, 0.5)
            h = rng.uniform(0.25, 0.5)
            x1 = rng.uniform(0, 1 - w)
            y1 = rng.uniform(0, 1 - h)
            cls = rng.randint(0, 2)
            px = slice(int(x1 * size), int((x1 + w) * size))
            py = slice(int(y1 * size), int((y1 + h) * size))
            val = 0.9 if cls else 0.5
            x[b, :, py, px] = val
            y[b, k] = [cls, x1, y1, x1 + w, y1 + h]
    return x, y


def write_shapes_rec(path, n=256, size=64, max_obj=2, seed=0):
    """Pack the synthetic shapes dataset into a detection .rec (flat
    labels [2, 5, obj...]) so the NATIVE box-aware pipeline
    (io.ImageDetRecordIter, src/mxtpu/det_aug.cc) can feed training."""
    from mxnet_tpu import recordio
    rng = np.random.RandomState(seed)
    w = recordio.MXRecordIO(path, "w")
    for i in range(n):
        img = rng.uniform(0, 25, (size, size, 3))
        objs = []
        for _ in range(rng.randint(1, max_obj + 1)):
            bw, bh = rng.uniform(0.25, 0.5, 2)
            x1 = rng.uniform(0, 1 - bw)
            y1 = rng.uniform(0, 1 - bh)
            cls = rng.randint(0, 2)
            val = 230 if cls else 128
            img[int(y1 * size):int((y1 + bh) * size),
                int(x1 * size):int((x1 + bw) * size)] = val
            objs.append([float(cls), x1, y1, x1 + bw, y1 + bh])
        flat = np.asarray([2.0, 5.0] + [v for o in objs for v in o],
                          np.float32)
        # pack_img owns the JPEG encode (recordio.py); BGR in, like the
        # reference's cv2 convention — the shapes are channel-symmetric
        w.write(recordio.pack_img(
            recordio.IRHeader(len(flat), flat, i, 0),
            img.astype(np.uint8)[:, :, ::-1], quality=95))
    w.close()


def main():
    parser = argparse.ArgumentParser(description="train a tiny SSD")
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--steps", type=int, default=150)
    parser.add_argument("--lr", type=float, default=0.02)
    parser.add_argument("--num-classes", type=int, default=2)
    parser.add_argument("--image-size", type=int, default=64)
    parser.add_argument("--data-train", default="",
                        help="detection .rec: train through the native "
                             "box-aware pipeline (io.ImageDetRecordIter) "
                             "instead of in-memory synthetic batches; "
                             "'synthetic' writes+uses a generated one")
    args = parser.parse_args()

    rec_iter = None
    if args.data_train:
        rec_path = args.data_train
        if rec_path == "synthetic":
            import tempfile
            rec_path = os.path.join(tempfile.mkdtemp(prefix="ssd_rec_"),
                                    "shapes.rec")
            write_shapes_rec(rec_path, n=32 * args.batch_size,
                             size=args.image_size)
        # the native pipeline decodes/augments on C++ worker threads;
        # mirror is box-aware, pixels normalized to the synthetic scale
        rec_iter = mx.io.ImageDetRecordIter(
            path_imgrec=rec_path,
            data_shape=(3, args.image_size, args.image_size),
            batch_size=args.batch_size, shuffle=True, seed=0,
            rand_mirror=True, std_r=255.0, std_g=255.0, std_b=255.0)
        label_shape = (args.batch_size, rec_iter.max_objects,
                       rec_iter.object_width)
        print("rec-mode: %d samples, label shape %s"
              % (rec_iter.num_samples, label_shape))

    net = get_ssd_symbol(args.num_classes, mode="train")
    mod = mx.mod.Module(net, data_names=("data",), label_names=("label",),
                        context=mx.tpu() if mx.num_gpus() > 0 else mx.cpu())
    x, y = synthetic_batch(args.batch_size, args.image_size)
    if rec_iter is not None:
        y = np.full(label_shape, -1.0, np.float32)
    mod.bind(data_shapes=[("data", x.shape)],
             label_shapes=[("label", y.shape)])
    mod.init_params(mx.init.Xavier(magnitude=2))
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": args.lr,
                                         "momentum": 0.9, "wd": 1e-4})
    import time

    def next_batch(step):
        if rec_iter is None:
            xs, ys = synthetic_batch(args.batch_size, args.image_size,
                                     seed=step)
            return mx.io.DataBatch([mx.nd.array(xs)], [mx.nd.array(ys)])
        try:
            return next(rec_iter)
        except StopIteration:
            rec_iter.reset()
            return next(rec_iter)

    for step in range(args.steps):
        batch = next_batch(step)
        t0 = time.time()
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()
        if step % 10 == 0:
            cls_prob = mod.get_outputs()[0].asnumpy()
            cls_target = mod.get_outputs()[2].asnumpy()
            mask = cls_target >= 0
            pred = cls_prob.argmax(axis=1)
            acc = (pred[mask[:, :]] == cls_target[mask]).mean() \
                if mask.any() else 0.0
            print("step %d anchor-cls acc %.3f (%.2fs)"
                  % (step, acc, time.time() - t0))
    # final detection sanity: run the detect head
    det = mod.get_outputs()[3].asnumpy()
    print("detections shape:", det.shape)
    print("best detection per image (cls, score, box):")
    for b in range(min(2, det.shape[0])):
        best = det[b, det[b, :, 1].argmax()]
        print("  img%d:" % b, best)
    if args.steps >= 100:
        assert acc > 0.75, "SSD anchor classification failed to learn"
    print("SSD OK")


if __name__ == "__main__":
    main()
