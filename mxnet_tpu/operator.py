"""Python-defined operators (mx.operator: CustomOp / CustomOpProp / register).

Port of /root/reference/python/mxnet/operator.py (880 L) — user code
subclasses ``CustomOp`` (imperative forward/backward on NDArrays) and
``CustomOpProp`` (shapes/types), registers under a name, and invokes via
``mx.nd.Custom(*data, op_type=name)`` or ``mx.sym.Custom``.

TPU-native wiring: the reference routes callbacks through the C API's
custom-op thread (src/operator/custom/custom.cc:385-408); here the Python
forward runs inside the XLA program as a ``jax.pure_callback`` (host
callback with declared result shapes), and the gradient is a
``jax.custom_vjp`` whose backward is a second pure_callback into
``CustomOp.backward`` — so Custom ops compose with jit/grad/vmap-free use
like any native op.
"""
from __future__ import annotations

import functools

import numpy as _np

from .base import MXNetError
from .ops.registry import register_op

__all__ = ["CustomOp", "CustomOpProp", "register", "get_entry"]


class CustomOp(object):
    """Base class for operators implemented in Python
    (reference operator.py:413)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        """Compute outputs; write them with self.assign(out_data[i], ...)."""
        pass

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        """Compute input gradients into in_grad."""
        pass

    def assign(self, dst, req, src):
        """Assign src to dst per req ('null'|'write'|'inplace'|'add')
        (reference operator.py:450)."""
        if req == "null":
            return
        _reject_device_value(src)  # before any arithmetic coerces it
        if req in ("write", "inplace"):
            dst[:] = src
        elif req == "add":
            dst[:] = dst[:] + src  # noqa: E203 — NDArray in-place add


class CustomOpProp(object):
    """Property/metadata class for a custom op (reference operator.py:459)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        return (in_type, [in_type[0]] * len(self.list_outputs()),
                [in_type[0]] * len(self.list_auxiliary_states()))

    def list_outputs(self):
        return ["output"]

    def list_arguments(self):
        return ["data"]

    def list_auxiliary_states(self):
        return []

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return CustomOp()


_REGISTRY = {}


def register(reg_name):
    """Decorator: register a CustomOpProp subclass under reg_name
    (reference operator.py:register)."""
    def do_register(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError(
                "Can only register subclass of CustomOpProp")
        _REGISTRY[reg_name] = prop_cls
        return prop_cls
    return do_register


def get_entry(op_type):
    prop_cls = _REGISTRY.get(op_type)
    if prop_cls is None:
        raise MXNetError("Custom op type %s is not registered with "
                         "mx.operator.register" % op_type)
    return prop_cls


def _reject_device_value(value):
    """Device NDArrays must never enter host-callback arithmetic: numpy
    would coerce them element-by-element, re-entering JAX dispatch from
    inside the executing program and deadlocking it."""
    if hasattr(value, "_data") and not isinstance(value, _HostArray):
        raise MXNetError(
            "CustomOp callbacks run on the host inside the compiled "
            "program; write numpy arrays (use .asnumpy() values), "
            "not device NDArrays")


class _HostArray(object):
    """Tiny NDArray-alike handed to CustomOp callbacks: supports
    [:] read/write, asnumpy, shape/dtype — enough for the reference's
    assign() idiom without device round-trips inside the callback."""

    __slots__ = ("_arr",)

    def __init__(self, arr):
        self._arr = _np.asarray(arr)

    def __getitem__(self, idx):
        return self._arr[idx]

    def __setitem__(self, idx, value):
        if isinstance(value, _HostArray):
            value = value._arr
        else:
            _reject_device_value(value)
        self._arr[idx] = _np.asarray(value)

    def asnumpy(self):
        return self._arr

    @property
    def shape(self):
        return self._arr.shape

    @property
    def dtype(self):
        return self._arr.dtype

    def __array__(self, dtype=None):
        return self._arr if dtype is None else self._arr.astype(dtype)


def _prop_for(op_type, kwargs):
    """Instantiate the registered prop with the op's extra kwargs
    (reference passes all kwargs as strings; we pass them as-is)."""
    prop_cls = get_entry(op_type)
    return prop_cls(**kwargs)


def _parse_params(params):
    op_type = params.get("op_type")
    if op_type is None:
        raise MXNetError("Custom op requires op_type kwarg")
    kwargs = {k: v for k, v in params.items()
              if k not in ("op_type",) and not k.startswith("_")}
    return op_type, kwargs


def _custom_arg_names(params):
    op_type, kwargs = _parse_params(params)
    return list(_prop_for(op_type, kwargs).list_arguments())


def _custom_aux_names(params):
    op_type, kwargs = _parse_params(params)
    return list(_prop_for(op_type, kwargs).list_auxiliary_states())


@functools.lru_cache(maxsize=None)
def _custom_impl(op_type, kwargs_key, is_train):
    """Build the custom_vjp-wrapped jax function for one
    (op_type, kwargs, is_train); is_train is static so the callback fns
    close over it (custom_vjp primals are the arrays only)."""
    import jax
    import jax.numpy as jnp

    kwargs = dict(kwargs_key)
    prop = _prop_for(op_type, kwargs)
    n_args = len(prop.list_arguments())
    n_out = len(prop.list_outputs())
    n_aux = len(prop.list_auxiliary_states())

    def _shapes_dtypes(arrays):
        in_shapes = [tuple(a.shape) for a in arrays[:n_args]]
        inferred = prop.infer_shape(list(in_shapes))
        out_shapes = [tuple(s) for s in inferred[1]]
        in_types = [a.dtype for a in arrays[:n_args]]
        tinferred = prop.infer_type(list(in_types))
        out_types = list(tinferred[1])
        return out_shapes, out_types

    def _fwd_host(*arrays):
        op = prop.create_operator(None, [a.shape for a in arrays[:n_args]],
                                  [a.dtype for a in arrays[:n_args]])
        out_shapes, out_types = _shapes_dtypes(arrays)
        in_data = [_HostArray(a) for a in arrays[:n_args]]
        aux = [_HostArray(a.copy()) for a in arrays[n_args:]]
        out_data = [_HostArray(_np.zeros(s, t))
                    for s, t in zip(out_shapes, out_types)]
        op.forward(is_train=is_train, req=["write"] * n_out,
                   in_data=in_data, out_data=out_data, aux=aux)
        return tuple([o.asnumpy() for o in out_data] +
                     [a.asnumpy() for a in aux])

    def _bwd_host(*arrays):
        # arrays = out_grads + in_data + out_data + aux
        og = [_HostArray(a) for a in arrays[:n_out]]
        ind = [_HostArray(a) for a in arrays[n_out:n_out + n_args]]
        outd = [_HostArray(a)
                for a in arrays[n_out + n_args:n_out + n_args + n_out]]
        aux = [_HostArray(a.copy())
               for a in arrays[n_out + n_args + n_out:]]
        op = prop.create_operator(None, [a.shape for a in ind],
                                  [a.dtype for a in ind])
        in_grad = [_HostArray(_np.zeros(a.shape, a.dtype)) for a in ind]
        op.backward(req=["write"] * n_args, out_grad=og, in_data=ind,
                    out_data=outd, in_grad=in_grad, aux=aux)
        return tuple(g.asnumpy() for g in in_grad)

    def _result_spec(arrays):
        out_shapes, out_types = _shapes_dtypes(arrays)
        spec = [jax.ShapeDtypeStruct(s, t)
                for s, t in zip(out_shapes, out_types)]
        spec += [jax.ShapeDtypeStruct(a.shape, a.dtype)
                 for a in arrays[n_args:]]
        return tuple(spec)

    @jax.custom_vjp
    def run(*arrays):
        return jax.pure_callback(_fwd_host, _result_spec(arrays), *arrays,
                                 vmap_method="sequential")

    def run_fwd(*arrays):
        outs = run(*arrays)
        return outs, (arrays, outs[:n_out])

    def run_bwd(res, cotangents):
        arrays, outs = res
        in_data = arrays[:n_args]
        aux = arrays[n_args:]
        out_grads = cotangents[:n_out]
        spec = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype)
                     for a in in_data)
        grads = jax.pure_callback(
            _bwd_host, spec, *(tuple(out_grads) + tuple(in_data) +
                               tuple(outs) + tuple(aux)),
            vmap_method="sequential")
        # aux states carry no gradient
        return tuple(grads) + tuple(
            jnp.zeros(a.shape, a.dtype) for a in aux)

    run.defvjp(run_fwd, run_bwd)
    return run


def _freeze(kwargs):
    return tuple(sorted(kwargs.items()))


@register_op("Custom",
             arg_names=_custom_arg_names,
             aux_names=_custom_aux_names,
             num_outputs=lambda p: len(
                 _prop_for(*_parse_params(p)).list_outputs()),
             mutate_aux=True, takes_train=True,
             param_defaults={"op_type": None})
def _custom(*arrays, op_type=None, _train=False, **kwargs):
    """Dispatch to the registered CustomOpProp (reference custom.cc:385).

    Returns visible outputs, then updated aux values (mutate_aux
    convention, as BatchNorm)."""
    impl = _custom_impl(op_type, _freeze(kwargs), bool(_train))
    outs = impl(*arrays)
    prop = _prop_for(op_type, kwargs)
    n_out = len(prop.list_outputs())
    n_aux = len(prop.list_auxiliary_states())
    if n_out == 1 and n_aux == 0:
        return outs[0]
    return tuple(outs)
