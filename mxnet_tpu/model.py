"""Model helpers: checkpointing + kvstore plumbing shared by Module & Gluon.

Port of /root/reference/python/mxnet/model.py: the `_create_kvstore` /
`_initialize_kvstore` / `_update_params(_on_kvstore)` trio (:57-130) that
both Module and the Gluon Trainer build on, and the two-artifact checkpoint
contract `prefix-symbol.json` + `prefix-%04d.params` (:340-395) with
``arg:``/``aux:`` key prefixes.
"""
from __future__ import annotations

from collections import namedtuple

from . import ndarray as nd
from . import symbol as sym
from . import kvstore as kvs
from .base import MXNetError

__all__ = ["BatchEndParam", "save_checkpoint", "load_checkpoint",
           "load_params"]

BatchEndParam = namedtuple("BatchEndParams",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def _create_kvstore(kvstore, num_device, arg_params):
    """Decide (kvstore, update_on_kvstore) (reference model.py:57-94)."""
    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, kvs.KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore:
            # a single device needs no kvstore at all
            kv = None
        else:
            kv = kvs.create(kvstore)
            if kvstore == "local":
                # biggest array bounds the choice in the reference; with XLA
                # the merged update is always cheap, keep update on kvstore
                max_size = max(int(nd_arr.size)
                               for nd_arr in arg_params.values())
                if max_size > 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        raise TypeError("kvstore must be KVStore, str or None")
    if kv is None:
        update_on_kvstore = False
    return (kv, update_on_kvstore)


def _initialize_kvstore(kvstore, param_arrays, arg_params, param_names,
                        update_on_kvstore):
    """Init kvstore keys from arg_params (reference model.py:96-103)."""
    for idx, param_on_devs in enumerate(param_arrays):
        name = param_names[idx]
        kvstore.init(name, arg_params[name])
        if update_on_kvstore:
            kvstore.pull(name, param_on_devs, priority=-idx)


def _update_params_on_kvstore(param_arrays, grad_arrays, kvstore,
                              param_names):
    """push grads / pull weights (reference model.py:105-115)."""
    for index, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list[0] is None:
            continue
        name = param_names[index]
        kvstore.push(name, grad_list, priority=-index)
        kvstore.pull(name, arg_list, priority=-index)


def _update_params(param_arrays, grad_arrays, updater, num_device,
                   kvstore=None, param_names=None):
    """aggregate via kvstore, update locally (reference model.py:117-130)."""
    for index, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list[0] is None:
            continue
        if kvstore:
            name = param_names[index]
            kvstore.push(name, grad_list, priority=-index)
            kvstore.pull(name, grad_list, priority=-index)
        for k, p in enumerate(zip(arg_list, grad_list)):
            w, g = p
            updater(index * num_device + k, g, w)


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params):
    """Write prefix-symbol.json + prefix-%04d.params (reference :340)."""
    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    nd.save(param_name, save_dict)


def load_params(prefix, epoch):
    """Load params only (reference model.py:load_params)."""
    save_dict = nd.load("%s-%04d.params" % (prefix, epoch))
    arg_params = {}
    aux_params = {}
    for k, v in save_dict.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
        else:
            raise MXNetError("unknown param prefix in %s" % k)
    return arg_params, aux_params


def load_checkpoint(prefix, epoch):
    """Load symbol + params (reference model.py:379-395)."""
    symbol = sym.load("%s-symbol.json" % prefix)
    arg_params, aux_params = load_params(prefix, epoch)
    return (symbol, arg_params, aux_params)
