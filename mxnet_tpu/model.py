"""Model helpers: checkpointing + kvstore plumbing shared by Module & Gluon.

Port of /root/reference/python/mxnet/model.py: the `_create_kvstore` /
`_initialize_kvstore` / `_update_params(_on_kvstore)` trio (:57-130) that
both Module and the Gluon Trainer build on, and the two-artifact checkpoint
contract `prefix-symbol.json` + `prefix-%04d.params` (:340-395) with
``arg:``/``aux:`` key prefixes.
"""
from __future__ import annotations

from collections import namedtuple

import numpy as _np

from . import ndarray as nd
from . import symbol as sym
from . import kvstore as kvs
from .base import MXNetError

__all__ = ["BatchEndParam", "FeedForward", "save_checkpoint",
           "load_checkpoint", "load_params"]

BatchEndParam = namedtuple("BatchEndParams",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def _create_kvstore(kvstore, num_device, arg_params):
    """Decide (kvstore, update_on_kvstore) (reference model.py:57-94)."""
    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, kvs.KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore:
            # a single device needs no kvstore at all
            kv = None
        else:
            kv = kvs.create(kvstore)
            if kvstore == "local":
                # biggest array bounds the choice in the reference; with XLA
                # the merged update is always cheap, keep update on kvstore
                max_size = max(int(nd_arr.size)
                               for nd_arr in arg_params.values())
                if max_size > 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        raise TypeError("kvstore must be KVStore, str or None")
    if kv is None:
        update_on_kvstore = False
    return (kv, update_on_kvstore)


def _initialize_kvstore(kvstore, param_arrays, arg_params, param_names,
                        update_on_kvstore):
    """Init kvstore keys from arg_params (reference model.py:96-103)."""
    for idx, param_on_devs in enumerate(param_arrays):
        name = param_names[idx]
        kvstore.init(name, arg_params[name])
        if update_on_kvstore:
            kvstore.pull(name, param_on_devs, priority=-idx)


def _update_params_on_kvstore(param_arrays, grad_arrays, kvstore,
                              param_names):
    """push grads / pull weights (reference model.py:105-115).

    All keys go in ONE push/pull call: for dist stores the whole key
    batch becomes a single jitted all-reduce program (kvstore.py
    _dist_allreduce) instead of the reference's per-key engine ops."""
    names, grads, args = [], [], []
    for index, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list[0] is None:
            continue
        names.append(param_names[index])
        grads.append(grad_list)
        args.append(arg_list)
    if names:
        kvstore.push(names, grads)
        kvstore.pull(names, args)


def _update_params(param_arrays, grad_arrays, updater, num_device,
                   kvstore=None, param_names=None):
    """aggregate via kvstore, update locally (reference model.py:117-130)."""
    live = [(i, a, g) for i, (a, g) in
            enumerate(zip(param_arrays, grad_arrays)) if g[0] is not None]
    if kvstore and live:
        names = [param_names[i] for i, _, _ in live]
        grads = [g for _, _, g in live]
        kvstore.push(names, grads)
        kvstore.pull(names, grads)
    for index, arg_list, grad_list in live:
        for k, p in enumerate(zip(arg_list, grad_list)):
            w, g = p
            updater(index * num_device + k, g, w)


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    keep_last=None, mode=None):
    """Write prefix-symbol.json + prefix-%04d.params (reference :340).

    Crash-safe via checkpoint.CheckpointManager: each artifact lands
    atomically and a manifest with content checksums commits the epoch
    LAST, so recovery (``CheckpointManager.latest()``) never picks up a
    torn half-written checkpoint.  ``keep_last`` prunes to the N newest
    complete checkpoints.  Under ``MXTPU_ASYNC_CKPT=1`` the write runs
    on the background pipeline: this call only snapshots to host memory
    (checkpoint.py, "async checkpoint pipeline")."""
    from .checkpoint import CheckpointManager
    CheckpointManager(prefix, keep_last=keep_last).save(
        epoch, arg_params, aux_params, symbol=symbol, mode=mode)


def load_params(prefix, epoch):
    """Load params only (reference model.py:load_params)."""
    save_dict = nd.load("%s-%04d.params" % (prefix, epoch))
    arg_params = {}
    aux_params = {}
    for k, v in save_dict.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
        else:
            raise MXNetError("unknown param prefix in %s" % k)
    return arg_params, aux_params


def load_checkpoint(prefix, epoch):
    """Load symbol + params (reference model.py:379-395)."""
    symbol = sym.load("%s-symbol.json" % prefix)
    arg_params, aux_params = load_params(prefix, epoch)
    return (symbol, arg_params, aux_params)


class FeedForward(object):
    """Deprecated legacy model API (reference model.py:FeedForward, 967 L).

    Kept for script compatibility; internally delegates to
    mxnet_tpu.module.Module, as the reference docs advise migrating to.
    """

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        import warnings
        warnings.warn("FeedForward is deprecated. Please use Module "
                      "instead.", DeprecationWarning)
        from .initializer import Uniform
        self.symbol = symbol
        self.ctx = ctx
        self.num_epoch = num_epoch
        self.epoch_size = epoch_size
        self.optimizer = optimizer
        self.initializer = initializer if initializer is not None \
            else Uniform(0.01)
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.allow_extra_params = allow_extra_params
        self.begin_epoch = begin_epoch
        self.kwargs = dict(kwargs)
        self._module = None

    def _init_iter(self, X, y, is_train):
        from .io import NDArrayIter, DataIter
        if isinstance(X, DataIter):
            return X
        X = X.asnumpy() if isinstance(X, nd.NDArray) else _np.asarray(X)
        if y is not None:
            y = y.asnumpy() if isinstance(y, nd.NDArray) else _np.asarray(y)
        elif is_train:
            raise ValueError("y must be specified when X is numpy.ndarray")
        if y is None:
            y = _np.zeros(X.shape[0], dtype=_np.float32)
        batch_size = min(self.numpy_batch_size, X.shape[0])
        return NDArrayIter(X, y, batch_size=batch_size,
                           shuffle=is_train, last_batch_handle="roll_over"
                           if is_train else "pad")

    def _make_module(self, data_iter):
        from .module import Module
        ctx = self.ctx if self.ctx is not None else None
        mod = Module(self.symbol,
                     data_names=[d.name for d in data_iter.provide_data],
                     label_names=[l.name for l in
                                  (data_iter.provide_label or [])],
                     context=ctx)
        self._module = mod
        return mod

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            logger=None, work_load_list=None, monitor=None,
            eval_end_callback=None, eval_batch_end_callback=None):
        """Train (reference model.py:FeedForward.fit)."""
        data = self._init_iter(X, y, is_train=True)
        if eval_data is not None and not hasattr(eval_data, "provide_data"):
            ex, ey = eval_data
            eval_data = self._init_iter(ex, ey, is_train=False)
        mod = self._make_module(data)
        opt_params = {k: v for k, v in self.kwargs.items()}
        mod.fit(data, eval_data=eval_data, eval_metric=eval_metric,
                epoch_end_callback=epoch_end_callback,
                batch_end_callback=batch_end_callback, kvstore=kvstore,
                optimizer=self.optimizer, optimizer_params=opt_params,
                initializer=self.initializer,
                arg_params=self.arg_params, aux_params=self.aux_params,
                allow_missing=True, begin_epoch=self.begin_epoch,
                num_epoch=self.num_epoch if self.num_epoch else 1,
                monitor=monitor)
        self.arg_params, self.aux_params = mod.get_params()
        return self

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        """Forward over X; returns numpy outputs (reference
        model.py:FeedForward.predict)."""
        data = self._init_iter(X, None, is_train=False)
        if reset:
            data.reset()
        if self._module is None or not self._module.binded:
            mod = self._make_module(data)
            mod.bind(data_shapes=data.provide_data,
                     label_shapes=data.provide_label, for_training=False)
            if self.arg_params is not None:
                mod.set_params(self.arg_params, self.aux_params or {},
                               allow_missing=False)
            else:
                mod.init_params(self.initializer)
        outs = self._module.predict(data, num_batch=num_batch)
        if isinstance(outs, (list, tuple)):
            res = [o.asnumpy() for o in outs]
        else:
            res = outs.asnumpy()
        if return_data:
            return res, data
        return res

    def score(self, X, eval_metric="acc", num_batch=None,
              batch_end_callback=None, reset=True):
        """Evaluate (reference model.py:FeedForward.score)."""
        from . import metric as metric_mod
        data = self._init_iter(X, None, is_train=False)
        if reset:
            data.reset()
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        assert self._module is not None, "call fit before score"
        res = self._module.score(data, eval_metric, num_batch=num_batch)
        return dict(res).get(eval_metric.name, list(dict(res).values())[0])

    def save(self, prefix, epoch=None):
        """save_checkpoint with this model's params (reference :340)."""
        if epoch is None:
            epoch = self.num_epoch or 0
        save_checkpoint(prefix, epoch, self.symbol, self.arg_params or {},
                        self.aux_params or {})

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        """Load a saved FeedForward (reference model.py:FeedForward.load)."""
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch,
                           **kwargs)

    @staticmethod
    def create(symbol, X, y=None, ctx=None, num_epoch=None, epoch_size=None,
               optimizer="sgd", initializer=None, eval_data=None,
               eval_metric="acc", epoch_end_callback=None,
               batch_end_callback=None, kvstore="local", logger=None,
               work_load_list=None, eval_end_callback=None,
               eval_batch_end_callback=None, **kwargs):
        """Construct + fit in one call (reference model.py:FeedForward
        .create)."""
        if initializer is None:
            from .initializer import Uniform
            initializer = Uniform(0.01)
        model = FeedForward(symbol, ctx=ctx, num_epoch=num_epoch,
                            epoch_size=epoch_size, optimizer=optimizer,
                            initializer=initializer, **kwargs)
        model.fit(X, y, eval_data=eval_data, eval_metric=eval_metric,
                  epoch_end_callback=epoch_end_callback,
                  batch_end_callback=batch_end_callback, kvstore=kvstore,
                  logger=logger, work_load_list=work_load_list,
                  eval_end_callback=eval_end_callback,
                  eval_batch_end_callback=eval_batch_end_callback)
        return model
