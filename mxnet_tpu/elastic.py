"""Elastic job membership: world size as a per-restart decision.

The launcher (``tools/launch.py --elastic``) may drop a permanently
failing rank from the next restart attempt and re-admit it later, so a
worker can come up in a job whose world size differs from the one that
wrote its checkpoints.  This module is the worker-side half of that
contract:

- **membership**: the env-described identity of this worker inside the
  current attempt — contiguous ``rank`` in a ``world_size``-process job,
  plus the stable ``slot`` id the launcher tracks across evictions
  (``MXTPU_WORKER_SLOT``; a re-ranked survivor keeps its slot while its
  rank shifts down) and the restart ``attempt`` counter.
- **transition accounting**: ``note_membership`` feeds the
  ``elastic.world_size`` gauge and the ``elastic.transitions`` counter
  (a transition = the world size this process observes differs from the
  previous observation, including the previous *attempt*'s world via
  ``MXTPU_PREV_WORLD_SIZE`` exported by the launcher).  The flight
  recorder's crash postmortem carries :func:`snapshot` so "what did the
  job look like when it died" is always in the record.
- **deterministic reshard**: :func:`shard_for_epoch` partitions an
  epoch's sample indices over the *current* world.  The permutation is
  seeded by the epoch alone — never by the world size — so the union of
  all ranks' shards is every sample exactly once for ANY world size,
  and a job resumed at N−1 (or re-grown to N) mid-run replays the epoch
  with full, non-overlapping coverage.  Params/opt-state are replicated
  in the data-parallel path, so this re-partition IS the whole resume
  story; sharded-update regimes (ZeRO-1, arXiv 2004.13336) will layer a
  state reshard on top of the same membership signal.

Everything here reads plain env/process state — no jax import — so the
checkpoint layer and the launcher-side tests can use it before (or
without) a backend.
"""
from __future__ import annotations

import os
import threading

import numpy as _np

__all__ = ["membership", "note_membership", "snapshot", "shard_for_epoch",
           "transitions"]

_lock = threading.Lock()
_last_world = None      # last world size this process observed
_last_rank = None       # rank passed with that observation (live mesh
                        # state — authoritative over env when they skew)
_transitions = 0        # world-size changes observed by this process


def _env_int(name, default=None):
    v = os.environ.get(name)
    if v is None or v == "":
        return default
    try:
        return int(v)
    except ValueError:
        return default


def membership():
    """The launch-contract view of this worker, re-read from env on
    every call (a restarted process sees the new attempt's exports; an
    in-process world change re-reads them too).  Keys:

    - ``world_size`` / ``rank``: the contiguous per-attempt contract
      (``MXTPU_NUM_WORKERS`` / ``MXTPU_WORKER_RANK``; 1 / 0 standalone).
    - ``slot``: launcher-stable worker identity across re-rankings
      (``MXTPU_WORKER_SLOT``; equals rank when the launcher predates
      elastic mode or the job never changed size).
    - ``attempt``: restart attempt (``MXTPU_RESTART_ATTEMPT``, 0 based).
    - ``prev_world_size``: the previous attempt's world size as exported
      by the launcher (None on attempt 0 / non-elastic launchers).
    - ``coordinator``: the jax.distributed coordinator address, if any.
    """
    world = _env_int("MXTPU_NUM_WORKERS", 1) or 1
    rank = _env_int("MXTPU_WORKER_RANK", 0) or 0
    return {
        "world_size": world,
        "rank": rank,
        "slot": _env_int("MXTPU_WORKER_SLOT", rank),
        "attempt": _env_int("MXTPU_RESTART_ATTEMPT", 0) or 0,
        "prev_world_size": _env_int("MXTPU_PREV_WORLD_SIZE"),
        "coordinator": os.environ.get("MXTPU_COORDINATOR"),
    }


def note_membership(world_size=None, rank=None):
    """Record the membership this process is running under (called from
    distributed bring-up and from the KVStore's world-change check).
    Sets the ``elastic.world_size`` gauge; increments
    ``elastic.transitions`` when the observed world size differs from
    the last observation — seeding the "last" value from
    ``MXTPU_PREV_WORLD_SIZE`` so the first observation of a freshly
    restarted process counts the cross-attempt reshard too."""
    global _last_world, _last_rank, _transitions
    mem = membership()
    if world_size is None:
        world_size = mem["world_size"]
    if rank is None:
        rank = mem["rank"]
    changed = False
    with _lock:
        prev = _last_world
        if prev is None:
            prev = mem["prev_world_size"]
        if prev is not None and prev != world_size:
            _transitions += 1
            changed = True
        _last_world = world_size
        _last_rank = rank
    try:
        from . import telemetry as _telemetry
        _telemetry.gauge("elastic.world_size").set(world_size)
        if changed:
            _telemetry.counter("elastic.transitions").inc()
    except Exception:
        pass  # interpreter teardown; membership note must never raise
    return changed


def transitions():
    """World-size changes observed by this process (incl. the one
    implied by MXTPU_PREV_WORLD_SIZE at restart)."""
    with _lock:
        return _transitions


def snapshot():
    """Membership block for the crash postmortem / health dumps: the
    current env contract plus this process's transition count and the
    last live-mesh observation (``note_membership``'s arguments — the
    authoritative world/rank when the env and the joined mesh skew,
    e.g. a harness re-exported env inside one process)."""
    doc = membership()
    with _lock:
        doc["transitions"] = _transitions
        doc["last_noted_world_size"] = _last_world
        doc["last_noted_rank"] = _last_rank
    return doc


def shard_for_epoch(num_samples, epoch, rank=None, world_size=None,
                    seed=None):
    """Deterministic, world-size-agnostic data shard for one epoch.

    Returns the sample indices rank ``rank`` owns in an epoch of
    ``num_samples`` samples under a ``world_size``-way split (both
    default to the current membership).  Properties the elastic resume
    path depends on:

    - The epoch permutation is seeded by ``(seed, epoch)`` ONLY — two
      jobs at different world sizes draw the *same* permutation, so the
      shards are a contiguous partition of one fixed order: across all
      ranks every sample appears exactly once, for any world size.  A
      mid-epoch reshard replays the epoch from its checkpoint with full
      coverage and no duplicates.
    - Epoch-seeded, not constant: consecutive epochs see different
      orders (the usual shuffle), and a restart replays the interrupted
      epoch's order bit-identically.
    - Remainder samples go to the lowest ranks (rank < num_samples %
      world_size owns one extra) — still a partition, just uneven by at
      most one.

    ``seed`` defaults to ``MXTPU_DATA_SEED`` (0 when unset).
    """
    mem = None
    if rank is None or world_size is None:
        mem = membership()
    if rank is None:
        rank = mem["rank"]
    if world_size is None:
        world_size = mem["world_size"]
    if world_size < 1:
        raise ValueError("world_size must be >= 1, got %d" % world_size)
    if not 0 <= rank < world_size:
        raise ValueError("rank %d outside world of %d" % (rank, world_size))
    if seed is None:
        seed = _env_int("MXTPU_DATA_SEED", 0) or 0
    # RandomState (MT19937) is stable across numpy versions by contract;
    # mixing epoch into the seed keeps one draw per epoch, order-free
    order = _np.random.RandomState(
        (int(seed) * 1_000_003 + int(epoch)) % (2 ** 32)).permutation(
            int(num_samples))
    base, extra = divmod(int(num_samples), int(world_size))
    start = rank * base + min(rank, extra)
    stop = start + base + (1 if rank < extra else 0)
    return order[start:stop]
