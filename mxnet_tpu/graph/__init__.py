"""Graph-level rewrite pipeline over the symbol DAG (ROADMAP item 3).

``optimize(symbol)`` runs the env-configured pass pipeline
(``MXTPU_GRAPH_PASSES`` — default ``fuse,fold,cse,dce``; ``0``/``off``
disables) between ``simple_bind`` and trace→jit and returns the
rewritten symbol plus a structured pass report.  See
:mod:`mxnet_tpu.graph.passes` for the pass catalogue and
:mod:`mxnet_tpu.graph.graph` for the IR.
"""
from .graph import Graph, make_eval_fn, rebuild, topo_from_heads  # noqa
from .passes import (  # noqa
    PIPELINE_VERSION, enabled, last_report, list_passes, optimize,
    pipeline_config, pipeline_fingerprint, register_pass, run_pass)

__all__ = ["Graph", "make_eval_fn", "rebuild", "topo_from_heads",
           "PIPELINE_VERSION", "enabled", "last_report", "list_passes",
           "optimize", "pipeline_config", "pipeline_fingerprint",
           "register_pass", "run_pass"]
