"""The graph rewrite pass pipeline (ROADMAP item 3).

NNVM-style graph-level optimization as a first-class, inspectable
compiler stage between ``simple_bind`` and trace→jit, in the spirit of
TVM (arXiv 1802.04799) and Relay (arXiv 1810.00952): each pass is a pure
``Graph -> Graph`` function registered in an ordered, env-configurable
pipeline.  Built-in passes, in default order:

- ``fuse`` — pattern fusion: ``Convolution→BatchNorm(→Activation)``
  (pre-scaled weights in eval, the exact composition in train),
  ``FullyConnected→Activation`` (transpose-free dot), and
  ``elemwise_add→LayerNorm`` (the transformer sublayer epilogue, a
  Pallas kernel on TPU) — ops/fused.py.
- ``fold`` — constant folding: parameter-free subgraphs (attention
  masks, position ids, shape constants) evaluate ONCE here and become
  ``_graph_constant`` literals; RNG-consuming, train-dependent and
  aux-mutating ops never fold.
- ``cse`` — common-subexpression elimination over the topo order (same
  op, same canonical params, same inputs; RNG/stateful ops excluded).
- ``dce`` — dead-node elimination: drops nodes unreachable from the
  heads (the orphans fuse/cse leave behind).

Configuration: ``MXTPU_GRAPH_PASSES`` — comma-separated pass names, in
run order; unset/empty means the default pipeline; ``0``/``off``/
``none`` disables rewriting entirely.  The pipeline version + enabled
set are part of the AOT cache fingerprint (aot_cache.fingerprint), so a
rewritten graph can never replay a pre-rewrite executable.

Every :func:`optimize` call produces a structured pass report — nodes
before/after, rewrites by pattern, per-pass wall time — published on
``graph.*`` telemetry gauges and stored as AOT entry metadata next to
the ``xla.cost.*`` attribution (executor._analyze_compiled).
"""
from __future__ import annotations

import logging
import os
import time

import numpy as _np

from ..base import MXNetError
from ..ops.fused import ACT_FUSABLE, ConstPayload
from ..ops.registry import _hashable, get_op
from ..symbol.symbol import _SymNode
from .graph import Graph, _clone_node, rebuild

__all__ = ["PIPELINE_VERSION", "register_pass", "list_passes",
           "pipeline_config", "enabled", "pipeline_fingerprint",
           "optimize", "run_pass", "last_report"]

#: bump when pass semantics change in a way that alters emitted graphs —
#: part of the AOT cache fingerprint
PIPELINE_VERSION = 1

_DEFAULT_PIPELINE = ("fuse", "fold", "cse", "dce")
_OFF_VALUES = ("0", "off", "none", "false")

_PASSES = {}
_warned_unknown = set()

#: the most recent optimize() report (graph_probe / debugging)
_last_report = None


def register_pass(name):
    """Register ``fn(graph) -> (graph, stats)`` as pass ``name`` — the
    extension point future kernels (MoE dispatch, quantized matmul)
    plug their patterns into."""
    def _reg(fn):
        _PASSES[name] = fn
        return fn
    return _reg


def list_passes():
    return sorted(_PASSES)


def pipeline_config():
    """The enabled pass names, in run order, from MXTPU_GRAPH_PASSES."""
    raw = os.environ.get("MXTPU_GRAPH_PASSES")
    if raw is None or not raw.strip():
        return _DEFAULT_PIPELINE
    if raw.strip().lower() in _OFF_VALUES:
        return ()
    names = []
    for name in raw.split(","):
        name = name.strip()
        if not name:
            continue
        if name not in _PASSES:
            if name not in _warned_unknown:
                _warned_unknown.add(name)
                logging.warning(
                    "mxnet_tpu.graph: unknown pass %r in "
                    "MXTPU_GRAPH_PASSES (have: %s) — skipping it",
                    name, ", ".join(list_passes()))
            continue
        names.append(name)
    return tuple(names)


def enabled():
    return bool(pipeline_config())


def pipeline_fingerprint():
    """Identity text for the AOT cache: version + enabled-pass set +
    every env knob that changes what the passes emit (the fold size cap
    decides which subgraphs become literals; MXTPU_LN_PALLAS decides
    the fused LN lowering).  A graph rewritten differently is a
    different program — stale pre-rewrite entries must miss, never
    execute."""
    return "graphpass-v%d:%s:fold%d:lnp%s" % (
        PIPELINE_VERSION, ",".join(pipeline_config()),
        _fold_max_bytes(), os.environ.get("MXTPU_LN_PALLAS", ""))


def last_report():
    return _last_report


# ---------------------------------------------------------------------------
# pass: pattern fusion
# ---------------------------------------------------------------------------

def _single_consumer(consumers, node):
    """The (consumer, slot) of ``node`` iff it has exactly one use and
    is not a head, else None."""
    uses = consumers.get(id(node), [])
    if len(uses) != 1 or uses[0][0] is None:
        return None
    return uses[0]


def _opname(node):
    return node.op.name if node.op is not None else "null"


def _merge_attrs(tail, members):
    attrs = {}
    for m in members:
        attrs.update(m.attrs or {})
    attrs.update(tail.attrs or {})
    attrs["__fused_ops__"] = "+".join(_opname(m) for m in members)
    attrs["__fused_names__"] = ",".join(m.name for m in members)
    return attrs


def _match_conv_bn_act(node, consumers):
    """``node`` is the chain tail.  Returns (conv, bn, act_type, members)
    or None.  The interior links must be single-consumer non-heads; BN
    must be the plain 1-output channel-axis form."""
    act_type = "linear"
    bn = node
    members = [node]
    if _opname(node) == "Activation":
        act_type = node.op.canon_params(node.params).get("act_type", "relu")
        if act_type not in ACT_FUSABLE:
            return None
        bn_entry = node.inputs[0]
        bn = bn_entry[0]
        if bn_entry[1] != 0 or _opname(bn) != "BatchNorm" or \
                _single_consumer(consumers, bn) is None:
            return None
        members = [bn, node]
    elif _opname(node) != "BatchNorm":
        return None
    bnp = bn.op.canon_params(bn.params)
    if bnp.get("output_mean_var") or int(bnp.get("axis", 1)) != 1:
        return None
    conv_entry = bn.inputs[0]
    conv = conv_entry[0]
    if conv_entry[1] != 0 or _opname(conv) != "Convolution" or \
            _single_consumer(consumers, conv) is None:
        return None
    convp = conv.op.canon_params(conv.params)
    if convp.get("layout") not in (None, "NCHW", "NCW", "NCDHW"):
        return None
    return conv, bn, act_type, [conv] + members


def _fuse_conv_bn_act(node, remap, consumers, stats):
    m = _match_conv_bn_act(node, consumers)
    if m is None:
        return None
    conv, bn, act_type, members = m
    convp = conv.op.canon_params(conv.params)
    bnp = bn.op.canon_params(bn.params)
    params = {k: convp.get(k) for k in
              ("kernel", "stride", "dilate", "pad", "num_filter",
               "num_group", "no_bias", "workspace")}
    params.update({k: bnp.get(k) for k in
                   ("eps", "momentum", "fix_gamma", "use_global_stats")})
    params["act_type"] = act_type
    # inputs: conv's data/weight(/bias), then bn's gamma/beta + aux
    inputs = [remap(e) for e in conv.inputs]
    inputs += [remap(e) for e in bn.inputs[1:]]  # gamma, beta, mm, mv
    stats["conv_bn_act"] = stats.get("conv_bn_act", 0) + 1
    return _SymNode(get_op("_fused_conv_bn_act"), node.name, params,
                    inputs, attrs=_merge_attrs(node, members))


def _dense_params(fc, act_type):
    fcp = fc.op.canon_params(fc.params)
    return {"num_hidden": fcp.get("num_hidden"),
            "no_bias": fcp.get("no_bias", False),
            "flatten": fcp.get("flatten", True),
            "act_type": act_type}


def _fuse_dense_act(node, remap, consumers, stats):
    if _opname(node) != "Activation":
        return None
    act_type = node.op.canon_params(node.params).get("act_type", "relu")
    if act_type not in ACT_FUSABLE:
        return None
    fc_entry = node.inputs[0]
    fc = fc_entry[0]
    if fc_entry[1] != 0 or _opname(fc) != "FullyConnected" or \
            _single_consumer(consumers, fc) is None:
        return None
    inputs = [remap(e) for e in fc.inputs]
    stats["dense_act"] = stats.get("dense_act", 0) + 1
    return _SymNode(get_op("_fused_dense_act"), node.name,
                    _dense_params(fc, act_type), inputs,
                    attrs=_merge_attrs(node, [fc, node]))


def _fuse_dense_bare(node, remap, consumers, stats):
    """A FullyConnected with no fusable activation still rewrites to the
    fused dense op with act_type='linear': the matmul contracts with
    dot_general directly, so the per-call weight transpose
    (``matmul(data, w.T)``) disappears from the lowered program —
    bit-identical output (same contraction, no reassociation)."""
    if _opname(node) != "FullyConnected":
        return None
    inputs = [remap(e) for e in node.inputs]
    stats["dense_bare"] = stats.get("dense_bare", 0) + 1
    return _SymNode(get_op("_fused_dense_act"), node.name,
                    _dense_params(node, "linear"), inputs,
                    attrs=_merge_attrs(node, [node]))


#: equal-shape adds only: a broadcast_add residual (e.g. a positional
#: embedding) would hand the Pallas kernel mismatched lhs/rhs shapes
_RESIDUAL_ADDS = ("elemwise_add", "_grad_add", "_Plus", "_plus")


def _fuse_layer_norm_residual(node, remap, consumers, stats):
    if _opname(node) != "LayerNorm":
        return None
    add_entry = node.inputs[0]
    add = add_entry[0]
    if add_entry[1] != 0 or _opname(add) not in _RESIDUAL_ADDS or \
            add.is_var or _single_consumer(consumers, add) is None:
        return None
    lnp = node.op.canon_params(node.params)
    params = {"axis": lnp.get("axis", -1), "eps": lnp.get("eps", 1e-5)}
    inputs = [remap(add.inputs[0]), remap(add.inputs[1])]
    inputs += [remap(e) for e in node.inputs[1:]]  # gamma, beta
    stats["layer_norm_residual"] = stats.get("layer_norm_residual", 0) + 1
    return _SymNode(get_op("_fused_layer_norm_residual"), node.name,
                    params, inputs, attrs=_merge_attrs(node, [add, node]))


def _fuse_batch_dot(node, remap, consumers, stats):
    """batch_dot with a transpose flag → transpose-free dot_general
    (same contraction, bit-identical; the swapaxes disappears from the
    lowered program).  Flag-free batch_dot already lowers to one
    dot_general and stays put."""
    if _opname(node) != "batch_dot":
        return None
    p = node.op.canon_params(node.params)
    if not (p.get("transpose_a") or p.get("transpose_b")):
        return None
    params = {"transpose_a": bool(p.get("transpose_a")),
              "transpose_b": bool(p.get("transpose_b"))}
    inputs = [remap(e) for e in node.inputs]
    stats["batch_dot"] = stats.get("batch_dot", 0) + 1
    return _SymNode(get_op("_fused_batch_dot"), node.name, params,
                    inputs, attrs=_merge_attrs(node, [node]))


_FUSE_MATCHERS = (_fuse_conv_bn_act, _fuse_dense_act,
                  _fuse_layer_norm_residual, _fuse_dense_bare,
                  _fuse_batch_dot)


@register_pass("fuse")
def fuse_patterns(graph):
    """Collapse known multi-op patterns into fused-region nodes.  Each
    match fires at the chain's TAIL; interiors it absorbs become
    unreachable (DCE removes them).  A BatchNorm whose only consumer is
    a fusable Activation defers to the longer conv→bn→act match."""
    consumers = graph.consumers()
    stats = {}

    def deferred_to_act(node):
        # bn/fc tail whose single consumer is a fusable act: let the
        # act tail claim the longer chain
        if _opname(node) not in ("BatchNorm", "FullyConnected"):
            return False
        use = _single_consumer(consumers, node)
        if use is None or use[1] != 0:
            return False
        consumer = use[0]
        if _opname(consumer) != "Activation":
            return False
        act = consumer.op.canon_params(consumer.params).get("act_type",
                                                            "relu")
        return act in ACT_FUSABLE

    def make(node, remap):
        if node.is_var or deferred_to_act(node):
            return None
        for matcher in _FUSE_MATCHERS:
            fused = matcher(node, remap, consumers, stats)
            if fused is not None:
                return fused
        return None

    return rebuild(graph, make), stats


# ---------------------------------------------------------------------------
# pass: constant folding
# ---------------------------------------------------------------------------

def _fold_max_bytes():
    return int(os.environ.get("MXTPU_GRAPH_FOLD_MAX_BYTES", 1 << 22))


@register_pass("fold")
def fold_constants(graph):
    """Evaluate parameter-free subgraphs once, at bind, and splice the
    results in as ``_graph_constant`` literals.  A node is foldable when
    it is not a variable, consumes no randomness (``needs_rng``), has no
    train-dependent behaviour (``takes_train``), mutates no auxiliary
    state (``mutate_aux``), and every input is foldable — RNG and
    side-effecting ops therefore never move, and neither does anything
    downstream of a variable.  Results larger than
    MXTPU_GRAPH_FOLD_MAX_BYTES stay unfolded (a literal that big belongs
    in HBM as a computed tensor, not in the program text)."""
    nodes = graph.nodes
    foldable = {}
    for node in nodes:
        if node.is_var or node.op is None:
            foldable[id(node)] = False
            continue
        foldable[id(node)] = (
            not node.op.needs_rng and not node.op.takes_train and
            not node.op.mutate_aux and node.op.name != "_graph_constant" and
            all(foldable.get(id(inp), False) for inp, _ in node.inputs))
    if not any(foldable.values()):
        return graph, {"folded": 0, "constants": 0}

    # boundary entries: (const node, out idx) consumed by a NON-const
    # node or exported as a head — these materialize as literals
    boundary = set()
    for node in nodes:
        if node.is_var or foldable[id(node)]:
            continue
        for inp, idx in node.inputs:
            if foldable.get(id(inp), False):
                boundary.add((id(inp), idx))
    for n, i in graph.heads:
        if foldable.get(id(n), False):
            boundary.add((id(n), i))
    if not boundary:
        return graph, {"folded": 0, "constants": 0}

    # evaluate the const region eagerly, once, node by node
    values = {}

    def value_of(node):
        if id(node) in values:
            return values[id(node)]
        inputs = [value_of(inp)[idx] for inp, idx in node.inputs]
        out = node.op.fn(*inputs, **node.op.canon_params(dict(node.params)))
        flat = list(out) if isinstance(out, (tuple, list)) else [out]
        values[id(node)] = flat
        return flat

    const_nodes = {}   # (id(producer), idx) -> _graph_constant node
    cap = _fold_max_bytes()
    for node in nodes:
        for idx in range(0 if node.is_var else node.num_outputs()):
            if (id(node), idx) not in boundary:
                continue
            try:
                val = _np.asarray(value_of(node)[idx])
            except Exception as e:  # a fold that can't evaluate stays put
                logging.warning("mxnet_tpu.graph: constant fold of %s "
                                "failed (%s: %s); leaving it in the graph",
                                node.name, type(e).__name__, e)
                continue
            if val.nbytes > cap:
                continue
            name = node.name if idx == 0 else "%s_out%d" % (node.name, idx)
            const_nodes[(id(node), idx)] = _SymNode(
                get_op("_graph_constant"), "%s_folded" % name,
                {"value": ConstPayload(val)}, [],
                attrs=dict(node.attrs or {}))

    if not const_nodes:
        return graph, {"folded": 0, "constants": 0}

    # splice: walk the topo order redirecting every boundary entry at
    # its literal; const nodes (no inputs) go right after their producer
    # so the node list stays topologically sorted
    new_of = {}

    def map_entry(entry):
        old, idx = entry
        c = const_nodes.get((id(old), idx))
        return (c, 0) if c is not None else (new_of[id(old)], idx)

    new_nodes = []
    for node in nodes:
        if node.is_var:
            new_of[id(node)] = node
            new_nodes.append(node)
        else:
            new_inputs = [map_entry(e) for e in node.inputs]
            if all(n is o[0]
                   for (n, _), o in zip(new_inputs, node.inputs)):
                node2 = node
            else:
                node2 = _clone_node(node, new_inputs)
            new_of[id(node)] = node2
            new_nodes.append(node2)
            for idx in range(node.num_outputs()):
                c = const_nodes.get((id(node), idx))
                if c is not None:
                    new_nodes.append(c)
    heads = [map_entry(h) for h in graph.heads]
    out = Graph(new_nodes, heads)
    # honest accounting: "folded" counts only region ops the splice
    # actually disconnected from the heads — a boundary that stayed put
    # (over the size cap, failed eval) keeps its subtree live and those
    # nodes must not be reported as removed
    live = out.reachable()
    n_folded = sum(1 for node in nodes
                   if not node.is_var and foldable[id(node)]
                   and id(new_of[id(node)]) not in live)
    return out, {"folded": n_folded, "constants": len(const_nodes)}


# ---------------------------------------------------------------------------
# pass: common-subexpression elimination
# ---------------------------------------------------------------------------

@register_pass("cse")
def eliminate_common_subexpr(graph):
    """Merge structurally identical nodes: same op, same canonical
    params, same (already-merged) inputs.  RNG-consuming and
    aux-mutating nodes never merge (two Dropouts with identical inputs
    are two independent draws; two BatchNorms own distinct moving
    stats).  Variables never merge — their NAME is their identity."""
    rep = {}       # id(node) -> representative node (in the new graph)
    by_key = {}
    merged = 0
    new_nodes = []
    for node in graph.nodes:
        if node.is_var:
            rep[id(node)] = node
            new_nodes.append(node)
            continue
        new_inputs = [(rep[id(i)], idx) for i, idx in node.inputs]
        changed = any(n is not o[0]
                      for (n, _), o in zip(new_inputs, node.inputs))
        if node.op.needs_rng or node.op.mutate_aux:
            key = None
        else:
            try:
                key = (id(node.op),
                       _hashable(node.op.canon_params(dict(node.params))),
                       tuple((id(n), idx) for n, idx in new_inputs))
            except TypeError:
                key = None
        if key is not None and key in by_key:
            rep[id(node)] = by_key[key]
            merged += 1
            continue
        if changed:
            node2 = _clone_node(node, new_inputs)
        else:
            node2 = node
        rep[id(node)] = node2
        if key is not None:
            by_key[key] = node2
        new_nodes.append(node2)
    heads = [(rep[id(n)], i) for n, i in graph.heads]
    return Graph(new_nodes, heads), {"merged": merged}


# ---------------------------------------------------------------------------
# pass: dead-node elimination
# ---------------------------------------------------------------------------

@register_pass("dce")
def eliminate_dead_nodes(graph):
    """Drop nodes unreachable from the heads — ONLY those (the
    equivalence law tests pin this): everything contributing to any
    head survives, including aux-mutating ops feeding nothing else."""
    live = graph.reachable()
    kept = [n for n in graph.nodes if id(n) in live]
    removed = len(graph.nodes) - len(kept)
    return Graph(kept, graph.heads), {"removed": removed}


# ---------------------------------------------------------------------------
# the pipeline
# ---------------------------------------------------------------------------

def run_pass(name, graph):
    """Run one registered pass; returns (graph, stats)."""
    fn = _PASSES.get(name)
    if fn is None:
        raise MXNetError("unknown graph pass %r (have: %s)"
                         % (name, ", ".join(list_passes())))
    return fn(graph)


def optimize(symbol, passes=None):
    """Run the configured pipeline over ``symbol``'s graph.  Returns
    ``(rewritten_symbol, report)``; with the pipeline disabled (or no
    rewrites fired) the original symbol comes back unchanged.  The
    report lands on ``graph.*`` telemetry gauges and rides into AOT
    entry metadata next to the ``xla.cost.*`` attribution."""
    global _last_report
    from .. import telemetry as _telemetry

    names = tuple(passes) if passes is not None else pipeline_config()
    g = Graph.from_symbol(symbol)
    before = len(g)
    before_ops = g.num_ops()
    report = {"version": PIPELINE_VERSION, "pipeline": list(names),
              "nodes_before": before, "ops_before": before_ops,
              "passes": [], "rewrites": {}}
    t_total = time.perf_counter()
    changed = False
    for name in names:
        fn = _PASSES.get(name)
        if fn is None:
            raise MXNetError("unknown graph pass %r" % name)
        n0 = len(g)
        t0 = time.perf_counter()
        g, stats = fn(g)
        ms = (time.perf_counter() - t0) * 1e3
        entry = {"name": name, "nodes_before": n0, "nodes_after": len(g),
                 "ms": round(ms, 3)}
        entry.update(stats)
        report["passes"].append(entry)
        for k, v in stats.items():
            if isinstance(v, int) and v:
                report["rewrites"][k] = report["rewrites"].get(k, 0) + v
                changed = True
    report["nodes_after"] = len(g)
    report["ops_after"] = g.num_ops()
    report["total_ms"] = round((time.perf_counter() - t_total) * 1e3, 3)
    _telemetry.gauge("graph.nodes_before").set(before)
    _telemetry.gauge("graph.nodes_after").set(report["nodes_after"])
    _telemetry.gauge("graph.rewrites").set(
        sum(report["rewrites"].values()))
    _telemetry.gauge("graph.pass_ms").set(report["total_ms"])
    _telemetry.counter("graph.optimize_calls").inc()
    _last_report = report
    if not changed:
        # nothing fired: hand back the ORIGINAL symbol so executors can
        # share plans/identity with the unrewritten path
        return symbol, report
    return g.to_symbol(), report
