"""Graph IR for the pre-lowering rewrite pipeline.

The NNVM-style graph the reference ran its optimization passes over
(src/nnvm/, TVM arXiv 1802.04799 / Relay arXiv 1810.00952) — here a thin,
explicit view of the ``_SymNode`` DAG a :class:`~mxnet_tpu.symbol.Symbol`
denotes.  A :class:`Graph` is just ``(nodes, heads)``:

- ``nodes`` — an ordered node list, topologically sorted.  Unlike
  ``Symbol._topo_nodes()`` it MAY contain nodes that are no longer
  reachable from the heads (pattern fusion and CSE orphan the interiors
  they replace); the DCE pass is what drops them, so every pass's
  before/after node counts in the report are honest Graph-level numbers.
- ``heads`` — the output entries, ``[(node, out_index), ...]``.

Passes are pure ``Graph -> Graph`` functions (mxnet_tpu.graph.passes):
they never mutate the input graph's op nodes — :func:`rebuild` walks the
topo order and clones exactly the nodes whose inputs changed (variables
and untouched subgraphs are shared by identity, which is safe because
nothing downstream writes through them).
"""
from __future__ import annotations

from ..base import MXNetError
from ..symbol.symbol import Symbol, _SymNode

__all__ = ["Graph", "rebuild", "topo_from_heads", "make_eval_fn"]


def topo_from_heads(heads):
    """Topological order of every node reachable from ``heads``."""
    seen = set()
    order = []

    def visit(node):
        if id(node) in seen:
            return
        seen.add(id(node))
        for inp, _ in node.inputs:
            visit(inp)
        order.append(node)

    for n, _ in heads:
        visit(n)
    return order


class Graph:
    """The rewrite pipeline's unit of work."""

    __slots__ = ("nodes", "heads")

    def __init__(self, nodes, heads):
        self.nodes = list(nodes)
        self.heads = list(heads)

    @classmethod
    def from_symbol(cls, symbol):
        if not isinstance(symbol, Symbol):
            raise MXNetError("graph passes run over a Symbol, got %r"
                             % type(symbol).__name__)
        heads = list(symbol._outputs)
        return cls(topo_from_heads(heads), heads)

    def to_symbol(self):
        return Symbol(self.heads[0][0], list(self.heads))

    def reachable(self):
        """ids of nodes reachable from the heads."""
        return {id(n) for n in topo_from_heads(self.heads)}

    def consumers(self):
        """id(node) -> list of (consumer_node, input_slot) over
        ``nodes``; head entries appear with consumer ``None``."""
        out = {id(n): [] for n in self.nodes}
        for node in self.nodes:
            if node.is_var:
                continue
            for slot, (inp, _idx) in enumerate(node.inputs):
                out.setdefault(id(inp), []).append((node, slot))
        for n, _i in self.heads:
            out.setdefault(id(n), []).append((None, -1))
        return out

    def num_ops(self):
        return sum(1 for n in self.nodes if not n.is_var)

    def __len__(self):
        return len(self.nodes)


def _clone_node(node, new_inputs):
    return _SymNode(node.op, node.name, dict(node.params), list(new_inputs),
                    attrs=dict(node.attrs), is_var=node.is_var,
                    is_aux_var=node.is_aux_var)


def rebuild(graph, make=None):
    """Walk ``graph.nodes`` in order, remapping each node's inputs onto
    the rebuilt graph.  ``make(node, remap)`` — when given — may return a
    replacement node for ``node`` (its inputs already expressed in the
    NEW graph via ``remap((old_node, idx)) -> (new_node, idx)``); return
    None to keep the node.  Kept nodes are shared when none of their
    inputs changed and cloned otherwise, so the input graph is never
    mutated.  Nodes orphaned by a replacement stay in ``nodes`` (DCE's
    job), but the returned node list stays topologically sorted.
    """
    new_of = {}

    def remap(entry):
        old, idx = entry
        return (new_of[id(old)], idx)

    new_nodes = []
    for node in graph.nodes:
        if node.is_var:
            new_of[id(node)] = node
            new_nodes.append(node)
            continue
        replacement = make(node, remap) if make is not None else None
        if replacement is not None:
            new_of[id(node)] = replacement
            new_nodes.append(replacement)
            continue
        new_inputs = [remap(e) for e in node.inputs]
        if all(n is o[0] for n, o in zip((x for x, _ in new_inputs),
                                         node.inputs)):
            new_of[id(node)] = node
            new_nodes.append(node)
        else:
            clone = _clone_node(node, new_inputs)
            new_of[id(node)] = clone
            new_nodes.append(clone)
    heads = [(new_of[id(n)], i) for n, i in graph.heads]
    return Graph(new_nodes, heads)


def apply_node(node, inputs, rng, index, train):
    """Evaluate ONE op node — the semantics both graph interpreters
    (Executor._build_plan's plan and :func:`make_eval_fn`) must agree
    on, kept in one place: ``_train`` threading for train-dependent
    ops, the per-node RNG fold-in keyed by TOPO INDEX, and the
    visible-outputs / trailing-aux-extras split.  Returns
    ``(vis, extra)``."""
    import jax

    params = dict(node.params)
    if node.op.takes_train:
        params["_train"] = train
    if node.op.needs_rng:
        inputs = list(inputs) + [jax.random.fold_in(rng, index)]
    out = node.op.fn(*inputs, **node.op.canon_params(params))
    flat = list(out) if isinstance(out, (tuple, list)) else [out]
    n_vis = node.op.num_outputs(node.params)
    return flat[:n_vis], flat[n_vis:]


def aux_writebacks(node, extra):
    """``(aux_var_name, new_value)`` pairs for a ``mutate_aux`` node's
    trailing extras — extras correspond 1:1, in order, to the node's
    trailing auxiliary-variable inputs (``_apply_op`` guarantees aux
    slots hold plain Variables)."""
    aux_inputs = [inp for inp, _ in node.inputs if inp.is_aux_var]
    return list(zip((n.name for n in aux_inputs[-len(extra):]), extra))


def make_eval_fn(graph):
    """A pure ``fn(arg_vals, aux_vals, rng, train) -> (outs, new_aux)``
    evaluating the graph node by node — the same contract as the
    executor's plan (Executor._build_plan), minus ctx_group placement
    and monitor taps.  Used by the gluon HybridBlock symbolic lowering
    to run an optimized graph as its CachedOp body.

    RNG-consuming nodes fold the step key with their topo index;
    ``mutate_aux`` extras are returned keyed by the aux variable's name
    (train only), exactly like the executor (shared
    :func:`apply_node` / :func:`aux_writebacks` core)."""
    nodes = topo_from_heads(graph.heads)
    heads = list(graph.heads)

    def eval_fn(arg_vals, aux_vals, rng, train):
        vals = {}
        new_aux = {}
        for i, node in enumerate(nodes):
            if node.is_var:
                vals[id(node)] = [aux_vals[node.name] if node.is_aux_var
                                  else arg_vals[node.name]]
                continue
            inputs = [vals[id(inp)][idx] for inp, idx in node.inputs]
            vis, extra = apply_node(node, inputs, rng, i, train)
            vals[id(node)] = vis
            if node.op.mutate_aux and extra and train:
                new_aux.update(aux_writebacks(node, extra))
        outs = [vals[id(n)][i] for n, i in heads]
        return outs, new_aux

    return eval_fn
