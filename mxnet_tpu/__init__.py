"""mxnet_tpu: a TPU-native deep learning framework with MXNet's capabilities.

Brand-new implementation targeting JAX/XLA/Pallas/pjit — the reference
(Apache MXNet v0.11, /root/reference) defines the capability surface
(NDArray/Symbol/Module/Gluon/KVStore/IO/...), not the architecture.  The
C++ engine/executor/kernels collapse into trace→XLA-compile→async-dispatch;
what this package provides is everything above that line, TPU-first.
"""
from . import base
from .base import MXNetError

# join the launch.py process mesh BEFORE any JAX backend initializes
# (ps-lite bootstrap analogue; no-op without MXTPU_COORDINATOR)
base._maybe_init_distributed()
from .context import Context, current_context, cpu, gpu, tpu, num_gpus
from . import ops
from . import operator  # registers the Custom op before nd/sym populate
from . import ndarray
from . import ndarray as nd
from .ndarray import NDArray
from . import random
from . import random as rnd
from . import autograd
from . import symbol
from . import symbol as sym
from .symbol import Symbol
from . import executor
from .executor import Executor
from .attribute import AttrScope
from . import name
from .name import NameManager, Prefix
from . import test_utils
from . import initializer
from . import initializer as init
from . import optimizer
from . import optimizer as opt
from . import lr_scheduler
from . import metric
from . import callback
from . import io
from . import kvstore
from . import kvstore as kv
from . import elastic
from . import fault
from . import telemetry
from . import watchdog
# workers spawned by tools/launch.py carry MXTPU_HEARTBEAT_DIR: start
# touching the per-rank heartbeat file the launcher's stall monitor
# watches (no-op otherwise)
watchdog._maybe_start_heartbeat()
from . import checkpoint
from .checkpoint import CheckpointManager
from . import model
from . import module
from . import module as mod
from .module import Module
from . import recordio
from . import stream
from . import image
from . import rnn
from . import profiler
from . import monitor
from .monitor import Monitor, StepStatsMonitor
from . import visualization
from . import visualization as viz
from . import gluon
from . import config
from . import precision
from .precision import PrecisionPolicy, LossScaler
from . import predictor
from .predictor import Predictor
from . import serving
from . import plugin
from . import rtc

__version__ = "0.1.0"
