"""Network visualization (mx.viz): print_summary + plot_network.

Port of /root/reference/python/mxnet/visualization.py — a keras-style
text summary (layer, output shape, params, previous layers) and a
graphviz rendering.  Works on any Symbol from this package's graph.
"""
from __future__ import annotations

from .base import MXNetError
from .symbol import Symbol

__all__ = ["print_summary", "plot_network"]


def _node_label(node):
    op = node.op.name if node.op is not None else "null"
    if op == "null":
        return node.name
    p = node.params or {}
    fused = (node.attrs or {}).get("__fused_ops__")
    if fused:
        # fused-region node from the graph rewrite pipeline: a grouped
        # label naming the constituent ops, so rewritten graphs render
        # instead of falling through to an opaque internal op name
        return "%s\n[%s]" % (op.lstrip("_"), fused)
    if op == "_graph_constant":
        v = p.get("value")
        shape = list(getattr(getattr(v, "value", None), "shape", ()))
        return "constant\n%s" % (shape,)
    if op == "Convolution":
        return "Convolution\n%s/%s, %s" % (
            "x".join(str(x) for x in p.get("kernel", ())),
            "x".join(str(x) for x in p.get("stride", (1,))),
            p.get("num_filter", "?"))
    if op == "FullyConnected":
        return "FullyConnected\n%s" % p.get("num_hidden", "?")
    if op == "Pooling":
        return "Pooling\n%s, %s/%s" % (
            p.get("pool_type", "max"),
            "x".join(str(x) for x in p.get("kernel", ())),
            "x".join(str(x) for x in p.get("stride", (1,))))
    if op == "Activation" or op == "LeakyReLU":
        return "%s\n%s" % (op, p.get("act_type", ""))
    return op


def print_summary(symbol, shape=None, line_length=120, positions=None):
    """Print a layer-by-layer summary table (reference
    visualization.py:print_summary)."""
    if not isinstance(symbol, Symbol):
        raise TypeError("symbol must be Symbol")
    shape_dict = None
    if shape is not None:
        arg_shapes, out_shapes, aux_shapes = symbol.infer_shape(**shape)
        shape_dict = dict(zip(symbol.list_arguments(), arg_shapes))
        shape_dict.update(dict(zip(symbol.list_auxiliary_states(),
                                   aux_shapes)))
    positions = positions or [0.44, 0.64, 0.74, 1.0]
    if positions[-1] <= 1:
        positions = [int(line_length * p) for p in positions]
    to_display = ["Layer (type)", "Output Shape", "Param #",
                  "Previous Layer"]

    def print_row(fields, positions):
        line = ""
        for i, field in enumerate(fields):
            line += str(field)
            line = line[:positions[i]]
            line += " " * (positions[i] - len(line))
        print(line)

    print("_" * line_length)
    print_row(to_display, positions)
    print("=" * line_length)

    nodes = symbol._topo_nodes()
    # per-node output shapes via forward inference when shapes given
    out_shape_of = {}
    if shape_dict is not None:
        import jax
        import jax.numpy as jnp

        vals = {}
        for i, node in enumerate(nodes):
            if node.is_var:
                s = shape_dict.get(node.name)
                vals[id(node)] = [jax.ShapeDtypeStruct(s or (), jnp.float32)]
                continue
            inputs = [vals[id(inp)][idx] for inp, idx in node.inputs]
            params = dict(node.params)
            if node.op.takes_train:
                params["_train"] = False
            if node.op.needs_rng:
                inputs.append(jax.ShapeDtypeStruct((2,), jnp.uint32))
            try:
                out = node.op.abstract_eval(*inputs, **params)
            except Exception:
                vals[id(node)] = [jax.ShapeDtypeStruct((), jnp.float32)]
                continue
            flat = list(out) if isinstance(out, (tuple, list)) else [out]
            vals[id(node)] = flat
            out_shape_of[id(node)] = tuple(flat[0].shape)

    total_params = 0
    param_suffixes = ("weight", "bias", "gamma", "beta", "parameters")
    for node in nodes:
        if node.is_var:
            continue
        name = node.name
        op = node.op.name
        out_shape = out_shape_of.get(id(node), "")
        cur_params = 0
        pre_layers = []
        for inp, _ in node.inputs:
            if inp.is_var and inp.name.endswith(param_suffixes):
                if shape_dict is not None and inp.name in shape_dict:
                    n = 1
                    for d in shape_dict[inp.name]:
                        n *= d
                    cur_params += n
            else:
                pre_layers.append(inp.name)
        total_params += cur_params
        fields = ["%s (%s)" % (name, op), str(out_shape), str(cur_params),
                  ", ".join(pre_layers[:3])]
        print_row(fields, positions)
        print("_" * line_length)
    print("Total params: %d" % total_params)
    print("_" * line_length)
    return total_params


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Build a graphviz Digraph of the network (reference
    visualization.py:plot_network).  Requires the graphviz package."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise ImportError("plot_network requires the 'graphviz' package; "
                          "use print_summary for a text view")
    if not isinstance(symbol, Symbol):
        raise TypeError("symbol must be Symbol")
    node_attrs = node_attrs or {}
    node_attr = {"shape": "box", "fixedsize": "false", "style": "filled"}
    node_attr.update(node_attrs)
    dot = Digraph(name=title, format=save_format)
    fill = {"null": "#8dd3c7", "Convolution": "#fb8072",
            "FullyConnected": "#fb8072", "BatchNorm": "#bebada",
            "Activation": "#ffffb3", "Pooling": "#80b1d3",
            "Concat": "#fdb462", "SoftmaxOutput": "#b3de69",
            # graph-pipeline fused regions / folded literals
            "_fused_conv_bn_act": "#fb8072",
            "_fused_dense_act": "#fb8072",
            "_fused_layer_norm_residual": "#bebada",
            "_graph_constant": "#d9d9d9"}
    nodes = symbol._topo_nodes()
    param_suffixes = ("weight", "bias", "gamma", "beta", "parameters",
                      "moving_mean", "moving_var")
    keep = {}
    for node in nodes:
        if node.is_var:
            if hide_weights and node.name.endswith(param_suffixes):
                continue
            keep[id(node)] = node.name
            dot.node(node.name, label=node.name,
                     fillcolor=fill.get("null"), **node_attr)
            continue
        keep[id(node)] = node.name
        dot.node(node.name, label=_node_label(node),
                 fillcolor=fill.get(node.op.name, "#fccde5"), **node_attr)
    for node in nodes:
        if id(node) not in keep or node.is_var:
            continue
        for inp, _ in node.inputs:
            if id(inp) in keep:
                dot.edge(inp.name, node.name)
    return dot
