"""Device context.

TPU-native analogue of the reference ``python/mxnet/context.py`` — a
``Context`` names a logical device (``cpu(0)``, ``tpu(3)``; ``gpu`` is kept as
an alias family so reference scripts run unmodified and maps to the default
accelerator).  A Context resolves lazily to a concrete ``jax.Device``; data
placement uses ``jax.device_put``.

Unlike the reference there is no per-device worker thread or stream — XLA owns
scheduling — so Context is pure placement metadata plus the thread-local
"current context" stack used by ``with mx.tpu(0):``.

Reference: /root/reference/python/mxnet/context.py
"""
from __future__ import annotations

import threading

import jax

__all__ = ["Context", "cpu", "gpu", "tpu", "current_context", "num_gpus", "num_tpus"]


class Context:
    """A logical device context.

    Parameters
    ----------
    device_type : str
        'cpu', 'gpu', 'tpu', or 'cpu_pinned'.  'gpu' is accepted for
        compatibility with reference scripts and resolves to the platform's
        default accelerator (TPU when present).
    device_id : int
        Index into the device list of that platform.
    """

    # dev_type enumeration kept numerically compatible with the reference
    # (include/mxnet/base.h Context::DeviceType) plus kTPU.
    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 4: "tpu"}
    devstr2type = {v: k for k, v in devtype2str.items()}
    _default_ctx = threading.local()

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        else:
            self.device_typeid = Context.devstr2type[device_type]
            self.device_id = device_id
        self._old_ctx = None

    @property
    def device_type(self):
        return Context.devtype2str[self.device_typeid]

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_typeid == other.device_typeid
            and self.device_id == other.device_id
        )

    def __str__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    def __repr__(self):
        return self.__str__()

    def __enter__(self):
        self._old_ctx = getattr(Context._default_ctx, "value", None)
        Context._default_ctx.value = self
        return self

    def __exit__(self, ptype, value, trace):
        Context._default_ctx.value = self._old_ctx

    # -- JAX resolution ----------------------------------------------------
    def jax_device(self):
        """Resolve to a concrete jax.Device.

        'tpu'/'gpu' map onto the accelerator platform when present (falling
        back to CPU so tests run anywhere); 'cpu'/'cpu_pinned' map to host.
        """
        # local_devices only: under multi-process (launch.py / pods) the
        # global list contains peers' non-addressable devices
        devs = jax.local_devices()
        accel = [d for d in devs if d.platform != "cpu"]
        if self.device_type in ("tpu", "gpu"):
            pool = accel or [d for d in devs if d.platform == "cpu"]
        else:
            pool = [d for d in devs if d.platform == "cpu"]
        if not pool:
            pool = devs
        return pool[self.device_id % len(pool)]

    def empty_cache(self):
        """Compatibility no-op (XLA owns the memory pools)."""


def cpu(device_id=0):
    """Return a CPU context."""
    return Context("cpu", device_id)


def gpu(device_id=0):
    """Return an accelerator context (alias; resolves to TPU when present)."""
    return Context("gpu", device_id)


def tpu(device_id=0):
    """Return a TPU context — the native device of this framework."""
    return Context("tpu", device_id)


def num_gpus():
    """Number of accelerator devices visible to this process."""
    return len([d for d in jax.devices() if d.platform != "cpu"])


num_tpus = num_gpus


def current_context():
    """Return the current context (default ``tpu(0)`` — TPU-first)."""
    ctx = getattr(Context._default_ctx, "value", None)
    if ctx is None:
        ctx = Context("tpu", 0)
        Context._default_ctx.value = ctx
    return ctx
