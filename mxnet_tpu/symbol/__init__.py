"""The ``sym`` namespace: Symbol + every registered operator as a creator.

Mirrors /root/reference/python/mxnet/symbol/__init__.py.
"""
from .symbol import (Symbol, Variable, var, Group, load, load_json,
                     populate as _populate)
from . import shape_hints  # noqa: F401 - registers FInferShape analogues

_populate(globals())


def zeros(shape, dtype=None, **kwargs):
    return globals()["_zeros"](shape=shape, dtype=str(dtype or "float32"),
                               **kwargs)


def ones(shape, dtype=None, **kwargs):
    return globals()["_ones"](shape=shape, dtype=str(dtype or "float32"),
                              **kwargs)


def arange(start, stop=None, step=1.0, repeat=1, dtype=None, **kwargs):
    return globals()["_arange"](start=start, stop=stop, step=step,
                                repeat=repeat,
                                dtype=str(dtype or "float32"), **kwargs)


# mx.sym.contrib namespace (mirrors python/mxnet/symbol/contrib.py)
import types as _types

contrib = _types.ModuleType(__name__ + ".contrib",
                            "Contrib operators (experimental).")
for _n, _f in list(globals().items()):
    if _n.startswith("_contrib_"):
        setattr(contrib, _n[len("_contrib_"):], _f)
