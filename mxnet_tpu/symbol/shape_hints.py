"""Parameter shape inference hints.

The reference's per-op FInferShape fills in *unknown input* shapes (conv
weights, BN gammas, ...) from the data shape during simple_bind
(src/executor/infer_graph_attr_pass.cc).  Forward inference here is free
(jax.eval_shape runs the lowering abstractly); these hints supply only the
reverse direction: given known data shapes + op params, the shapes of the
learnable/auxiliary inputs.

Each hint: ``fn(shape_map: {arg_name: shape|None}, params) -> {name: shape}``.
"""
from __future__ import annotations

from ..ops import get_op
from ..ops.rnn import rnn_param_size


def _register(op_name, fn):
    get_op(op_name).shape_hint = fn


def _prod(xs):
    out = 1
    for x in xs:
        out *= x
    return out


def _fc_hint(shapes, params):
    data = shapes.get("data")
    nh = int(params.get("num_hidden", 0))
    out = {}
    if data is not None:
        in_dim = _prod(data[1:]) if params.get("flatten", True) else data[-1]
        out["weight"] = (nh, in_dim)
    out["bias"] = (nh,)
    return out


_register("FullyConnected", _fc_hint)


def _conv_hint(shapes, params):
    data = shapes.get("data")
    nf = int(params.get("num_filter", 0))
    kernel = tuple(params.get("kernel", ()))
    ng = int(params.get("num_group", 1))
    out = {"bias": (nf,)}
    if data is not None:
        out["weight"] = (nf, data[1] // ng) + kernel
    return out


_register("Convolution", _conv_hint)


def _deconv_hint(shapes, params):
    data = shapes.get("data")
    nf = int(params.get("num_filter", 0))
    kernel = tuple(params.get("kernel", ()))
    ng = int(params.get("num_group", 1))
    out = {"bias": (nf,)}
    if data is not None:
        out["weight"] = (data[1], nf // ng) + kernel
    return out


_register("Deconvolution", _deconv_hint)


def _channel_hint(*names):
    def hint(shapes, params):
        data = shapes.get("data")
        if data is None:
            return {}
        axis = int(params.get("axis", 1))
        c = data[axis % len(data)]
        return {n: (c,) for n in names}
    return hint


_register("BatchNorm", _channel_hint("gamma", "beta", "moving_mean",
                                     "moving_var"))
_register("InstanceNorm", _channel_hint("gamma", "beta"))
_register("LeakyReLU", _channel_hint("gamma"))


def _layer_norm_hint(shapes, params):
    data = shapes.get("data")
    if data is None:
        return {}
    axis = int(params.get("axis", -1))
    c = data[axis % len(data)]
    return {"gamma": (c,), "beta": (c,)}


_register("LayerNorm", _layer_norm_hint)


def _fused_conv_bn_hint(shapes, params):
    # conv weight/bias hint + the BN channel vector family on num_filter
    out = _conv_hint(shapes, params)
    nf = int(params.get("num_filter", 0))
    for n in ("gamma", "beta", "moving_mean", "moving_var"):
        out[n] = (nf,)
    return out


_register("_fused_conv_bn_act", _fused_conv_bn_hint)
_register("_fused_dense_act", _fc_hint)


def _fused_ln_res_hint(shapes, params):
    data = shapes.get("lhs") or shapes.get("rhs")
    if data is None:
        return {}
    axis = int(params.get("axis", -1))
    c = data[axis % len(data)]
    return {"gamma": (c,), "beta": (c,)}


_register("_fused_layer_norm_residual", _fused_ln_res_hint)


def _embedding_hint(shapes, params):
    return {"weight": (int(params.get("input_dim", 0)),
                       int(params.get("output_dim", 0)))}


_register("Embedding", _embedding_hint)


def _upsampling_hint(shapes, params):
    # bilinear mode: weight (C, 1, k, k), k = 2s - s%2
    # (reference upsampling-inl.h:189-200)
    if params.get("sample_type") != "bilinear":
        return {}
    data = shapes.get("data")
    if data is None:
        return {}
    s = int(params.get("scale", 1))
    k = 2 * s - s % 2
    return {"weight": (data[1], 1, k, k)}


_register("UpSampling", _upsampling_hint)


def _softmax_output_label_hint(shapes, params):
    # forward-only binds (Predictor) omit the label; its shape follows
    # from data (reference softmax_output-inl.h SoftmaxOutputProp
    # InferShape): (b,) default, (b, x, y, ...) for multi_output,
    # data.shape for preserve_shape
    data = shapes.get("data")
    if data is None:
        return {}
    if params.get("preserve_shape"):
        return {"label": tuple(data)}
    if params.get("multi_output"):
        return {"label": (data[0],) + tuple(data[2:])}
    return {"label": tuple(data[:-1]) if len(data) > 1 else (data[0],)}


_register("SoftmaxOutput", _softmax_output_label_hint)
_register("SVMOutput", lambda shapes, params: (
    {"label": (shapes["data"][0],)} if shapes.get("data") else {}))


def _regression_label_hint(shapes, params):
    data = shapes.get("data")
    return {"label": tuple(data)} if data is not None else {}


for _name in ("LinearRegressionOutput", "LogisticRegressionOutput",
              "MAERegressionOutput"):
    _register(_name, _regression_label_hint)


def _rnn_hint(shapes, params):
    data = shapes.get("data")
    if data is None:
        return {}
    T, N, I = data
    H = int(params.get("state_size", 0))
    L = int(params.get("num_layers", 1))
    bi = bool(params.get("bidirectional", False))
    D = 2 if bi else 1
    mode = params.get("mode", "lstm")
    out = {
        "parameters": (rnn_param_size(L, I, H, bi, mode),),
        "state": (L * D, N, H),
    }
    if mode == "lstm":
        out["state_cell"] = (L * D, N, H)
    return out


_register("RNN", _rnn_hint)
