"""Symbol: the declarative graph API.

TPU-native analogue of the reference Symbol
(/root/reference/python/mxnet/symbol/symbol.py + nnvm's Symbol/Graph).  A
Symbol is an immutable DAG of op nodes over named variables; binding it
traces the graph into a single JAX function and jit-compiles it — the
pipeline that in the reference was simple_bind → GraphExecutor::Init →
nnvm passes (Gradient/PlaceDevice/PlanMemory/AttachOpExecs,
src/executor/graph_executor.cc:1556) collapses into trace→XLA (SURVEY §3.2).

Missing learnable inputs are auto-created as variables with reference
naming (``convolution0_weight``), auxiliary states (BatchNorm moving stats)
are tracked separately, and shape/dtype inference runs the registered
lowerings abstractly via ``jax.eval_shape`` with per-op hints filling
parameter shapes (the analogue of each op's FInferShape).
"""
from __future__ import annotations

import json

import numpy as _np

from ..attribute import AttrScope
from ..base import MXNetError
from ..name import NameManager
from ..ops import get_op
from ..ops.registry import _OP_REGISTRY

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json"]


class _SymNode:
    """One op application in the graph."""

    __slots__ = ("op", "name", "params", "inputs", "attrs", "is_var",
                 "is_aux_var")

    def __init__(self, op, name, params, inputs, attrs=None, is_var=False,
                 is_aux_var=False):
        self.op = op
        self.name = name
        self.params = params or {}
        self.inputs = inputs  # list of (node, out_index)
        self.attrs = dict(attrs or {})
        self.is_var = is_var
        self.is_aux_var = is_aux_var

    def num_outputs(self):
        if self.is_var:
            return 1
        return self.op.num_outputs(self.params)

    def output_names(self):
        if self.is_var:
            return [self.name]
        n = self.num_outputs()
        if n == 1:
            return ["%s_output" % self.name]
        return ["%s_output%d" % (self.name, i) for i in range(n)]


class Symbol:
    """A handle onto one or more outputs of a graph."""

    __slots__ = ("_node", "_indices")

    def __init__(self, node, indices=None):
        self._node = node
        self._indices = indices  # list of (node, idx); None → all of _node

    # -- handle helpers ----------------------------------------------------
    @property
    def _outputs(self):
        """List of (node, out_index) this symbol denotes."""
        if self._indices is not None:
            return self._indices
        return [(self._node, i) for i in range(self._node.num_outputs())]

    @property
    def name(self):
        outs = self._outputs
        if len(outs) == 1:
            return outs[0][0].name
        return None  # grouped symbol, like the reference returns None

    def __repr__(self):
        if self._indices is not None and len(self._indices) > 1:
            return "<Symbol group [%s]>" % ", ".join(
                n.name for n, _ in self._indices)
        return "<Symbol %s>" % (self.name,)

    def __iter__(self):
        return (Symbol(n, [(n, i)]) for n, i in self._outputs)

    def __len__(self):
        return len(self._outputs)

    def __getitem__(self, index):
        outs = self._outputs
        if isinstance(index, str):
            names = self.list_outputs()
            if index in names:
                index = names.index(index)
            else:
                raise ValueError("Cannot find output %s" % index)
        if isinstance(index, slice):
            return Symbol(self._node, outs[index])
        return Symbol(outs[index][0], [outs[index]])

    def __copy__(self):
        return Symbol(self._node, self._indices)

    def __deepcopy__(self, memo):
        return Symbol(self._node, self._indices)

    # -- graph traversal ---------------------------------------------------
    def _topo_nodes(self):
        """Topological order of nodes reachable from this symbol."""
        seen = set()
        order = []

        def visit(node):
            if id(node) in seen:
                return
            seen.add(id(node))
            for inp, _ in node.inputs:
                visit(inp)
            order.append(node)

        for n, _ in self._outputs:
            visit(n)
        return order

    def list_arguments(self):
        return [n.name for n in self._topo_nodes()
                if n.is_var and not n.is_aux_var]

    def list_outputs(self):
        names = []
        for n, i in self._outputs:
            names.append(n.output_names()[i])
        return names

    def list_auxiliary_states(self):
        return [n.name for n in self._topo_nodes() if n.is_aux_var]

    def list_inputs(self):
        return [n.name for n in self._topo_nodes() if n.is_var]

    def get_internals(self):
        outs = []
        for n in self._topo_nodes():
            for i in range(n.num_outputs()):
                outs.append((n, i))
        return Symbol(self._node, outs)

    def get_children(self):
        nodes = []
        for n, _ in self._outputs:
            nodes.extend(n.inputs)
        if not nodes:
            return None
        return Symbol(nodes[0][0], nodes)

    # -- attributes --------------------------------------------------------
    def attr(self, key):
        outs = self._outputs
        if len(outs) == 1:
            return outs[0][0].attrs.get(key)
        return None

    def list_attr(self):
        outs = self._outputs
        if len(outs) == 1:
            return dict(outs[0][0].attrs)
        return {}

    def attr_dict(self):
        out = {}
        for n in self._topo_nodes():
            if n.attrs:
                out[n.name] = dict(n.attrs)
        return out

    def _set_attr(self, **kwargs):
        for n, _ in self._outputs:
            n.attrs.update(kwargs)

    # -- composition -------------------------------------------------------
    def _binary(self, other, op_name, scalar_op, reverse=False):
        if isinstance(other, Symbol):
            a, b = (other, self) if reverse else (self, other)
            return _apply_op(get_op(op_name), None, [a, b], {})
        if isinstance(other, (int, float)):
            return _apply_op(get_op(scalar_op), None, [self],
                             {"scalar": float(other)})
        raise TypeError("type %s not supported" % type(other))

    def __add__(self, other):
        return self._binary(other, "elemwise_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, other):
        return self._binary(other, "elemwise_sub", "_minus_scalar")

    def __rsub__(self, other):
        return _apply_op(get_op("_rminus_scalar"), None, [self],
                         {"scalar": float(other)})

    def __mul__(self, other):
        return self._binary(other, "elemwise_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._binary(other, "elemwise_div", "_div_scalar")

    __div__ = __truediv__

    def __rtruediv__(self, other):
        return _apply_op(get_op("_rdiv_scalar"), None, [self],
                         {"scalar": float(other)})

    __rdiv__ = __rtruediv__

    def __pow__(self, other):
        return self._binary(other, "elemwise_power", "_power_scalar")

    def __neg__(self):
        return _apply_op(get_op("negative"), None, [self], {})

    def __eq__(self, other):
        if isinstance(other, (Symbol, int, float)):
            return self._binary(other, "broadcast_equal", "_equal_scalar")
        return NotImplemented

    def __ne__(self, other):
        if isinstance(other, (Symbol, int, float)):
            return self._binary(other, "broadcast_not_equal",
                                "_not_equal_scalar")
        return NotImplemented

    def __gt__(self, other):
        return self._binary(other, "broadcast_greater", "_greater_scalar")

    def __ge__(self, other):
        return self._binary(other, "broadcast_greater_equal",
                            "_greater_equal_scalar")

    def __lt__(self, other):
        return self._binary(other, "broadcast_lesser", "_lesser_scalar")

    def __le__(self, other):
        return self._binary(other, "broadcast_lesser_equal",
                            "_lesser_equal_scalar")

    __hash__ = object.__hash__

    # convenience op methods mirroring the reference's generated methods
    def reshape(self, shape, **kwargs):
        return _apply_op(get_op("Reshape"), kwargs.get("name"), [self],
                         {"shape": shape})

    def astype(self, dtype):
        return _apply_op(get_op("Cast"), None, [self], {"dtype": str(dtype)})

    # -- shape/type inference ---------------------------------------------
    def infer_shape(self, *args, **kwargs):
        arg_shapes, out_shapes, aux_shapes, _ = self._infer(args, kwargs)
        return arg_shapes, out_shapes, aux_shapes

    def infer_shape_partial(self, *args, **kwargs):
        try:
            return self.infer_shape(*args, **kwargs)
        except MXNetError:
            return None, None, None

    def infer_type(self, *args, **kwargs):
        known = {}
        if args:
            for name, t in zip(self.list_arguments(), args):
                if t is not None:
                    known[name] = _np.dtype(t)
        known.update({k: _np.dtype(v) for k, v in kwargs.items()})
        # types ride the same abstract evaluation as shapes
        try:
            _, _, _, avals = self._infer((), {}, dtype_hint=known,
                                         require_shapes=False)
        except MXNetError:
            return None, None, None
        args_t = [avals["arg:" + n][1] for n in self.list_arguments()]
        outs_t = [avals["out:%d" % i][1] for i in range(len(self._outputs))]
        aux_t = [avals["aux:" + n][1]
                 for n in self.list_auxiliary_states()]
        return args_t, outs_t, aux_t

    def _infer(self, args, kwargs, dtype_hint=None, require_shapes=True):
        """Joint shape+dtype inference over the graph via jax.eval_shape."""
        import jax

        arg_names = self.list_arguments()
        known_shapes = {}
        if args:
            for name, s in zip(arg_names, args):
                if s is not None:
                    known_shapes[name] = tuple(s)
        for k, v in kwargs.items():
            if v is not None:
                known_shapes[k] = tuple(v)
        dtype_hint = dtype_hint or {}

        aval = {}   # id(node) -> list of ShapeDtypeStruct per output
        named = {}

        def node_aval(node):
            if id(node) in aval:
                return aval[id(node)]
            if node.is_var:
                shape = known_shapes.get(node.name)
                dtype = dtype_hint.get(node.name, _np.float32)
                sds = (jax.ShapeDtypeStruct(shape, dtype)
                       if shape is not None else None)
                aval[id(node)] = [sds]
                return aval[id(node)]
            in_avals = []
            unknown = {}
            for i, (inp, idx) in enumerate(node.inputs):
                ia = node_aval(inp)[idx]
                in_avals.append(ia)
                if ia is None:
                    unknown[i] = inp
            if unknown:
                hint = getattr(node.op, "shape_hint", None)
                if hint is None:
                    missing = [n.name for n in unknown.values()]
                    raise MXNetError(
                        "cannot infer shape of %s (inputs of %s); provide "
                        "shapes or register a shape hint" %
                        (missing, node.name))
                names = node.op.arg_names(node.params) + \
                    node.op.aux_names(node.params)
                shape_map = {names[i]: (tuple(a.shape) if a is not None
                                        else None)
                             for i, a in enumerate(in_avals)}
                hinted = hint(shape_map, node.params)
                for i, vnode in unknown.items():
                    hs = hinted.get(names[i])
                    if hs is None:
                        raise MXNetError("shape hint for %s could not infer "
                                         "%s" % (node.name, names[i]))
                    dtype = dtype_hint.get(vnode.name, _np.float32)
                    sds = jax.ShapeDtypeStruct(tuple(hs), dtype)
                    aval[id(vnode)] = [sds]
                    in_avals[i] = sds
            fn_inputs = list(in_avals)
            params = dict(node.params)
            if node.op.takes_train:
                params["_train"] = True
            if node.op.needs_rng:
                fn_inputs.append(
                    jax.ShapeDtypeStruct((2,), _np.uint32))
            out = node.op.abstract_eval(*fn_inputs,
                                        **node.op.canon_params(params))
            outs = list(out) if isinstance(out, (tuple, list)) else [out]
            # visible outputs only (drop trailing aux-update values)
            n_vis = node.op.num_outputs(node.params)
            aval[id(node)] = outs[:n_vis]
            return aval[id(node)]

        for n, i in self._outputs:
            node_aval(n)

        nodes = self._topo_nodes()
        for node in nodes:
            if node.is_var:
                a = aval.get(id(node), [None])[0]
                if a is None and require_shapes:
                    raise MXNetError("cannot fully infer shape of %s"
                                     % node.name)
                key = ("aux:" if node.is_aux_var else "arg:") + node.name
                named[key] = (tuple(a.shape), a.dtype) if a is not None \
                    else (None, None)
        for i, (n, idx) in enumerate(self._outputs):
            a = node_aval(n)[idx]
            named["out:%d" % i] = (tuple(a.shape), a.dtype)

        arg_shapes = [named["arg:" + n][0] for n in arg_names]
        out_shapes = [named["out:%d" % i][0]
                      for i in range(len(self._outputs))]
        aux_shapes = [named["aux:" + n][0]
                      for n in self.list_auxiliary_states()]
        return arg_shapes, out_shapes, aux_shapes, named

    # -- binding -----------------------------------------------------------
    def simple_bind(self, ctx=None, grad_req="write", type_dict=None,
                    group2ctx=None, shared_arg_names=None, shared_exec=None,
                    shared_buffer=None, mesh=None, batch_names=None,
                    partition_rules=None, **kwargs):
        from ..executor import Executor
        from ..context import current_context
        from .. import nd
        ctx = ctx or current_context()
        arg_shapes, _, aux_shapes = self.infer_shape(**kwargs)
        if arg_shapes is None:
            raise MXNetError("cannot infer shapes for simple_bind")
        type_dict = type_dict or {}
        args = {}
        for name, shape in zip(self.list_arguments(), arg_shapes):
            dtype = type_dict.get(name, _np.float32)
            args[name] = nd.zeros(shape, ctx=ctx, dtype=dtype)
        aux = {}
        for name, shape in zip(self.list_auxiliary_states(), aux_shapes):
            aux[name] = nd.zeros(shape, ctx=ctx,
                                 dtype=type_dict.get(name, _np.float32))
        args_grad = None
        if grad_req != "null":
            args_grad = {
                name: nd.zeros(a.shape, ctx=ctx, dtype=a.dtype)
                for name, a in args.items()}
        return Executor(self, ctx, args, args_grad, grad_req, aux,
                        group2ctx=group2ctx, shared_exec=shared_exec,
                        mesh=mesh, batch_names=batch_names,
                        partition_rules=partition_rules)

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None):
        from ..executor import Executor
        from ..context import current_context
        ctx = ctx or current_context()
        arg_names = self.list_arguments()
        if isinstance(args, (list, tuple)):
            args = dict(zip(arg_names, args))
        if isinstance(args_grad, (list, tuple)):
            args_grad = dict(zip(arg_names, args_grad))
        if isinstance(aux_states, (list, tuple)):
            aux_states = dict(zip(self.list_auxiliary_states(), aux_states))
        return Executor(self, ctx, args or {}, args_grad, grad_req,
                        aux_states or {}, group2ctx=group2ctx,
                        shared_exec=shared_exec)

    def eval(self, ctx=None, **kwargs):
        exe = self.bind(ctx, args=kwargs, grad_req="null")
        return exe.forward()

    def grad(self, wrt):  # pragma: no cover - reference-deprecated API
        raise NotImplementedError("use bind().backward()")

    # -- serialization -----------------------------------------------------
    #: tojson schema version.  2 added the stamp itself (graph-pipeline
    #: era): consumers hashing the JSON (Module._fused_setup's AOT
    #: cache_extra) atomically orphan every pre-stamp cache entry, and
    #: future schema changes bump it instead of silently reshaping the
    #: document.  load_json accepts stamped and legacy documents alike.
    JSON_SCHEMA_VERSION = 2

    def tojson(self):
        """nnvm-style JSON (reference format: nodes/arg_nodes/heads)."""
        nodes = self._topo_nodes()
        nid = {id(n): i for i, n in enumerate(nodes)}
        out_nodes = []
        for n in nodes:
            entry = {
                "op": "null" if n.is_var else n.op.name,
                "name": n.name,
                "inputs": [[nid[id(i)], idx, 0] for i, idx in n.inputs],
            }
            attrs = {}
            for k, v in n.params.items():
                attrs[k] = str(v)
            if n.attrs:
                attrs.update({"__%s__" % k if not k.startswith("__") else k: v
                              for k, v in n.attrs.items()})
            if n.is_aux_var:
                attrs["__aux__"] = "True"
            if attrs:
                entry["attrs"] = attrs
            out_nodes.append(entry)
        heads = [[nid[id(n)], i, 0] for n, i in self._outputs]
        arg_nodes = [i for i, n in enumerate(nodes) if n.is_var]
        return json.dumps({
            "nodes": out_nodes,
            "arg_nodes": arg_nodes,
            "node_row_ptr": list(range(len(nodes) + 1)),
            "heads": heads,
            "attrs": {"mxnet_version": ["int", 1100],
                      "mxtpu_json_schema": ["int",
                                            self.JSON_SCHEMA_VERSION]},
        }, indent=2)

    def save(self, fname):
        # crash-safe like every other checkpoint artifact: the final path
        # only ever holds a complete symbol file
        from ..checkpoint import atomic_write
        atomic_write(fname, self.tojson().encode("utf-8"))

    def debug_str(self):
        lines = []
        for n in self._topo_nodes():
            if n.is_var:
                lines.append("Variable:%s" % n.name)
            else:
                ins = ", ".join("%s[%d]" % (i.name, idx)
                                for i, idx in n.inputs)
                lines.append("Op:%s, Name=%s, Inputs=[%s]"
                             % (n.op.name, n.name, ins))
        return "\n".join(lines)


def _parse_attr_value(v):
    import ast
    try:
        return ast.literal_eval(v)
    except (ValueError, SyntaxError):
        # python-2-era checkpoints spell tuples with long suffixes:
        # "(2L, 2L)" (reference upgrades these in legacy_json_util.cc)
        if isinstance(v, str) and "L" in v:
            try:
                return ast.literal_eval(
                    __import__("re").sub(r"(\d)L\b", r"\1", v))
            except (ValueError, SyntaxError):
                pass
        return v


#: attr keys the reference stored bare in old JSON and moved to hidden
#: __key__ form on load (/root/reference/src/c_api/c_api_symbolic.cc:39,
#: src/nnvm/legacy_json_util.cc UpgradeJSON_FixParsing)
_HIDDEN_KEYS = ("ctx_group", "lr_mult", "wd_mult", "force_mirroring",
                "mirror_stage")


def _upgrade_legacy_attrs(entry, attrs):
    """Reference-era JSON upgrade: bare hidden keys become __key__ user
    attrs; '<arg>_<key>' entries on an op node are remembered so they can
    be moved onto the matching input variable (legacy_json_util.cc:29-90).
    Returns (attrs, moved) where moved = {arg_name: {key: value}}."""
    out, moved = {}, {}
    for k, v in attrs.items():
        hit = False
        for hk in _HIDDEN_KEYS:
            if k == hk:
                out["__%s__" % hk] = v
                hit = True
            elif k.endswith("_" + hk) and entry.get("op") != "null":
                moved.setdefault(k[:-len(hk) - 1], {})["__%s__" % hk] = v
                hit = True
            if hit:
                break
        if not hit:
            out[k] = v
    return out, moved


def load_json(json_str):
    """Load a Symbol from its JSON string (reference: mx.sym.load_json).

    Accepts the current format and reference-era legacy JSON: per-node
    attrs under "attrs", "attr" (nnvm-era) or "param" (pre-nnvm), bare
    hidden keys, and python-2 long literals — the role of the reference's
    legacy_json_util.cc upgrade pass."""
    data = json.loads(json_str)
    nodes = []
    for entry in data["nodes"]:
        attrs = entry.get("attrs", entry.get("attr",
                                             entry.get("param", {}))) or {}
        attrs, moved = _upgrade_legacy_attrs(entry, attrs)
        user_attrs = {k[2:-2]: v for k, v in attrs.items()
                      if k.startswith("__") and k.endswith("__")
                      and k != "__aux__"}
        params = {k: _parse_attr_value(v) for k, v in attrs.items()
                  if not (k.startswith("__") and k.endswith("__"))}
        if entry["op"] == "null":
            node = _SymNode(None, entry["name"], {}, [], attrs=user_attrs,
                            is_var=True,
                            is_aux_var=attrs.get("__aux__") == "True")
        else:
            op = get_op(entry["op"])
            inputs = [(nodes[i], idx) for i, idx, *_ in entry["inputs"]]
            node = _SymNode(op, entry["name"], params, inputs,
                            attrs=user_attrs)
            if moved:
                # '<arg>_<key>' → the input variable whose name ends with
                # '_<arg>' (or equals it), matching FListInputNames intent
                for arg_name, kv in moved.items():
                    for inp, _idx in inputs:
                        if inp.is_var and (
                                inp.name == arg_name or
                                inp.name.endswith("_" + arg_name)):
                            inp.attrs.update(
                                {k[2:-2]: v for k, v in kv.items()})
                            break
        nodes.append(node)
    heads = data.get("heads", [[len(nodes) - 1, 0, 0]])
    outs = [(nodes[h[0]], h[1]) for h in heads]
    return Symbol(outs[0][0], outs)


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


def Variable(name, attr=None, shape=None, lr_mult=None, wd_mult=None,
             dtype=None, init=None, stype=None, **kwargs):
    """Create a named variable (reference: mx.sym.Variable)."""
    if not isinstance(name, str):
        raise TypeError("Expect a string for variable name")
    attr = AttrScope.current().get(attr)
    attrs = dict(attr or {})
    if shape is not None:
        attrs["__shape__"] = str(tuple(shape))
    if lr_mult is not None:
        attrs["__lr_mult__"] = str(lr_mult)
    if wd_mult is not None:
        attrs["__wd_mult__"] = str(wd_mult)
    if dtype is not None:
        attrs["__dtype__"] = str(dtype)
    if init is not None:
        attrs["__init__"] = init if isinstance(init, str) else \
            init.dumps() if hasattr(init, "dumps") else str(init)
    for k, v in kwargs.items():
        if k.startswith("__") and k.endswith("__"):
            attrs[k] = str(v)
    node = _SymNode(None, name, {}, [], attrs=attrs, is_var=True)
    return Symbol(node, [(node, 0)])


var = Variable


def Group(symbols):
    """Group symbols into one multi-output symbol (reference: mx.sym.Group)."""
    outs = []
    for s in symbols:
        outs.extend(s._outputs)
    return Symbol(outs[0][0], outs)


# ---------------------------------------------------------------------------
# Symbolic op application (the analogue of MXSymbolCreateAtomicSymbol +
# Compose, c_api_symbolic.cc)
# ---------------------------------------------------------------------------

def _apply_op(op, name, sym_args, params, **sym_kwargs):
    hint = op.name.lower().replace("_", "")
    if op.name.startswith("_"):
        hint = "op" + hint
    name = NameManager.current().get(name, hint)
    attrs = AttrScope.current().get(None)

    # variadic ops (Concat, add_n, stack, ...): fill num_args from the
    # positional inputs, as the reference's generated wrappers do
    if "num_args" in op.param_defaults and "num_args" not in params \
            and len(sym_args) > 0:
        params = dict(params, num_args=len(sym_args))

    arg_names = op.arg_names(params)
    aux_names = op.aux_names(params)

    inputs = [None] * len(arg_names)
    aux_inputs = [None] * len(aux_names)
    # positional then keyword symbol inputs; positionals beyond the
    # learnable args fill the auxiliary-state slots, as the reference's
    # generated wrappers allowed (sym.BatchNorm(x, g, b, mean, var))
    for i, s in enumerate(sym_args):
        if i < len(arg_names):
            inputs[i] = s
        elif i < len(arg_names) + len(aux_names):
            aux_inputs[i - len(arg_names)] = s
        else:
            raise MXNetError("too many positional inputs for %s" % op.name)
    for k, v in sym_kwargs.items():
        if k in arg_names:
            inputs[arg_names.index(k)] = v
        elif k in aux_names:
            aux_inputs[aux_names.index(k)] = v
        else:
            raise MXNetError("unknown input %s for %s" % (k, op.name))
    # auto-create variables for missing learnable inputs
    filled = []
    for argname, s in zip(arg_names, inputs):
        if s is None:
            s = Variable("%s_%s" % (name, argname))
        filled.append(s)
    for auxname, s in zip(aux_names, aux_inputs):
        if s is None:
            s = Variable("%s_%s" % (name, auxname))
        outs = s._outputs
        if len(outs) != 1 or not outs[0][0].is_var:
            # aux states are mutable storage the executor writes back
            # into by variable name; an op output in an aux slot would
            # silently mispair the write-backs
            raise MXNetError(
                "auxiliary input %s of %s must be a Variable"
                % (auxname, op.name))
        outs[0][0].is_aux_var = True
        filled.append(s)

    node_inputs = []
    for s in filled:
        outs = s._outputs
        if len(outs) != 1:
            raise MXNetError("input symbols must have a single output")
        node_inputs.append(outs[0])

    node = _SymNode(op, name, params, node_inputs, attrs=attrs)
    return Symbol(node, [(node, i) for i in range(node.num_outputs())])


def make_symbol_function(op, func_name):
    def creator(*args, **kwargs):
        name = kwargs.pop("name", None)
        kwargs.pop("attr", None)
        sym_args = list(args)
        sym_kwargs = {}
        params = {}
        for k, v in kwargs.items():
            if isinstance(v, Symbol):
                sym_kwargs[k] = v
            else:
                params[k] = v
        return _apply_op(op, name, sym_args, params, **sym_kwargs)
    creator.__name__ = func_name
    creator.__doc__ = (op.fn.__doc__ or "") + \
        "\n\nSymbolic version of operator `%s`." % op.name
    return creator


def populate(namespace):
    for opname, op in list(_OP_REGISTRY.items()):
        if opname not in namespace:
            namespace[opname] = make_symbol_function(op, opname)
