"""Base utilities for the TPU-native framework.

Plays the role of the reference's ``python/mxnet/base.py`` (ctypes bridge,
handle types, ``check_call``) — but there is no C ABI to cross for the compute
path: ops lower to XLA via JAX.  What remains here is the shared error type,
string/registry helpers, and a few numeric aliases.

Reference: /root/reference/python/mxnet/base.py
"""
from __future__ import annotations

import numpy as _np

__all__ = ["MXNetError", "string_types", "numeric_types", "integer_types"]


class MXNetError(RuntimeError):
    """Error raised by the framework (reference: base.py:MXNetError)."""


string_types = (str,)
numeric_types = (float, int, _np.generic)
integer_types = (int, _np.integer)


def check_call(ret):
    """Kept for API compatibility; no C calls to check in the TPU build."""
    if ret:  # pragma: no cover - compatibility shim
        raise MXNetError(str(ret))


def _as_list(obj):
    """Return obj wrapped in a list if it is not already a list/tuple."""
    if isinstance(obj, (list, tuple)):
        return list(obj)
    return [obj]
