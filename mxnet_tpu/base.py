"""Base utilities for the TPU-native framework.

Plays the role of the reference's ``python/mxnet/base.py`` (ctypes bridge,
handle types, ``check_call``) — but there is no C ABI to cross for the compute
path: ops lower to XLA via JAX.  What remains here is the shared error type,
string/registry helpers, and a few numeric aliases.

Reference: /root/reference/python/mxnet/base.py
"""
from __future__ import annotations

import numpy as _np

__all__ = ["MXNetError", "string_types", "numeric_types", "integer_types"]


class MXNetError(RuntimeError):
    """Error raised by the framework (reference: base.py:MXNetError)."""


string_types = (str,)
numeric_types = (float, int, _np.generic)
integer_types = (int, _np.integer)


def check_call(ret):
    """Kept for API compatibility; no C calls to check in the TPU build."""
    if ret:  # pragma: no cover - compatibility shim
        raise MXNetError(str(ret))


def _as_list(obj):
    """Return obj wrapped in a list if it is not already a list/tuple."""
    if isinstance(obj, (list, tuple)):
        return list(obj)
    return [obj]


def _maybe_init_distributed():
    """Join the process mesh from tools/launch.py's env contract
    (MXTPU_COORDINATOR / MXTPU_NUM_WORKERS / MXTPU_WORKER_RANK) — the
    TPU-era replacement for ps-lite's DMLC_PS_ROOT_URI bootstrap.

    Must run before any JAX backend initializes; mxnet_tpu/__init__ calls
    it at import time, and kvstore.create('dist_*') re-invokes it as a
    safety net, warning loudly if joining failed."""
    import os
    coord = os.environ.get("MXTPU_COORDINATOR")
    if not coord:
        return
    import jax
    try:
        if jax.distributed.is_initialized():
            return
    except AttributeError:
        pass
    if os.environ.get("MXTPU_RANK_FROM_MPI") == "1" and \
            "MXTPU_WORKER_RANK" not in os.environ:
        # mpi launcher (tools/launch.py --launcher mpi): adopt the rank
        # mpirun assigned this process (and fill the reference-compat
        # DMLC_WORKER_ID alongside, like the local/ssh launchers do)
        for var in ("OMPI_COMM_WORLD_RANK", "PMI_RANK", "PMIX_RANK",
                    "SLURM_PROCID"):
            if var in os.environ:
                os.environ["MXTPU_WORKER_RANK"] = os.environ[var]
                os.environ.setdefault("DMLC_WORKER_ID", os.environ[var])
                break
    try:
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=int(os.environ["MXTPU_NUM_WORKERS"]),
            process_id=int(os.environ["MXTPU_WORKER_RANK"]))
    except (RuntimeError, KeyError) as e:
        import logging
        logging.warning(
            "mxnet_tpu: could not join the distributed mesh at %s (%s); "
            "this process runs single-process. Import mxnet_tpu (or "
            "create the dist kvstore) before touching any arrays.",
            coord, e)
