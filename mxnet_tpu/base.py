"""Base utilities for the TPU-native framework.

Plays the role of the reference's ``python/mxnet/base.py`` (ctypes bridge,
handle types, ``check_call``) — but there is no C ABI to cross for the compute
path: ops lower to XLA via JAX.  What remains here is the shared error type,
string/registry helpers, and a few numeric aliases.

Reference: /root/reference/python/mxnet/base.py
"""
from __future__ import annotations

import numpy as _np

__all__ = ["MXNetError", "string_types", "numeric_types", "integer_types"]


class MXNetError(RuntimeError):
    """Error raised by the framework (reference: base.py:MXNetError)."""


string_types = (str,)
numeric_types = (float, int, _np.generic)
integer_types = (int, _np.integer)


def check_call(ret):
    """Kept for API compatibility; no C calls to check in the TPU build."""
    if ret:  # pragma: no cover - compatibility shim
        raise MXNetError(str(ret))


def _as_list(obj):
    """Return obj wrapped in a list if it is not already a list/tuple."""
    if isinstance(obj, (list, tuple)):
        return list(obj)
    return [obj]


def _distributed_initialized(jax):
    """Has jax.distributed already joined a mesh in this process?  The
    public ``is_initialized`` only exists on newer jax; fall back to the
    coordination client's global state.  Getting this wrong is not
    cosmetic: re-running bring-up would make rank 0's port pre-probe see
    its OWN live coordination service and exit 76."""
    try:
        if jax.distributed.is_initialized():
            return True
    except AttributeError:
        pass
    try:
        from jax._src.distributed import global_state
        return global_state.client is not None or \
            global_state.coordinator_address is not None
    except Exception:
        return False


def _membership_env_changed(jax):
    """Does the env membership contract disagree with the live mesh?
    An elastic restart re-exports MXTPU_NUM_WORKERS/MXTPU_WORKER_RANK
    for the re-ranked survivors; a process that joined under the OLD
    contract must not silently keep using it."""
    import os
    try:
        want_num = int(os.environ["MXTPU_NUM_WORKERS"])
        want_rank = int(os.environ["MXTPU_WORKER_RANK"])
    except (KeyError, ValueError):
        return False  # no/garbled contract: nothing to compare against
    try:
        return (jax.process_count() != want_num or
                jax.process_index() != want_rank)
    except Exception:
        return False  # backend not up yet; initialize() will see env


def _coordinator_port_free(coord):
    """Rank 0 pre-probe: can the coordinator port still be bound?  A
    restarted job can race a dying predecessor (or another tenant) for a
    pinned --port; probing with our own socket gives a deterministic
    "address in use" verdict instead of whatever message the JAX
    coordination service wraps the bind failure in."""
    import socket
    host, _, port = coord.rpartition(":")
    try:
        port = int(port)
    except ValueError:
        return True  # unparseable address: let initialize() report it
    import errno
    s = socket.socket()
    try:
        # SO_REUSEADDR to exactly match the grpc server's bind semantics:
        # TIME_WAIT debris from a killed predecessor job must not fail
        # the probe (it would not fail the real bind either) — only a
        # LIVE socket holding the port is a conflict
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host if host not in ("", "localhost") else "", port))
        return True
    except OSError as e:
        # ONLY a genuine address-in-use is this probe's verdict; any
        # other failure (unresolvable hostname, non-local address,
        # IPv6 literal this parse mangled) must fall through to the
        # real bind so the job surfaces a config error instead of
        # burning its restart budget on retryable exit 76s
        return getattr(e, "errno", None) != errno.EADDRINUSE
    finally:
        s.close()


def _maybe_init_distributed():
    """Join the process mesh from tools/launch.py's env contract
    (MXTPU_COORDINATOR / MXTPU_NUM_WORKERS / MXTPU_WORKER_RANK) — the
    TPU-era replacement for ps-lite's DMLC_PS_ROOT_URI bootstrap.

    Must run before any JAX backend initializes; mxnet_tpu/__init__ calls
    it at import time, and kvstore.create('dist_*') re-invokes it as a
    safety net.

    Bring-up is timeout-guarded (a worker pointed at a dead coordinator
    used to block in ``jax.distributed.initialize`` forever): non-zero
    ranks probe the coordinator over TCP with retry/backoff for a
    ``MXTPU_CONNECT_TIMEOUT × (MXTPU_CONNECT_RETRIES+1)`` window
    (defaults 60s × 3); expiry raises MXNetError naming the coordinator
    — an *exit*, which the launcher classifies retryable and answers
    with a job restart, instead of an eternal hang.  A rank-0
    coordinator-port bind failure exits ``EXIT_PORT_IN_USE`` (76) so the
    launcher can re-pick the port (``--port 0``) on restart."""
    import os
    coord = os.environ.get("MXTPU_COORDINATOR")
    if not coord:
        return
    import jax
    if _distributed_initialized(jax):
        # already joined — but an elastic restart may have re-exported
        # the membership env (tools/launch.py --elastic re-ranks the
        # survivors and changes MXTPU_NUM_WORKERS between attempts).
        # Each elastic attempt is a fresh PROCESS, so normally this path
        # never sees a mismatch.  When it does (a harness re-exporting
        # env inside one process), say so loudly and KEEP the old mesh:
        # jax pins the process topology for the process lifetime
        # (process_count/process_index are lru_cached over the frozen
        # backend), so a shutdown+re-initialize here would neither
        # update what jax reports nor ever clear the mismatch — it
        # would just re-run bring-up on every later call.  The only
        # supported way to change this process's membership is to exit
        # and let the launcher respawn it (retryable exits exist for
        # exactly that).
        if _membership_env_changed(jax):
            import logging
            logging.warning(
                "mxnet_tpu: membership env (MXTPU_NUM_WORKERS/"
                "MXTPU_WORKER_RANK=%s/%s) no longer matches the mesh "
                "this process joined (%d processes, rank %d); jax "
                "cannot re-join in-process — keeping the existing "
                "mesh. Exit the process and let tools/launch.py "
                "respawn it under the new membership.",
                os.environ.get("MXTPU_NUM_WORKERS"),
                os.environ.get("MXTPU_WORKER_RANK"),
                jax.process_count(), jax.process_index())
        return  # re-calls are no-ops
    if os.environ.get("MXTPU_RANK_FROM_MPI") == "1" and \
            "MXTPU_WORKER_RANK" not in os.environ:
        # mpi launcher (tools/launch.py --launcher mpi): adopt the rank
        # mpirun assigned this process (and fill the reference-compat
        # DMLC_WORKER_ID alongside, like the local/ssh launchers do)
        for var in ("OMPI_COMM_WORLD_RANK", "PMI_RANK", "PMIX_RANK",
                    "SLURM_PROCID"):
            if var in os.environ:
                os.environ["MXTPU_WORKER_RANK"] = os.environ[var]
                os.environ.setdefault("DMLC_WORKER_ID", os.environ[var])
                break
    try:
        num = int(os.environ["MXTPU_NUM_WORKERS"])
        rank = int(os.environ["MXTPU_WORKER_RANK"])
    except KeyError as e:
        # misconfigured env (coordinator without rank contract): the old
        # degrade-to-single-process behaviour, loudly
        import logging
        logging.warning(
            "mxnet_tpu: could not join the distributed mesh at %s (%s); "
            "this process runs single-process. Import mxnet_tpu (or "
            "create the dist kvstore) before touching any arrays.",
            coord, e)
        return
    import sys
    import time
    from .watchdog import EXIT_PORT_IN_USE, _env_float

    def _port_in_use_exit(detail):
        print("mxnet_tpu: coordinator port %s is already bound (%s); "
              "exiting %d so the launcher re-picks the port (--port 0) "
              "on restart" % (coord, detail, EXIT_PORT_IN_USE),
              file=sys.stderr, flush=True)
        raise SystemExit(EXIT_PORT_IN_USE)

    if rank == 0 and not _coordinator_port_free(coord):
        _port_in_use_exit("pre-bind probe failed")

    t = _env_float("MXTPU_CONNECT_TIMEOUT", 0.0)
    timeout = t if t > 0 else 60.0
    # 0 retries is a valid choice (fail fast after one window)
    retries = max(0, int(_env_float("MXTPU_CONNECT_RETRIES", 2.0)))
    if rank != 0:
        # dead-coordinator defense BEFORE touching jax.distributed: on
        # deadline expiry jax's own initialization_timeout hard-aborts
        # the process (LOG(FATAL) in the XLA coordination client, SIGABRT
        # — no Python exception to catch), so the bounded wait runs as a
        # plain TCP probe here, where failure can raise a diagnosable
        # MXNetError naming the coordinator
        _wait_for_coordinator(coord, timeout * (retries + 1))
    try:
        try:
            # belt only (the TCP probe above bounds the dead-coordinator
            # case): never BELOW jax's own 300s default — the connect
            # timeout is sized for "is the coordinator reachable", not
            # for a slow-but-healthy whole-cluster join (hosts can start
            # minutes apart on a real pod)
            jax.distributed.initialize(
                coordinator_address=coord, num_processes=num,
                process_id=rank,
                initialization_timeout=int(
                    max(300, timeout * (retries + 1))))
        except TypeError:
            # older jax without initialization_timeout: the TCP probe
            # above already bounded the dead-coordinator case
            jax.distributed.initialize(
                coordinator_address=coord, num_processes=num,
                process_id=rank)
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception as e:  # jax wraps grpc errors inconsistently
        msg = str(e).lower()
        if "should only be called once" in msg:
            # raced another in-process initializer: already joined —
            # still publish the membership (the race winner may have
            # been user code calling jax.distributed.initialize
            # directly, which records nothing)
            from . import elastic
            elastic.note_membership(num, rank)
            return
        if rank == 0 and ("address already in use" in msg or
                          "address in use" in msg or
                          "failed to bind" in msg):
            _port_in_use_exit(e)
        raise MXNetError(
            "could not join the distributed mesh at %s as rank %d/%d: "
            "%s. Exiting so the launcher can restart the job instead "
            "of hanging in bring-up forever." % (coord, rank, num, e)
        ) from e
    # joined: publish the membership this process runs under — feeds the
    # elastic.world_size gauge / elastic.transitions counter (a restart
    # at a different world size counts via MXTPU_PREV_WORLD_SIZE) and
    # the postmortem membership block
    from . import elastic
    elastic.note_membership(num, rank)


def _wait_for_coordinator(coord, deadline_s):
    """Bounded retry-with-backoff TCP probe of the coordinator: returns
    once it accepts a connection (rank 0 may start it at any point inside
    the window), raises MXNetError naming the address when the deadline
    expires — the worker *exits* (retryable, launch.py restarts the job)
    instead of blocking in bring-up forever."""
    import socket
    import time
    host, _, port = coord.rpartition(":")
    try:
        port = int(port)
    except ValueError:
        return  # unparseable address: let initialize() report it
    deadline = time.monotonic() + max(1.0, deadline_s)
    delay, last = 0.2, None
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            break
        try:
            s = socket.create_connection(
                (host or "127.0.0.1", port),
                timeout=min(5.0, max(0.5, remaining)))
            s.close()
            return
        except OSError as e:
            last = e
        time.sleep(min(delay, max(0.0, deadline - time.monotonic())))
        delay = min(delay * 1.6, 3.0)
    raise MXNetError(
        "could not join the distributed mesh: coordinator %s did not "
        "accept a connection within %.0fs (last error: %s). The "
        "coordinator is dead, unreachable, or never started; exiting "
        "so the launcher can restart the job instead of hanging in "
        "bring-up forever." % (coord, deadline_s, last))
