"""Gluon basic layers.

Port of /root/reference/python/mxnet/gluon/nn/basic_layers.py: Sequential,
HybridSequential, Dense, Activation, Dropout, BatchNorm, LeakyReLU,
Embedding, Flatten, Lambda/HybridLambda.  Each hybrid layer's compute is a
single registry-op call, so a hybridized network fuses into one XLA
program.
"""
from __future__ import annotations

import numpy as _np

from ..block import Block, HybridBlock
from ...base import MXNetError

__all__ = ["Sequential", "HybridSequential", "Dense", "Activation",
           "FlashSelfAttention", "LayerNorm", "GELU",
           "Dropout", "BatchNorm", "LeakyReLU", "Embedding", "Flatten",
           "Lambda", "HybridLambda"]


class Sequential(Block):
    """Stack Blocks sequentially (reference basic_layers.py:29)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children:
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return self._children[i]


class HybridSequential(HybridBlock):
    """Stack HybridBlocks sequentially (reference basic_layers.py:53)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x):
        for block in self._children:
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return self._children[i]


class Dense(HybridBlock):
    """Fully-connected layer (reference basic_layers.py:77)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_units=0, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self._units = units
            self._in_units = in_units
            self._flatten = flatten
            self.weight = self.params.get(
                "weight", shape=(units, in_units),
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,),
                    init=_init_from_name(bias_initializer),
                    allow_deferred_init=True)
            else:
                self.bias = None
            if activation is not None:
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def infer_shape(self, x):
        in_units = int(_np.prod(x.shape[1:])) if self._flatten \
            else x.shape[-1]
        self.weight.shape = (self._units, in_units)

    def hybrid_forward(self, F, x, weight, bias=None):
        if bias is None:
            out = F.FullyConnected(x, weight, num_hidden=self._units,
                                   no_bias=True, flatten=self._flatten)
        else:
            out = F.FullyConnected(x, weight, bias,
                                   num_hidden=self._units,
                                   flatten=self._flatten)
        if self.act is not None:
            out = self.act(out)
        return out

    def __repr__(self):
        return "Dense({0} -> {1})".format(
            self.weight.shape[1] if self.weight.shape else None,
            self._units)


class Activation(HybridBlock):
    """Activation layer (reference basic_layers.py:154)."""

    def __init__(self, activation, **kwargs):
        self._act_type = activation
        super().__init__(**kwargs)

    def _alias(self):
        return self._act_type

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)

    def __repr__(self):
        return "Activation({})".format(self._act_type)


class Dropout(HybridBlock):
    """Dropout (reference basic_layers.py:179)."""

    def __init__(self, rate, **kwargs):
        super().__init__(**kwargs)
        self._rate = rate

    def hybrid_forward(self, F, x):
        return F.Dropout(x, p=self._rate)

    def __repr__(self):
        return "Dropout(p = {})".format(self._rate)


class BatchNorm(HybridBlock):
    """Batch normalization (reference basic_layers.py:209)."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"axis": axis, "eps": epsilon, "momentum": momentum,
                        "fix_gamma": not scale,
                        "use_global_stats": use_global_stats}
        self._axis = axis
        if in_channels != 0:
            self.in_channels = in_channels
        self.gamma = self.params.get(
            "gamma", grad_req="write" if scale else "null",
            shape=(in_channels,), init=_init_from_name(gamma_initializer),
            allow_deferred_init=True)
        self.beta = self.params.get(
            "beta", grad_req="write" if center else "null",
            shape=(in_channels,), init=_init_from_name(beta_initializer),
            allow_deferred_init=True)
        self.running_mean = self.params.get(
            "running_mean", grad_req="null", shape=(in_channels,),
            init=_init_from_name(running_mean_initializer),
            allow_deferred_init=True, differentiable=False)
        self.running_var = self.params.get(
            "running_var", grad_req="null", shape=(in_channels,),
            init=_init_from_name(running_variance_initializer),
            allow_deferred_init=True, differentiable=False)

    def infer_shape(self, x):
        c = x.shape[self._axis % len(x.shape)]
        for p in (self.gamma, self.beta, self.running_mean,
                  self.running_var):
            p.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        return F.BatchNorm(x, gamma, beta, running_mean, running_var,
                           **self._kwargs)

    def __repr__(self):
        in_channels = self.gamma.shape[0] if self.gamma.shape else None
        return "BatchNorm(axis={}, eps={}, momentum={}, in_channels={})" \
            .format(self._kwargs["axis"], self._kwargs["eps"],
                    self._kwargs["momentum"], in_channels)


class LeakyReLU(HybridBlock):
    """Leaky ReLU (reference basic_layers.py:273)."""

    def __init__(self, alpha, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)

    def __repr__(self):
        return "LeakyReLU({})".format(self._alpha)


class Embedding(HybridBlock):
    """Index → vector lookup (reference basic_layers.py:297)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"input_dim": input_dim, "output_dim": output_dim,
                        "dtype": dtype}
        self.weight = self.params.get(
            "weight", shape=(input_dim, output_dim),
            init=weight_initializer, allow_deferred_init=True)

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, **self._kwargs)

    def __repr__(self):
        return "Embedding({} -> {})".format(
            self._kwargs["input_dim"], self._kwargs["output_dim"])


class FlashSelfAttention(HybridBlock):
    """Multi-head self-attention over [B, T, C] through the fused
    O(T)-memory attention op (`_contrib_flash_attention`, the Pallas
    kernel on TPU).  TPU-native addition — the 2017 reference predates
    attention; exposed as a gluon layer so the kernel is reachable from
    the layer API, not just raw ops."""

    def __init__(self, units, num_heads, causal=False, use_bias=True,
                 weight_initializer=None, in_units=0, **kwargs):
        super().__init__(**kwargs)
        if units % num_heads:
            raise ValueError("units %d not divisible by num_heads %d"
                             % (units, num_heads))
        self._units = units
        self._num_heads = num_heads
        self._causal = causal
        self._ring = None
        with self.name_scope():
            self.qkv = Dense(3 * units, flatten=False, use_bias=use_bias,
                             weight_initializer=weight_initializer,
                             in_units=in_units, prefix="qkv_")
            self.out_proj = Dense(units, flatten=False, use_bias=use_bias,
                                  weight_initializer=weight_initializer,
                                  in_units=units, prefix="out_")

    def sequence_parallel(self, mesh, axis="sp", batch_axis=None,
                          impl=None):
        """Run this layer's attention as RING attention over ``mesh``'s
        ``axis`` (parallel/ring_attention.py): the sequence dim of
        q/k/v is sharded, K/V blocks rotate via ppermute, and packing
        segment ids (when given to forward) ride the ring — long
        context through the layer API, no ``parallel/`` calls in user
        code.  Applies on the traced path (functionalize/jit training);
        pass ``mesh=None`` to restore the single-device kernel."""
        self._ring = (None if mesh is None
                      else (mesh, axis, batch_axis, impl))
        self._cached_op = None

    def hybrid_forward(self, F, x, segments=None):
        b, t = x.shape[0], x.shape[1]
        h = self._num_heads
        d = self._units // h
        qkv = self.qkv(x)                        # [B, T, 3C]
        # HEAD-MAJOR fused layout [H, 3, D]: a tensor-parallel column
        # split of the qkv weight's out dim then lands on whole heads,
        # so GSPMD propagates it into the attention (a [3, H, D] layout
        # has indivisible major factor 3 and forces an all-gather)
        qkv = F.reshape(qkv, shape=(b, t, h, 3, d))
        qkv = F.transpose(qkv, axes=(3, 0, 2, 1, 4))  # [3, B, H, T, D]
        q = F.reshape(F.slice_axis(qkv, axis=0, begin=0, end=1),
                      shape=(b, h, t, d))
        k = F.reshape(F.slice_axis(qkv, axis=0, begin=1, end=2),
                      shape=(b, h, t, d))
        v = F.reshape(F.slice_axis(qkv, axis=0, begin=2, end=3),
                      shape=(b, h, t, d))
        if self._ring is not None:
            # sequence-parallel path: ring attention over the sp mesh
            # axis (T sharded; packing ids rotate with their K/V block)
            from ... import parallel as _par
            from ... import autograd as _ag
            if hasattr(q, "_data") and _ag.is_recording():
                # the ring call runs outside the op registry, so the
                # imperative tape cannot record it — grads upstream of
                # attention would silently be zero
                raise RuntimeError(
                    "sequence_parallel attention does not support the "
                    "imperative autograd tape; train through "
                    "functionalize/jit (see parallel/gpt_spmd.py), or "
                    "call sequence_parallel(None) first")
            mesh, axis_name, batch_axis, impl = self._ring

            def _raw(a):
                return a._data if hasattr(a, "_data") else a
            o = _par.ring_attention_fn(
                _raw(q), _raw(k), _raw(v), mesh=mesh, axis=axis_name,
                causal=self._causal, batch_axis=batch_axis, impl=impl,
                segment_ids=(None if segments is None
                             else _raw(segments)))
        else:
            attn = getattr(F, "_contrib_flash_attention")
            if segments is None:
                o = attn(q, k, v, causal=self._causal)  # [B, H, T, D]
            else:
                # sequence packing: [B, T] int ids, attend within-segment
                o = attn(q, k, v, segments, causal=self._causal,
                         use_segments=True)
        o = F.reshape(F.transpose(o, axes=(0, 2, 1, 3)),
                      shape=(b, t, self._units))
        return self.out_proj(o)


class LayerNorm(HybridBlock):
    """Layer normalization over the last axis (TPU-native addition — the
    2017 reference predates LayerNorm; statistics run in fp32 so bf16
    transformer activations keep stable norms, ops/nn.py LayerNorm)."""

    def __init__(self, epsilon=1e-5, axis=-1, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._epsilon = epsilon
        self.gamma = self.params.get(
            "gamma", grad_req="write" if scale else "null",
            shape=(in_channels,),
            init=_init_from_name(gamma_initializer),
            allow_deferred_init=True)
        self.beta = self.params.get(
            "beta", grad_req="write" if center else "null",
            shape=(in_channels,),
            init=_init_from_name(beta_initializer),
            allow_deferred_init=True)

    def infer_shape(self, x):
        dim = x.shape[self._axis]
        self.gamma.shape = (dim,)
        self.beta.shape = (dim,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis,
                           eps=self._epsilon)

    def __repr__(self):
        return "LayerNorm(eps={}, axis={})".format(self._epsilon,
                                                   self._axis)


class GELU(HybridBlock):
    """Gaussian error linear unit (tanh form; TPU-native addition)."""

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type="gelu")

    def __repr__(self):
        return "GELU"


class Flatten(HybridBlock):
    """Flatten to (N, -1) (reference basic_layers.py:331)."""

    def hybrid_forward(self, F, x):
        return F.Flatten(x)

    def __repr__(self):
        return "Flatten"


class Lambda(Block):
    """Wrap a function as a Block."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as nd_mod
            assert hasattr(nd_mod, function), \
                "Function name %s is not found in ndarray." % function
            self._func_impl = getattr(nd_mod, function)
        else:
            self._func_impl = function

    def forward(self, *args):
        return self._func_impl(*args)


class HybridLambda(HybridBlock):
    """Wrap a function as a HybridBlock."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            self._func_name = function

            def _fn(F, *args):
                return getattr(F, function)(*args)
            self._func_impl = _fn
        else:
            self._func_impl = lambda F, *args: function(F, *args)
            self._func_name = function.__name__

    def hybrid_forward(self, F, x, *args):
        return self._func_impl(F, x, *args)


def _init_from_name(name):
    if name is None or not isinstance(name, str):
        return name
    from ... import initializer as init_mod
    return init_mod.create(name)
