"""Gluon Trainer.

Port of /root/reference/python/mxnet/gluon/trainer.py (:26-121): applies an
Optimizer to a ParameterDict, optionally aggregating gradients through a
KVStore.  On TPU a single process sees the whole mesh, so the kvstore path
only matters for the dist facade.  The common (no-kvstore) path applies the
whole optimizer step as ONE donated jitted XLA program over the full
parameter pytree — a single dispatch per step instead of one jitted update
kernel per parameter; per-param lr_mult/wd_mult are baked in as a static
aux tree while lr / rescale_grad stay dynamic scalars.  Configurations the
tree-wide apply can't express (sparse grads, non-fusable optimizers,
kvstore aggregation) keep the per-param loop.
"""
from __future__ import annotations

from .. import optimizer as opt
from ..model import _create_kvstore
from .parameter import ParameterDict, Parameter

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                "got %s." % (type(params)))
        self._params = []
        for param in params:
            if not isinstance(param, Parameter):
                raise ValueError(
                    "First argument must be a list or dict of Parameters, "
                    "got list of %s." % (type(param)))
            self._params.append(param)
        self._compression_params = compression_params
        optimizer_params = optimizer_params if optimizer_params else {}
        self._scale = optimizer_params.get("rescale_grad", 1.0)
        self._init_optimizer(optimizer, optimizer_params)
        self._kv_initialized = False
        self._kvstore = kvstore

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            assert not optimizer_params, \
                "optimizer_params must be None if optimizer is an " \
                "Optimizer instance"
            self._optimizer = optimizer
        else:
            self._optimizer = opt.create(optimizer,
                                         param_idx2name={
                                             i: p.name for i, p in
                                             param_dict.items()},
                                         **optimizer_params)
        lr_mult = {}
        wd_mult = {}
        for i, param in enumerate(self._params):
            lr_mult[i] = param.lr_mult
            wd_mult[i] = param.wd_mult
        self._optimizer.set_lr_mult(lr_mult)
        self._optimizer.set_wd_mult(wd_mult)
        self._updaters = opt.get_updater(self._optimizer)
        self._fused = None  # fused tree-wide step cache

    def _init_kvstore(self):
        arg_arrays = {param.name: param.data() for param in self._params
                      if param.grad_req != "null"}
        kvstore, update_on_kvstore = _create_kvstore(self._kvstore, 1,
                                                     arg_arrays)
        self._kv = kvstore
        self._update_on_kvstore = update_on_kvstore
        if kvstore:
            if self._compression_params:
                kvstore.set_gradient_compression(self._compression_params)
            for i, param in enumerate(self._params):
                if param.grad_req == "null":
                    continue
                kvstore.init(i, param.data())
            if update_on_kvstore:
                kvstore.set_optimizer(self._optimizer)
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.lr

    def set_learning_rate(self, lr):
        self._optimizer.lr = lr

    def step(self, batch_size, ignore_stale_grad=False):
        """Apply one optimizer step, scaling grads by 1/batch_size."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size

        if self._kv is None and self._fused_step():
            return
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            if self._kv is not None:
                self._kv.push(i, param.list_grad())
                if self._update_on_kvstore:
                    self._kv.pull(i, param.list_data())
                    continue
                self._kv.pull(i, param.list_grad())
            self._updaters(i, param.grad(), param.data())

    # -- fused tree-wide step ----------------------------------------------
    def _fused_step(self):
        """Apply the whole optimizer step as ONE donated jitted program
        over the parameter pytree.  Returns False when the configuration
        can't fuse (caller then runs the per-param loop)."""
        def bail():
            # falling back to the per-param loop: hand accumulated fused
            # state to the Updater (else it create_states fresh zeros)
            # and drop the cache so a later fused return re-seeds from it
            self._fused_flush_to_updater()
            self._fused = None
            return False

        optimizer = self._optimizer
        kind = optimizer.fused_kind()
        if kind is None:
            return bail()
        from ..ndarray.sparse import RowSparseNDArray
        live = [(i, p) for i, p in enumerate(self._params)
                if p.grad_req != "null"]
        if not live:
            return True  # nothing to update — and nothing to dispatch
        if len({id(p) for _, p in live}) != len(live):
            return bail()  # duplicated Parameter: donation would alias
        if any(isinstance(p.grad(), RowSparseNDArray) for _, p in live):
            return bail()  # lazy/sparse updates keep the per-param path

        import jax
        from .. import profiler as _profiler

        # params are keyed by their updater index so state save/load and
        # the mult resolution (Trainer seeds lr_mult by index) line up
        keys = [str(i) for i, _ in live]
        idx2key = {i: str(i) for i, _ in live}
        mults = optimizer.fused_mults(idx2key)
        cache_key = (id(optimizer), kind, tuple(keys),
                     tuple(sorted(mults.items())),
                     tuple(sorted(optimizer.fused_hyper().items())),
                     tuple(p.shape for _, p in live))
        if self._fused is None or self._fused["key"] != cache_key:
            # a reconfiguration (new mults, frozen param...) rebuilds the
            # program; park accumulated momentum/Adam state in the Updater
            # first so the re-seed below picks it up instead of zeros
            self._fused_flush_to_updater()
            init_state, apply_fn = optimizer.make_fused_apply(idx2key)
            raw = {k: p.data()._data for k, (_, p) in zip(keys, live)}
            state = init_state(raw)
            if self._updaters.states:
                from ..optimizer import fused_state_from_updater
                for i, p in live:
                    if i in self._updaters.states:
                        state[str(i)] = fused_state_from_updater(
                            kind, self._updaters.states[i], p.data())
            self._fused = {
                "key": cache_key, "kind": kind, "state": state,
                "step": _profiler.instrument(
                    jax.jit(apply_fn, donate_argnums=(0, 2)))}

        fused = self._fused
        params = {str(i): p.data()._data for i, p in live}
        grads = {str(i): p.grad()._data for i, p in live}
        first = live[0][0]
        for i, _ in live:
            optimizer._update_count(i)
        t = float(optimizer._index_update_count[first])
        new_params, new_state = fused["step"](
            params, grads, fused["state"], optimizer.fused_base_lr(),
            float(optimizer.wd), float(optimizer.rescale_grad), t)
        fused["state"] = new_state
        for i, p in live:
            p.data()._set_data(new_params[str(i)])
        _profiler.note_step()
        return True

    def _fused_flush_to_updater(self):
        if self._fused is None:
            return
        from ..optimizer import fused_state_to_updater
        kind = self._fused["kind"]
        for key, st in self._fused["state"].items():
            self._updaters.states[int(key)] = \
                fused_state_to_updater(kind, st)

    def save_states(self, fname):
        assert self._optimizer is not None
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kv.save_optimizer_states(fname, dump_optimizer=True)
        else:
            self._fused_flush_to_updater()
            with open(fname, "wb") as fout:
                fout.write(self._updaters.get_states())

    def load_states(self, fname):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kv.load_optimizer_states(fname)
            self._optimizer = self._kv._optimizer
        else:
            with open(fname, "rb") as f:
                self._updaters.set_states(f.read())
            self._fused = None  # re-seed fused state from the Updater
