"""Gluon Trainer.

Port of /root/reference/python/mxnet/gluon/trainer.py (:26-121): applies an
Optimizer to a ParameterDict, optionally aggregating gradients through a
KVStore.  On TPU a single process sees the whole mesh, so the kvstore path
only matters for the dist facade; the common path is a direct optimizer
step per parameter — each update op is a jitted XLA kernel.
"""
from __future__ import annotations

from .. import optimizer as opt
from ..model import _create_kvstore
from .parameter import ParameterDict, Parameter

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                "got %s." % (type(params)))
        self._params = []
        for param in params:
            if not isinstance(param, Parameter):
                raise ValueError(
                    "First argument must be a list or dict of Parameters, "
                    "got list of %s." % (type(param)))
            self._params.append(param)
        self._compression_params = compression_params
        optimizer_params = optimizer_params if optimizer_params else {}
        self._scale = optimizer_params.get("rescale_grad", 1.0)
        self._init_optimizer(optimizer, optimizer_params)
        self._kv_initialized = False
        self._kvstore = kvstore

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            assert not optimizer_params, \
                "optimizer_params must be None if optimizer is an " \
                "Optimizer instance"
            self._optimizer = optimizer
        else:
            self._optimizer = opt.create(optimizer,
                                         param_idx2name={
                                             i: p.name for i, p in
                                             param_dict.items()},
                                         **optimizer_params)
        lr_mult = {}
        wd_mult = {}
        for i, param in enumerate(self._params):
            lr_mult[i] = param.lr_mult
            wd_mult[i] = param.wd_mult
        self._optimizer.set_lr_mult(lr_mult)
        self._optimizer.set_wd_mult(wd_mult)
        self._updaters = opt.get_updater(self._optimizer)

    def _init_kvstore(self):
        arg_arrays = {param.name: param.data() for param in self._params
                      if param.grad_req != "null"}
        kvstore, update_on_kvstore = _create_kvstore(self._kvstore, 1,
                                                     arg_arrays)
        self._kv = kvstore
        self._update_on_kvstore = update_on_kvstore
        if kvstore:
            if self._compression_params:
                kvstore.set_gradient_compression(self._compression_params)
            for i, param in enumerate(self._params):
                if param.grad_req == "null":
                    continue
                kvstore.init(i, param.data())
            if update_on_kvstore:
                kvstore.set_optimizer(self._optimizer)
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.lr

    def set_learning_rate(self, lr):
        self._optimizer.lr = lr

    def step(self, batch_size, ignore_stale_grad=False):
        """Apply one optimizer step, scaling grads by 1/batch_size."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size

        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            if self._kv is not None:
                self._kv.push(i, param.list_grad())
                if self._update_on_kvstore:
                    self._kv.pull(i, param.list_data())
                    continue
                self._kv.pull(i, param.list_grad())
            self._updaters(i, param.grad(), param.data())

    def save_states(self, fname):
        assert self._optimizer is not None
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kv.save_optimizer_states(fname, dump_optimizer=True)
        else:
            with open(fname, "wb") as fout:
                fout.write(self._updaters.get_states())

    def load_states(self, fname):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kv.load_optimizer_states(fname)
            self._optimizer = self._kv._optimizer
        else:
            with open(fname, "rb") as f:
                self._updaters.set_states(f.read())
