"""Gluon Trainer.

Port of /root/reference/python/mxnet/gluon/trainer.py (:26-121): applies an
Optimizer to a ParameterDict, optionally aggregating gradients through a
KVStore.  On TPU a single process sees the whole mesh, so the kvstore path
only matters for the dist facade.  The common (no-kvstore) path applies the
whole optimizer step as ONE donated jitted XLA program over the full
parameter pytree — a single dispatch per step instead of one jitted update
kernel per parameter; per-param lr_mult/wd_mult are baked in as a static
aux tree while lr / rescale_grad stay dynamic scalars.  Configurations the
tree-wide apply can't express (sparse grads, non-fusable optimizers,
kvstore aggregation) keep the per-param loop.
"""
from __future__ import annotations

import time

from .. import optimizer as opt
from ..model import _create_kvstore
from .parameter import ParameterDict, Parameter

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                "got %s." % (type(params)))
        self._params = []
        for param in params:
            if not isinstance(param, Parameter):
                raise ValueError(
                    "First argument must be a list or dict of Parameters, "
                    "got list of %s." % (type(param)))
            self._params.append(param)
        self._compression_params = compression_params
        optimizer_params = optimizer_params if optimizer_params else {}
        self._scale = optimizer_params.get("rescale_grad", 1.0)
        self._init_optimizer(optimizer, optimizer_params)
        self._kv_initialized = False
        self._kvstore = kvstore

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            assert not optimizer_params, \
                "optimizer_params must be None if optimizer is an " \
                "Optimizer instance"
            self._optimizer = optimizer
        else:
            self._optimizer = opt.create(optimizer,
                                         param_idx2name={
                                             i: p.name for i, p in
                                             param_dict.items()},
                                         **optimizer_params)
        lr_mult = {}
        wd_mult = {}
        for i, param in enumerate(self._params):
            lr_mult[i] = param.lr_mult
            wd_mult[i] = param.wd_mult
        self._optimizer.set_lr_mult(lr_mult)
        self._optimizer.set_wd_mult(wd_mult)
        self._updaters = opt.get_updater(self._optimizer)
        self._fused = None  # fused tree-wide step cache
        self._consec_guard_skips = 0  # divergence-guard skip streak
        self._pending_verdict = None  # (ok, indices, pre_num_update)
        self._precision = None  # PrecisionPolicy (mxnet_tpu.precision)

    def set_precision(self, policy):
        """Install a :class:`mxnet_tpu.precision.PrecisionPolicy` (or
        None).  Its fingerprint keys the fused tree-wide step; its loss
        scaler threads through the dynamic ``rescale_grad`` scalar and
        consumes the (one-step-late) divergence-guard verdict."""
        self._precision = policy
        self._fused_flush_to_updater()
        self._fused = None

    def _init_kvstore(self):
        arg_arrays = {param.name: param.data() for param in self._params
                      if param.grad_req != "null"}
        kvstore, update_on_kvstore = _create_kvstore(self._kvstore, 1,
                                                     arg_arrays)
        self._kv = kvstore
        self._update_on_kvstore = update_on_kvstore
        if kvstore:
            if self._compression_params:
                kvstore.set_gradient_compression(self._compression_params)
            for i, param in enumerate(self._params):
                if param.grad_req == "null":
                    continue
                kvstore.init(i, param.data())
            if update_on_kvstore:
                kvstore.set_optimizer(self._optimizer)
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.lr

    def set_learning_rate(self, lr):
        self._optimizer.lr = lr

    def step(self, batch_size, ignore_stale_grad=False):
        """Apply one optimizer step, scaling grads by 1/batch_size."""
        from .. import fault as _fault
        from .. import watchdog as _watchdog
        from ..checkpoint import check_async_error
        _fault.stall_if("worker.stall")
        # a failed background save_states write surfaces on the next
        # step (one global None-check; no dispatches)
        check_async_error()
        self._resolve_pending_verdict()
        from ..ops.optimizer_ops import (max_consecutive_skips,
                                         raise_skip_limit_error)
        limit = max_consecutive_skips()
        if self._consec_guard_skips >= limit:
            # the Kth skip may have been resolved from a save/flush path
            # (which never raises); the training loop is where the error
            # belongs
            raise_skip_limit_error(limit)
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size

        try:
            if self._kv is None and self._fused_step():
                return
            for i, param in enumerate(self._params):
                if param.grad_req == "null":
                    continue
                if self._kv is not None:
                    self._kv.push(i, param.list_grad())
                    if self._update_on_kvstore:
                        self._kv.pull(i, param.list_data())
                        continue
                    self._kv.pull(i, param.list_grad())
                self._updaters(i, param.grad(), param.data())
        finally:
            # progress lease (fused and per-param paths alike): gluon
            # training loops are user-owned, so the watchdog self-arms on
            # the first renewal when MXTPU_STALL_TIMEOUT is set; call
            # watchdog.disarm() after your last step if the process keeps
            # doing non-training work (ROBUSTNESS.md §7)
            _watchdog.renew("trainer_step", phase="train")

    # -- fused tree-wide step ----------------------------------------------
    def _zero_shardings(self, live):
        """ZeRO-1 state placement for the gluon path ({updater-index-key:
        NamedSharding}), or None.  Engages when MXTPU_ZERO>=1 and every
        live parameter resides on one NamedSharding mesh with a >1 ``dp``
        axis (gluon params land there via initialize(ctx=[N devices]) /
        shard_and_load); anything else — single device, mixed meshes,
        host arrays — keeps the replicated-state program."""
        from ..ops.optimizer_ops import zero_stage
        if zero_stage() < 1:
            return None
        from jax.sharding import NamedSharding
        from ..parallel.mesh import AXIS_DP
        mesh = None
        for _, p in live:
            s = getattr(p.data()._data, "sharding", None)
            if not isinstance(s, NamedSharding):
                return None
            if mesh is None:
                mesh = s.mesh
            elif s.mesh != mesh:
                return None
        if mesh is None or AXIS_DP not in mesh.shape or \
                mesh.shape[AXIS_DP] <= 1:
            return None
        from ..parallel.sharding import zero1_spec
        out = {}
        for i, p in live:
            arr = p.data()._data
            spec = zero1_spec(arr.shape, mesh, axis=AXIS_DP,
                              base=arr.sharding.spec, name=p.name)
            out[str(i)] = NamedSharding(mesh, spec)
        return out

    def _fused_step(self):
        """Apply the whole optimizer step as ONE donated jitted program
        over the parameter pytree.  Returns False when the configuration
        can't fuse (caller then runs the per-param loop)."""
        def bail():
            # falling back to the per-param loop: hand accumulated fused
            # state to the Updater (else it create_states fresh zeros)
            # and drop the cache so a later fused return re-seeds from it
            self._fused_flush_to_updater()
            self._fused = None
            return False

        optimizer = self._optimizer
        kind = optimizer.fused_kind()
        if kind is None:
            return bail()
        from ..ndarray.sparse import RowSparseNDArray
        live = [(i, p) for i, p in enumerate(self._params)
                if p.grad_req != "null"]
        if not live:
            return True  # nothing to update — and nothing to dispatch
        if len({id(p) for _, p in live}) != len(live):
            return bail()  # duplicated Parameter: donation would alias
        if any(isinstance(p.grad(), RowSparseNDArray) for _, p in live):
            return bail()  # lazy/sparse updates keep the per-param path

        import jax
        from .. import fault as _fault
        from .. import profiler as _profiler
        from ..ops.optimizer_ops import make_guarded_apply

        # params are keyed by their updater index so state save/load and
        # the mult resolution (Trainer seeds lr_mult by index) line up
        keys = [str(i) for i, _ in live]
        idx2key = {i: str(i) for i, _ in live}
        mults = optimizer.fused_mults(idx2key)
        from ..ops.optimizer_ops import zero_stage
        want_zero = zero_stage() >= 1
        from ..precision import policy_fingerprint
        cache_key = (id(optimizer), kind, tuple(keys),
                     tuple(sorted(mults.items())),
                     tuple(sorted(optimizer.fused_hyper().items())),
                     tuple(p.shape for _, p in live),
                     want_zero, policy_fingerprint(self._precision))
        if self._fused is None or self._fused["key"] != cache_key:
            # sharding resolution only on rebuild — step() is hot
            zero = self._zero_shardings(live) if want_zero else None
            # a reconfiguration (new mults, frozen param...) rebuilds the
            # program; park accumulated momentum/Adam state in the Updater
            # first so the re-seed below picks it up instead of zeros
            self._fused_flush_to_updater()
            init_state, apply_fn = optimizer.make_fused_apply(
                idx2key, zero_shardings=zero)
            raw = {k: p.data()._data for k, (_, p) in zip(keys, live)}
            state = init_state(raw)
            if self._updaters.states:
                from ..optimizer import fused_state_from_updater
                for i, p in live:
                    if i in self._updaters.states:
                        st = fused_state_from_updater(
                            kind, self._updaters.states[i], p.data())
                        if zero is not None:
                            # loaded states are full-size (saves gather);
                            # reshard onto this param's 1/N dp slice —
                            # fresh buffers, the tree is donated while
                            # the Updater keeps the loaded arrays
                            # (sharding.fresh_device_put docs)
                            from ..parallel.sharding import \
                                fresh_device_put
                            st = jax.tree_util.tree_map(
                                lambda s, _t=zero[str(i)]:
                                fresh_device_put(s, _t), st)
                        state[str(i)] = st
            from .. import aot_cache as _aot
            jit_kw = {"donate_argnums": (0, 2)}
            if zero is not None:
                # ZeRO-1 (ops.optimizer_ops docs): explicit shardings —
                # params stay on their resident (replicated) placement,
                # state in/out lives on its 1/N dp shard, grads arrive
                # replicated and the guard's constraints do the
                # reduce-scatter / sharded update / all-gather inside
                # the ONE donated program
                from jax.sharding import NamedSharding
                param_sh = {str(i): p.data()._data.sharding
                            for i, p in live}
                mesh = next(iter(param_sh.values())).mesh
                rep = NamedSharding(mesh,
                                    jax.sharding.PartitionSpec())
                jit_kw["in_shardings"] = (param_sh, param_sh, dict(zero),
                                          None, None, None, None, None)
                jit_kw["out_shardings"] = (param_sh, dict(zero), rep)
            else:
                param_sh = None
            self._fused = {
                "key": cache_key, "kind": kind, "state": state,
                "zero": zero,
                # same divergence guard as Module.fit_step: all-finite
                # check + no-op select inside the ONE donated program,
                # compiled outside jax's persistent cache on backends
                # where replaying a donated executable from it corrupts
                # the heap (aot_cache.donation_cache_guard)
                "step": _profiler.instrument(_aot.donation_cache_guard(
                    jax.jit(make_guarded_apply(
                        apply_fn, zero_shardings=zero,
                        param_shardings=param_sh),
                        **jit_kw)))}

        fused = self._fused
        params = {str(i): p.data()._data for i, p in live}
        grads = {str(i): p.grad()._data for i, p in live}
        first = live[0][0]
        pre_num_update = optimizer.num_update
        for i, _ in live:
            optimizer._update_count(i)
        t = float(optimizer._index_update_count[first])
        poison = float("nan") if _fault.trigger("grad.nan") else 0.0
        t0 = time.perf_counter_ns()
        rescale = float(optimizer.rescale_grad)
        scaler = getattr(self._precision, "loss_scaler", None)
        if scaler is not None:
            # loss scaling (precision.py): the loss was pre-scaled by
            # scaler.scale; undo it on the grads through the dynamic
            # rescale scalar — scale moves never recompile
            rescale *= scaler.unscale
        new_params, new_state, ok = fused["step"](
            params, grads, fused["state"], optimizer.fused_base_lr(),
            float(optimizer.wd), rescale, t, poison)
        t1 = time.perf_counter_ns()
        fused["state"] = new_state
        # donation killed the old buffers — write back even on a skipped
        # step (new_params then carries the unchanged values through)
        for i, p in live:
            p.data()._set_data(new_params[str(i)])
        _profiler.note_step()
        from .. import telemetry as _telemetry
        # no sync stamp and a pending (None) verdict: both resolve one
        # step late via handle_guard_verdict -> mark_last_step_verdict;
        # a crash in between leaves the honest "unknown", never "ok"
        _telemetry.note_train_step(t0, t1, None, None, None,
                                   "trainer_step")
        # the verdict is resolved one step LATE: reading ``ok`` now would
        # block on the whole fused program and kill the dispatch/compute
        # overlap the trainer path otherwise keeps (Module.fit syncs per
        # batch for metrics anyway, so IT reads immediately).  Skip
        # semantics tolerate the lag — the rewind happens before the next
        # step's clock ticks, and the K-consecutive raise fires one step
        # later (PERF.md "Divergence guard").
        self._pending_verdict = (ok, [i for i, _ in live], pre_num_update)
        return True

    def _resolve_pending_verdict(self):
        """Apply the previous fused step's guard verdict (skip counter +
        optimizer-clock rewind).  Never raises: the K-consecutive-skip
        MXNetError is checked at the top of step(), so save/flush paths
        that settle the clock cannot abort on a training-health error."""
        if self._pending_verdict is None:
            return
        from ..ops.optimizer_ops import handle_guard_verdict
        ok, indices, pre_num_update = self._pending_verdict
        self._pending_verdict = None
        ok_host = bool(ok)
        self._consec_guard_skips = handle_guard_verdict(
            ok_host, self._optimizer, indices, self._consec_guard_skips,
            pre_num_update, raise_on_limit=False, backfill_verdict=True)
        scaler = getattr(self._precision, "loss_scaler", None)
        if scaler is not None:
            # same (one-step-late) verdict the guard acted on: backoff
            # on skip, growth on streak — skip accounting untouched
            scaler.update(ok_host)

    def _fused_flush_to_updater(self):
        # state hand-offs and saves must see a settled optimizer clock
        self._resolve_pending_verdict()
        if self._fused is None:
            return
        from ..optimizer import fused_state_to_updater
        kind = self._fused["kind"]
        for key, st in self._fused["state"].items():
            self._updaters.states[int(key)] = \
                fused_state_to_updater(kind, st)

    def save_states(self, fname):
        """Atomic, checksummed write (checkpoint.write_state_file).
        Under MXTPU_ASYNC_CKPT=1 the framed payload is materialized here
        (bytes — donation-safe) and the fsync+rename run on the async
        writer thread; failures surface sticky on the next step()."""
        assert self._optimizer is not None
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kv.save_optimizer_states(fname, dump_optimizer=True)
        else:
            from ..checkpoint import async_write_state_file
            self._fused_flush_to_updater()
            async_write_state_file(fname, self._updaters.get_states())

    def load_states(self, fname):
        """Validated read — corrupt state files raise MXNetError naming
        the path (checkpoint.load_state_file)."""
        # settle any in-flight verdict against the OLD optimizer before
        # its state is replaced; a stale rollback applied to the restored
        # clock would corrupt Adam's t / the lr schedule
        self._resolve_pending_verdict()
        from ..checkpoint import flush_async
        # a load must never race the async writer over the same file
        flush_async(raise_errors=False)
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kv.load_optimizer_states(fname)
            self._optimizer = self._kv._optimizer
        else:
            from ..checkpoint import load_state_file
            load_state_file(fname, self._updaters.set_states)
            self._fused = None  # re-seed fused state from the Updater
        self._consec_guard_skips = 0  # fresh state, fresh streak
