"""Gluon Parameter / ParameterDict.

Port of /root/reference/python/mxnet/gluon/parameter.py (606 L): Parameter
with grad_req, lazy shape (zeros in shape → deferred init at first
forward), initialize/reset_ctx/save/load; ParameterDict with prefix
scoping and sharing.  Device placement is XLA's concern — ``ctx`` is kept
for API parity, with ``list_ctx`` reporting the context the data lives on.
"""
from __future__ import annotations

import numpy as _np

from .. import autograd
from .. import initializer as init_mod
from .. import ndarray as nd
from ..base import MXNetError
from ..context import current_context
from ..ndarray.ndarray import NDArray

__all__ = ["DeferredInitializationError", "Parameter", "ParameterDict"]


class DeferredInitializationError(MXNetError):
    """Parameter is not initialized yet because shape is unknown."""


def _replicate_over(ctx_list, data):
    """Replicate a raw array over the dp mesh formed by ``ctx_list``.

    Fresh buffers, not device_put: these params feed the Trainer's
    DONATED update tree, and an eager same-device device_put may hand
    back replica shards aliasing the source (loaded/initialized arrays
    other code still references) — donating an aliased buffer corrupts
    the heap (parallel.sharding.fresh_device_put, PR-7)."""
    from jax.sharding import NamedSharding, PartitionSpec
    from ..parallel.mesh import dp_mesh_from_ctx
    from ..parallel.sharding import fresh_device_put
    mesh = dp_mesh_from_ctx(ctx_list)
    return fresh_device_put(data, NamedSharding(mesh, PartitionSpec()))


class Parameter:
    def __init__(self, name, grad_req="write", shape=None, dtype=_np.float32,
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True):
        self.name = name
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._differentiable = differentiable
        self.grad_req = grad_req if differentiable else "null"
        self._data = None
        self._grad = None
        self._deferred_init = None

    def __repr__(self):
        return "Parameter %s (shape=%s, dtype=%s)" % (self.name, self.shape,
                                                      self.dtype)

    # -- initialization ----------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        if default_init is None:
            default_init = init_mod.Uniform()
        if self._data is not None and not force_reinit:
            return
        if self.shape is None or any(s == 0 for s in self.shape):
            if self.allow_deferred_init:
                self._deferred_init = (init, ctx, default_init)
                return
            raise ValueError(
                "Cannot initialize Parameter %s because it has invalid "
                "shape: %s." % (self.name, str(self.shape)))
        self._finish_init(init, ctx, default_init)

    def _finish_init(self, init, ctx, default_init):
        mesh_ctx = None
        if isinstance(ctx, (list, tuple)):
            if len(ctx) > 1:
                mesh_ctx = list(ctx)
            ctx = ctx[0] if ctx else None
        data = nd.zeros(self.shape, dtype=self.dtype, ctx=ctx)
        initializer = init or self.init or default_init
        if isinstance(initializer, str):
            initializer = init_mod.create(initializer)
        initializer(init_mod.InitDesc(self.name), data)
        if mesh_ctx is not None:
            # ctx list → replicate over a dp mesh of those devices; the
            # reference kept one copy per GPU and broadcast through KVStore
            # (gluon/trainer.py:init), here replication is a sharding
            data._set_data(_replicate_over(mesh_ctx, data._data))
        self._data = data
        self._init_grad()
        self._deferred_init = None

    def _finish_deferred_init(self, in_shape_fill=None):
        """Complete deferred init once the shape is known."""
        if self._deferred_init is None:
            raise DeferredInitializationError(
                "Parameter %s has not been initialized" % self.name)
        if in_shape_fill is not None:
            self.shape = tuple(in_shape_fill)
        if self.shape is None or any(s == 0 for s in self.shape):
            raise DeferredInitializationError(
                "Parameter %s still has unknown shape %s" %
                (self.name, self.shape))
        init, ctx, default_init = self._deferred_init
        self._finish_init(init, ctx, default_init)

    def _init_grad(self):
        if self.grad_req == "null":
            self._grad = None
            return
        self._data.attach_grad(grad_req=self.grad_req)
        self._grad = self._data._grad

    # -- access ------------------------------------------------------------
    def _check_initialized(self):
        if self._data is None:
            if self._deferred_init is not None:
                raise DeferredInitializationError(
                    "Parameter %s has not been initialized yet because "
                    "initialization was deferred. Actual initialization "
                    "happens during the first forward pass." % self.name)
            raise RuntimeError(
                "Parameter %s has not been initialized. Note that you "
                "should initialize parameters and create Trainer with "
                "Block.collect_params() instead of Block.params because "
                "the later does not include Parameters of nested child "
                "Blocks" % self.name)

    def data(self, ctx=None):
        self._check_initialized()
        return self._data

    def list_data(self):
        return [self.data()]

    def grad(self, ctx=None):
        self._check_initialized()
        if self._grad is None:
            raise RuntimeError(
                "Cannot get gradient array for Parameter %s because "
                "grad_req='null'" % self.name)
        return self._data._grad

    def list_grad(self):
        return [self.grad()]

    def list_ctx(self):
        self._check_initialized()
        return [self._data.context]

    def set_data(self, data):
        if self._data is None:
            # setting data before init resolves deferred init
            self.shape = tuple(data.shape)
            if self._deferred_init is not None:
                self._finish_deferred_init()
            else:
                self._data = data if isinstance(data, NDArray) \
                    else nd.array(data)
                self._init_grad()
                return
        self._data._set_data(
            data._data if isinstance(data, NDArray)
            else nd.array(data)._data)

    def zero_grad(self):
        if self._grad is not None:
            self._grad[:] = 0

    def reset_ctx(self, ctx):
        pass  # placement is XLA-managed; kept for API parity

    def cast(self, dtype):
        self.dtype = dtype
        if self._data is not None:
            self._data._set_data(self._data.astype(dtype)._data)

    # reattach to the autograd graph each forward when recording
    def _maybe_mark(self):
        if self._grad is not None and autograd.is_recording():
            autograd.mark_variable(self._data)

    def var(self):
        from .. import symbol
        return symbol.Variable(self.name, shape=self.shape,
                               lr_mult=self.lr_mult, wd_mult=self.wd_mult)


class ParameterDict:
    """Prefix-scoped dict of Parameters (reference parameter.py:380)."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = {}
        self._shared = shared

    def __repr__(self):
        name = self._prefix + " " if self._prefix else ""
        return "%sParameterDict containing %d parameters" % (
            name, len(self._params))

    def __getitem__(self, key):
        return self._params[key]

    def __iter__(self):
        return iter(self._params)

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    @property
    def prefix(self):
        return self._prefix

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared._params:
            self._params[name] = self._shared._params[name]
            return self._params[name]
        return None

    def get(self, name, **kwargs):
        """Get or create a parameter named prefix+name."""
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
        else:
            for k, v in kwargs.items():
                if hasattr(param, k) and getattr(param, k) is not None:
                    existing = getattr(param, k)
                    if k == "shape" and v is not None and \
                            existing is not None:
                        if len(v) == len(existing) and all(
                                a == b or a == 0 or b == 0
                                for a, b in zip(v, existing)):
                            setattr(param, k, tuple(
                                max(a, b) for a, b in zip(v, existing)))
                            continue
                    assert str(v) == str(existing) or v is None, \
                        "Parameter %s attribute %s mismatch: %s vs %s" % \
                        (name, k, str(v), str(existing))
                else:
                    setattr(param, k, v)
        return param

    def update(self, other):
        for k, v in other.items():
            if k in self._params:
                assert self._params[k] is v, \
                    "Cannot update self with other because they have " \
                    "different Parameters with the same name %s" % k
            else:
                self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        if init is None:
            init = init_mod.Uniform()
        for _, v in self.items():
            v.initialize(None, ctx, init, force_reinit=force_reinit)

    def zero_grad(self):
        for v in self.values():
            v.zero_grad()

    def reset_ctx(self, ctx):
        for v in self.values():
            v.reset_ctx(ctx)

    def setattr(self, name, value):
        for v in self.values():
            setattr(v, name, value)

    def save(self, filename, strip_prefix=""):
        arg_dict = {}
        for param in self.values():
            block = param.data()
            name = param.name
            if strip_prefix and name.startswith(strip_prefix):
                name = name[len(strip_prefix):]
            arg_dict[name] = block
        nd.save(filename, arg_dict)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        arg_dict = nd.load(filename)
        if restore_prefix:
            arg_dict = {restore_prefix + k: v for k, v in arg_dict.items()}
        if not allow_missing:
            for name in self.keys():
                assert name in arg_dict, \
                    "Parameter %s is missing in file %s" % (name, filename)
        for name, val in arg_dict.items():
            if name not in self._params:
                if not ignore_extra:
                    raise ValueError(
                        "Parameter %s loaded from file %s is not present "
                        "in ParameterDict" % (name, filename))
                continue
            self._params[name].set_data(val)
