"""Gluon DataLoader (reference python/mxnet/gluon/data/dataloader.py).

Batches a Dataset through a Sampler.  The reference (0.11) is
single-process; later versions added multiprocessing workers.  Here the
batchification keeps everything in numpy until the final device_put of the
full batch — one transfer per batch, TPU-friendly — and a double-buffered
background prefetcher (``prefetch``, default 2) overlaps the host-side
sample gather + batchify + host→device transfer of batch N+1 with the
device compute of batch N, the role the reference's ThreadedIter /
PrefetcherIter played for the C++ pipeline (src/io/iter_prefetcher.h).
"""
from __future__ import annotations

import os
import queue as _queue
import threading

import numpy as _np

from ... import ndarray as nd
from ... import telemetry as _telemetry
from ... import watchdog as _watchdog
from . import sampler as _sampler

__all__ = ["DataLoader"]


def default_batchify_fn(data):
    """Stack samples into a batch."""
    if isinstance(data[0], nd.NDArray):
        return nd.stack(*data, num_args=len(data), axis=0)
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = _np.asarray(data)
    return nd.array(data, dtype=data.dtype)


def _device_put_batch(batch):
    """Start the async host→device transfer for every array in the batch
    (jax.device_put returns immediately; by the time the consumer uses the
    batch the copy has overlapped with compute)."""
    import jax
    if isinstance(batch, (list, tuple)):
        for b in batch:
            _device_put_batch(b)
        return batch
    if isinstance(batch, nd.NDArray):
        batch._set_data(jax.device_put(batch._data))
    return batch


class _PrefetchIter:
    """Double-buffered iterator: a daemon thread stays ``depth`` batches
    ahead, so batchify + device_put of the next batch runs while the
    caller trains on the current one.  Worker exceptions re-raise at the
    point of consumption, preserving the sequential path's semantics.
    Abandoned iteration (a peeked batch, an early ``break``) must not pin
    the worker + its queued device batches for the process lifetime, so
    the producer polls a stop flag and ``close()``/``__del__`` drain."""

    _SENTINEL = object()

    def __init__(self, make_batches, depth):
        self._q = _queue.Queue(maxsize=depth)
        self._done = False
        self._stop = threading.Event()
        # the worker closes over LOCALS only — capturing self would cycle
        # (self._worker -> closure -> self) and defer the __del__ cleanup
        # below to a cyclic-GC pass instead of refcount drop
        q, stop, sentinel = self._q, self._stop, self._SENTINEL

        def put(item):
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except _queue.Full:
                    continue
            return False

        def work():
            try:
                from ... import fault as _fault
                for batch in make_batches():
                    # a wedged producer (hung storage read, deadlocked
                    # augmentation) starves the consumer in __next__; the
                    # consumer-side "data" lease expires and the watchdog
                    # diagnoses the stall
                    _fault.stall_if("data.stall")
                    # bounded per-batch delay (straggler stand-in): the
                    # consumer's data.prefetch_wait percentiles inflate
                    # on this rank only
                    _fault.delay_if("data.slow")
                    _fault.check("data.prefetch",
                                 "prefetch worker failure")
                    # start (don't wait for) the host→device copy; the
                    # span is the enqueue cost, the copy itself overlaps
                    # with device compute
                    with _telemetry.span("data.h2d", cat="data"):
                        batch = _device_put_batch(batch)
                    if not put(batch):
                        return
            except BaseException as e:  # noqa: BLE001 — re-raised below
                # e.__traceback__ carries the worker-side frames; the
                # consumer re-raises the same object so the user sees the
                # original failure point chained under their next() call
                put(e)
                return
            put(sentinel)

        self._worker = threading.Thread(target=work, daemon=True)
        self._worker.start()

    def close(self):
        """Unblock and retire the worker; free queued batches.  Drops
        every reference this iterator holds (queue, worker thread, batch
        factory) — a closed-but-still-referenced loader iterator must
        not pin queued host/device batches for the process lifetime."""
        self._done = True
        stop, worker, q = self._stop, self._worker, self._q
        if q is None:
            return  # already closed (close is re-entrant; __del__ too —
            # and must not touch the "data" lease again: a stale __del__
            # would revoke the lease a LIVE successor iterator renews)
        _watchdog.release("data")  # no more progress expected from here
        stop.set()
        try:
            # a put() already past its stop check can still land one item;
            # join first (the worker exits within one 0.1 s poll) so the
            # drain below really empties the queue
            worker.join(timeout=2.0)
        except Exception:
            pass  # interpreter shutdown
        while True:
            try:
                q.get_nowait()
            except _queue.Empty:
                break
        # the drained queue object and the dead worker thread (whose
        # frames closed over make_batches → dataset) are the last paths
        # keeping batch memory reachable from this iterator
        self._q = None
        self._worker = None

    __del__ = close

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        # deterministic teardown: `with iter(loader) as it:` frees the
        # worker thread + queued device batches at block exit instead of
        # whenever GC notices the abandoned iterator
        self.close()
        return False

    def __iter__(self):
        return self

    def __next__(self):
        if self._done or self._q is None:
            raise StopIteration
        # time the consumer actually spends starved waiting on the
        # producer — the "is the input pipeline keeping up" phase
        with _telemetry.span("data.prefetch_wait", cat="data"):
            item = self._q.get()
        if item is self._SENTINEL:
            self.close()  # worker finished; free the thread + queue now
            raise StopIteration
        if isinstance(item, BaseException):
            self.close()
            # re-raise the worker's exception object: its __traceback__
            # still points into the worker (batchify/dataset frames), so
            # the surfaced traceback chains the original failure site
            # under this consumption point
            raise item
        # consumer-side progress lease: renewed per batch actually
        # delivered, so a starved consumer (wedged producer) expires it.
        # primary=False: delivering batch 1 precedes the first step's
        # compile and must not end the startup-grace window
        _watchdog.renew("data", phase="data", primary=False)
        return item


def _default_prefetch():
    """Prefetch depth when the ctor doesn't pin one: MXTPU_DATA_PREFETCH
    overrides the built-in 2 — deployments tune pipeline depth per
    workload (deep for slow storage, 0 to disable) without touching
    model code."""
    try:
        return int(os.environ.get("MXTPU_DATA_PREFETCH", "2"))
    except ValueError:
        return 2


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False,
                 sampler=None, last_batch=None, batch_sampler=None,
                 batchify_fn=None, num_workers=0, prefetch=None):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError(
                    "batch_size must be specified unless batch_sampler is "
                    "specified")
            if sampler is None:
                if shuffle:
                    sampler = _sampler.RandomSampler(len(dataset))
                else:
                    sampler = _sampler.SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError(
                    "shuffle must not be specified if sampler is "
                    "specified")
            batch_sampler = _sampler.BatchSampler(
                sampler, batch_size, last_batch if last_batch else "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError(
                "batch_size, shuffle, sampler and last_batch must not be "
                "specified if batch_sampler is specified.")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn if batchify_fn is not None \
            else default_batchify_fn
        self._prefetch = max(0, int(prefetch if prefetch is not None
                                    else _default_prefetch()))

    def _make_batches(self):
        batches = _telemetry.counter("data.batches")
        for batch in self._batch_sampler:
            with _telemetry.span("data.batchify", cat="data"):
                out = self._batchify_fn(
                    [self._dataset[idx] for idx in batch])
            batches.inc()
            yield out

    def __iter__(self):
        if self._prefetch == 0:
            return self._make_batches()
        return _PrefetchIter(self._make_batches, self._prefetch)

    def __len__(self):
        return len(self._batch_sampler)
