"""Gluon vision datasets (reference python/mxnet/gluon/data/vision.py).

MNIST/FashionMNIST read idx files, CIFAR10/100 read the python-pickle
batches — from a local ``root`` directory (this build has no network;
``download`` raises with instructions).
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct

import numpy as np

from ... import ndarray as nd
from .dataset import Dataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100"]


class _DownloadedDataset(Dataset):
    def __init__(self, root, train, transform):
        self._root = os.path.expanduser(root)
        self._train = train
        self._transform = transform
        self._data = None
        self._label = None
        if not os.path.isdir(self._root):
            raise IOError(
                "Dataset directory %s does not exist. This build is "
                "offline: place the dataset files there manually."
                % self._root)
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """MNIST from idx files (reference data/vision.py:MNIST)."""

    _files = {
        True: ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
        False: ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
    }

    def __init__(self, root="~/.mxnet/datasets/mnist", train=True,
                 transform=None):
        super().__init__(root, train, transform)

    def _open(self, name):
        path = os.path.join(self._root, name)
        if os.path.exists(path):
            return open(path, "rb")
        if os.path.exists(path + ".gz"):
            return gzip.open(path + ".gz", "rb")
        raise IOError("MNIST file %s not found" % path)

    def _get_data(self):
        img_name, lbl_name = self._files[self._train]
        with self._open(lbl_name) as fin:
            struct.unpack(">II", fin.read(8))
            label = np.frombuffer(fin.read(), dtype=np.uint8) \
                .astype(np.int32)
        with self._open(img_name) as fin:
            _, num, rows, cols = struct.unpack(">IIII", fin.read(16))
            data = np.frombuffer(fin.read(), dtype=np.uint8)
            data = data.reshape(num, rows, cols, 1)
        self._data = data  # numpy; DataLoader batchify converts once
        self._label = label


class FashionMNIST(MNIST):
    def __init__(self, root="~/.mxnet/datasets/fashion-mnist", train=True,
                 transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    """CIFAR10 from the python pickle batches (reference CIFAR10)."""

    def __init__(self, root="~/.mxnet/datasets/cifar10", train=True,
                 transform=None):
        super().__init__(root, train, transform)

    def _batches(self):
        if self._train:
            return ["data_batch_%d" % i for i in range(1, 6)]
        return ["test_batch"]

    def _get_data(self):
        data = []
        labels = []
        base = self._root
        sub = os.path.join(base, "cifar-10-batches-py")
        if os.path.isdir(sub):
            base = sub
        for name in self._batches():
            with open(os.path.join(base, name), "rb") as f:
                batch = pickle.load(f, encoding="bytes")
            data.append(batch[b"data"].reshape(-1, 3, 32, 32))
            labels.extend(batch[b"labels"])
        self._data = np.concatenate(data).transpose(0, 2, 3, 1)
        self._label = np.asarray(labels, dtype=np.int32)


class CIFAR100(_DownloadedDataset):
    def __init__(self, root="~/.mxnet/datasets/cifar100", train=True,
                 fine_label=True, transform=None):
        self._fine = fine_label
        super().__init__(root, train, transform)

    def _get_data(self):
        base = self._root
        sub = os.path.join(base, "cifar-100-python")
        if os.path.isdir(sub):
            base = sub
        name = "train" if self._train else "test"
        with open(os.path.join(base, name), "rb") as f:
            batch = pickle.load(f, encoding="bytes")
        self._data = batch[b"data"].reshape(-1, 3, 32, 32) \
            .transpose(0, 2, 3, 1)
        key = b"fine_labels" if self._fine else b"coarse_labels"
        self._label = np.asarray(batch[key], dtype=np.int32)
