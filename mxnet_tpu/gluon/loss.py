"""Gluon losses.

Port of /root/reference/python/mxnet/gluon/loss.py: Loss base with
weight/sample_weight semantics, L1/L2, SigmoidBinaryCrossEntropy (from
logits or probabilities), SoftmaxCrossEntropy (sparse or dense labels),
KLDivLoss, plus CTCLoss lowered to a log-semiring lax.scan (the reference
bundled warp-ctc CUDA kernels, src/operator/contrib/ctc_include/).
"""
from __future__ import annotations

from .block import HybridBlock

__all__ = ["Loss", "L2Loss", "L1Loss", "SigmoidBinaryCrossEntropyLoss",
           "SigmoidBCELoss", "SoftmaxCrossEntropyLoss", "SoftmaxCELoss",
           "KLDivLoss", "HuberLoss", "HingeLoss", "CTCLoss"]


def _apply_weighting(F, loss, weight=None, sample_weight=None):
    if sample_weight is not None:
        loss = F.broadcast_mul(loss, sample_weight)
    if weight is not None:
        assert isinstance(weight, (float, int)), "weight must be a number"
        loss = loss * weight
    return loss


def _reshape_like(F, x, y):
    return F.Reshape(x, shape=y.shape) if hasattr(y, "shape") else x


class Loss(HybridBlock):
    """Base loss (reference loss.py:Loss)."""

    def __init__(self, weight, batch_axis, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def __repr__(self):
        return "{}(batch_axis={}, w={})".format(
            self.__class__.__name__, self._batch_axis, self._weight)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class L2Loss(Loss):
    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(pred - label)
        loss = _apply_weighting(F, loss, self._weight / 2, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class L1Loss(Loss):
    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(pred - label)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class SigmoidBinaryCrossEntropyLoss(Loss):
    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        if not self._from_sigmoid:
            # log(1+exp(x)) - x*z, numerically stable
            max_val = F.maximum(-pred, F.zeros_like(pred))
            loss = pred - pred * label + max_val + \
                F.log(F.exp(-max_val) + F.exp(-pred - max_val))
        else:
            eps = 1e-12
            loss = -(F.log(pred + eps) * label +
                     F.log(1. - pred + eps) * (1. - label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            loss = -F.pick(pred, label, axis=self._axis, keepdims=True)
        else:
            loss = -F.sum(pred * label, axis=self._axis, keepdims=True)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred)
        loss = label * (F.log(label + 1e-12) - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class HuberLoss(Loss):
    def __init__(self, rho=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(pred - label)
        loss = F.where(loss > self._rho,
                       loss - 0.5 * self._rho,
                       (0.5 / self._rho) * F.square(loss))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class HingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.maximum(self._margin - pred * label,
                         F.zeros_like(pred))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class CTCLoss(Loss):
    """Connectionist temporal classification loss.

    The reference bundles warp-ctc CUDA (src/operator/contrib/ctc_include/);
    here the forward algorithm runs in log space as a ``lax.scan`` over
    time — TPU-friendly static-shape dynamic programming.

    Layout 'NTC': pred (N, T, C); label (N, L) padded with -1.
    Blank label is C-1 (reference default blank_label='last'... 0.11 used
    first; we follow the gluon default `blank_label='last'`? The 0.11
    contrib op used blank=0 — configurable here).
    """

    def __init__(self, layout="NTC", label_layout="NT", blank_label="last",
                 weight=None, **kwargs):
        batch_axis = label_layout.find("N")
        super().__init__(weight, batch_axis, **kwargs)
        self._layout = layout
        self._label_layout = label_layout
        self._blank = blank_label

    def hybrid_forward(self, F, pred, label, pred_lengths=None,
                       label_lengths=None, sample_weight=None):
        import jax
        import jax.numpy as jnp
        from ..ndarray.ndarray import NDArray

        def unwrap(a):
            return a._data if isinstance(a, NDArray) else a

        is_nd = isinstance(pred, NDArray)
        p = unwrap(pred)
        l = unwrap(label)
        if self._layout == "TNC":
            p = jnp.swapaxes(p, 0, 1)
        plen = unwrap(pred_lengths)
        llen = unwrap(label_lengths)
        loss = _ctc_loss_jax(p, l.astype(jnp.int32),
                             blank_last=(self._blank == "last"),
                             pred_lengths=None if plen is None
                             else plen.astype(jnp.int32),
                             label_lengths=None if llen is None
                             else llen.astype(jnp.int32))
        out = NDArray(loss) if is_nd else loss
        out = _apply_weighting(F, out, self._weight, sample_weight)
        return out


def _ctc_loss_jax(logits, labels, blank_last=True, pred_lengths=None,
                  label_lengths=None):
    """log-semiring CTC forward over lax.scan. logits (N,T,C), labels (N,L)
    padded with -1 (or bounded by ``label_lengths``); ``pred_lengths``
    limits the per-sample number of frames entering the forward pass."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    N, T, C = logits.shape
    L = labels.shape[1]
    blank = C - 1 if blank_last else 0
    if label_lengths is not None:
        pos = jnp.arange(L)[None, :]
        labels = jnp.where(pos < label_lengths[:, None], labels, -1)
    logp = jax.nn.log_softmax(logits, axis=-1)

    # extended label seq: blank l1 blank l2 ... blank  (length 2L+1)
    ext = jnp.full((N, 2 * L + 1), blank, dtype=jnp.int32)
    ext = ext.at[:, 1::2].set(jnp.where(labels >= 0, labels, blank))
    valid = jnp.concatenate(
        [jnp.ones((N, 1), bool),
         jnp.repeat(labels >= 0, 2, axis=1)], axis=1)
    label_len = jnp.sum(labels >= 0, axis=1)

    neg_inf = -1e30
    S = 2 * L + 1
    alpha0 = jnp.full((N, S), neg_inf)
    alpha0 = alpha0.at[:, 0].set(logp[:, 0, blank])
    alpha0 = alpha0.at[:, 1].set(
        jnp.where(label_len > 0,
                  logp[jnp.arange(N), 0, ext[:, 1]], neg_inf))

    same_as_prev2 = jnp.concatenate(
        [jnp.zeros((N, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1)

    def step(alpha, logp_t):
        shift1 = jnp.concatenate(
            [jnp.full((N, 1), neg_inf), alpha[:, :-1]], axis=1)
        shift2 = jnp.concatenate(
            [jnp.full((N, 2), neg_inf), alpha[:, :-2]], axis=1)
        shift2 = jnp.where(same_as_prev2, neg_inf, shift2)
        merged = jnp.logaddexp(jnp.logaddexp(alpha, shift1), shift2)
        emit = jnp.take_along_axis(logp_t, ext, axis=1)
        new_alpha = jnp.where(valid, merged + emit, neg_inf)
        return new_alpha, new_alpha

    _, stacked = lax.scan(step, alpha0,
                          jnp.swapaxes(logp, 0, 1)[1:])
    all_alpha = jnp.concatenate([alpha0[None], stacked])   # [T, N, S]
    if pred_lengths is None:
        alpha = all_alpha[-1]
    else:
        t_idx = jnp.clip(pred_lengths - 1, 0, T - 1)
        alpha = all_alpha[t_idx, jnp.arange(N)]
    end1 = 2 * label_len
    end2 = 2 * label_len - 1
    a1 = jnp.take_along_axis(alpha, end1[:, None], axis=1)[:, 0]
    a2 = jnp.where(label_len > 0,
                   jnp.take_along_axis(alpha, jnp.maximum(end2, 0)[:, None],
                                       axis=1)[:, 0], neg_inf)
    return -jnp.logaddexp(a1, a2)
