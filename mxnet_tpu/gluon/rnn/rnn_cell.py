"""Gluon RNN cells.

Port of /root/reference/python/mxnet/gluon/rnn/rnn_cell.py (805 L):
RecurrentCell base with state_info/begin_state/unroll, RNNCell, LSTMCell,
GRUCell, SequentialRNNCell, BidirectionalCell, DropoutCell, ZoneoutCell,
ResidualCell.  ``unroll`` is eager step-by-step (like the reference); for
compiled recurrence use gluon.rnn.RNN/LSTM/GRU layers, which lower to the
fused lax.scan RNN op.
"""
from __future__ import annotations

from ..block import Block, HybridBlock
from ... import ndarray as nd

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "BidirectionalCell",
           "DropoutCell", "ZoneoutCell", "ResidualCell"]


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


def _format_sequence(length, inputs, layout, merge):
    """Normalize inputs to a list of per-step arrays or a merged tensor."""
    axis = layout.find("T")
    batch_axis = layout.find("N")
    if isinstance(inputs, (list, tuple)):
        in_list = list(inputs)
        batch_size = in_list[0].shape[batch_axis]
        if merge:
            merged = nd.stack(*in_list, num_args=len(in_list), axis=axis)
            return merged, axis, batch_size
        return in_list, axis, batch_size
    batch_size = inputs.shape[batch_axis]
    if merge is False:
        steps = nd.SliceChannel(inputs, num_outputs=inputs.shape[axis],
                                axis=axis, squeeze_axis=True)
        if not isinstance(steps, (list, tuple)):
            steps = [steps]
        return list(steps), axis, batch_size
    return inputs, axis, batch_size


class RecurrentCell(Block):
    """Base RNN cell (reference rnn_cell.py:RecurrentCell)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    def state_info(self, batch_size=0):
        raise NotImplementedError()

    @property
    def _curr_prefix(self):
        return "%st%d_" % (self.prefix, self._counter)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        assert not self._modified, \
            "After applying modifier cells the base cell cannot be called "\
            "directly. Call the modifier cell instead."
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            if func is None:
                state = nd.zeros(**info)
            else:
                info.update(kwargs)
                state = func(**info)
            states.append(state)
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """Unroll the cell for `length` steps (reference unroll)."""
        self.reset()
        inputs, axis, batch_size = _format_sequence(length, inputs, layout,
                                                    False)
        if begin_state is None:
            begin_state = self.begin_state(batch_size=batch_size)
        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
        if merge_outputs:
            outputs = nd.stack(*outputs, num_args=len(outputs), axis=axis)
        return outputs, states

    def __call__(self, inputs, states):
        self._counter += 1
        return self.forward(inputs, states)


class HybridRecurrentCell(RecurrentCell, HybridBlock):
    """RecurrentCell that is also hybridizable."""

    def __init__(self, prefix=None, params=None):
        RecurrentCell.__init__(self, prefix=prefix, params=params)
        self._active = False
        self._cached_op = None
        self._cached_param_list = None

    def __call__(self, inputs, states):
        self._counter += 1
        return HybridBlock.__call__(self, inputs, states)

    def forward(self, inputs, states):
        single = not isinstance(states, (list, tuple))
        if single:
            states = [states]
        out = HybridBlock.forward(self, inputs, *states)
        return out

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class RNNCell(HybridRecurrentCell):
    """Elman cell: h' = act(W x + b + R h + b') (reference RNNCell)."""

    def __init__(self, hidden_size, activation="tanh",
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(hidden_size,), allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(hidden_size,), allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "rnn"

    def infer_shape(self, x, *states):
        self.i2h_weight.shape = (self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states, h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        output = F.Activation(i2h + h2h, act_type=self._activation)
        return output, [output]


class LSTMCell(HybridRecurrentCell):
    """LSTM cell (reference LSTMCell). Gate order i, f, g, o."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(4 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(4 * hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(4 * hidden_size,), allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(4 * hidden_size,), allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "lstm"

    def infer_shape(self, x, *states):
        self.i2h_weight.shape = (4 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, h, c, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(h, h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        slices = F.SliceChannel(gates, num_outputs=4, axis=1)
        in_gate = F.sigmoid(slices[0])
        forget_gate = F.sigmoid(slices[1])
        in_transform = F.tanh(slices[2])
        out_gate = F.sigmoid(slices[3])
        next_c = forget_gate * c + in_gate * in_transform
        next_h = out_gate * F.tanh(next_c)
        return next_h, [next_h, next_c]


class GRUCell(HybridRecurrentCell):
    """GRU cell (reference GRUCell). Gate order r, z, n (cuDNN)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(3 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(3 * hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(3 * hidden_size,), allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(3 * hidden_size,), allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "gru"

    def infer_shape(self, x, *states):
        self.i2h_weight.shape = (3 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, prev_h, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=3 * self._hidden_size)
        h2h = F.FullyConnected(prev_h, h2h_weight, h2h_bias,
                               num_hidden=3 * self._hidden_size)
        i2h_s = F.SliceChannel(i2h, num_outputs=3, axis=1)
        h2h_s = F.SliceChannel(h2h, num_outputs=3, axis=1)
        reset_gate = F.sigmoid(i2h_s[0] + h2h_s[0])
        update_gate = F.sigmoid(i2h_s[1] + h2h_s[1])
        next_h_tmp = F.tanh(i2h_s[2] + reset_gate * h2h_s[2])
        next_h = (1.0 - update_gate) * next_h_tmp + update_gate * prev_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    """Stack cells (reference SequentialRNNCell)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children, batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children, **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._children:
            n = len(cell.state_info())
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.extend(state)
        return inputs, next_states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        _, _, batch_size = _format_sequence(length, inputs, layout, None)
        if begin_state is None:
            begin_state = self.begin_state(batch_size=batch_size)
        p = 0
        next_states = []
        for i, cell in enumerate(self._children):
            n = len(cell.state_info())
            states = begin_state[p:p + n]
            p += n
            inputs, states = cell.unroll(
                length, inputs=inputs, begin_state=states, layout=layout,
                merge_outputs=None if i < len(self._children) - 1
                else merge_outputs)
            next_states.extend(states)
        return inputs, next_states

    def __getitem__(self, i):
        return self._children[i]

    def __len__(self):
        return len(self._children)


class DropoutCell(RecurrentCell):
    """Apply dropout on input (reference DropoutCell)."""

    def __init__(self, rate, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        assert isinstance(rate, (int, float))
        self.rate = rate

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return "dropout"

    def forward(self, inputs, states):
        if self.rate > 0:
            inputs = nd.Dropout(inputs, p=self.rate)
        return inputs, states

    def __call__(self, inputs, states):
        self._counter += 1
        return self.forward(inputs, states)


class ModifierCell(RecurrentCell):
    """Base for cells wrapping another cell (reference ModifierCell)."""

    def __init__(self, base_cell):
        super().__init__(prefix=None, params=None)
        base_cell._modified = True
        self.base_cell = base_cell

    @property
    def params(self):
        return self.base_cell.params

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs)
        self.base_cell._modified = True
        return begin


class ZoneoutCell(ModifierCell):
    """Zoneout regularization (reference ZoneoutCell)."""

    def __init__(self, base_cell, zoneout_outputs=0., zoneout_states=0.):
        assert not isinstance(base_cell, BidirectionalCell), \
            "BidirectionalCell doesn't support zoneout. " \
            "Please add ZoneoutCell to the cells underneath instead."
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self.prev_output = None

    def _alias(self):
        return "zoneout"

    def reset(self):
        super().reset()
        self.prev_output = None

    def __call__(self, inputs, states):
        self._counter += 1
        cell = self.base_cell
        next_output, next_states = cell(inputs, states)
        from .. import block as _b
        mask_out = self.zoneout_outputs
        mask_st = self.zoneout_states
        prev_output = self.prev_output if self.prev_output is not None \
            else nd.zeros(next_output.shape)
        if mask_out > 0.:
            keep = nd.Dropout(nd.ones(next_output.shape), p=mask_out) > 0
            next_output = nd.where(keep, next_output, prev_output)
        if mask_st > 0.:
            new_states = []
            for new_s, old_s in zip(next_states, states):
                keep = nd.Dropout(nd.ones(new_s.shape), p=mask_st) > 0
                new_states.append(nd.where(keep, new_s, old_s))
            next_states = new_states
        self.prev_output = next_output
        return next_output, next_states


class ResidualCell(ModifierCell):
    """Residual connection around a cell (reference ResidualCell)."""

    def __call__(self, inputs, states):
        self._counter += 1
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        self.base_cell._modified = False
        outputs, states = self.base_cell.unroll(
            length, inputs=inputs, begin_state=begin_state, layout=layout,
            merge_outputs=False)
        self.base_cell._modified = True
        seq, _, _ = _format_sequence(length, inputs, layout, False)
        outputs = [o + i for o, i in zip(outputs, seq)]
        if merge_outputs:
            axis = layout.find("T")
            outputs = nd.stack(*outputs, num_args=len(outputs), axis=axis)
        return outputs, states


class BidirectionalCell(RecurrentCell):
    """Run two cells over the sequence in both directions (reference
    BidirectionalCell)."""

    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix="", params=None)
        self.register_child(l_cell)
        self.register_child(r_cell)
        self._output_prefix = output_prefix

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "Bidirectional cannot be stepped. Please use unroll")

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children, batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children, **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        inputs, axis, batch_size = _format_sequence(length, inputs, layout,
                                                    False)
        if begin_state is None:
            begin_state = self.begin_state(batch_size=batch_size)
        l_cell, r_cell = self._children
        n_l = len(l_cell.state_info())
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs, begin_state=begin_state[:n_l],
            layout=layout, merge_outputs=False)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=list(reversed(inputs)),
            begin_state=begin_state[n_l:], layout=layout,
            merge_outputs=False)
        outputs = [nd.Concat(lo, ro, num_args=2, dim=1)
                   for lo, ro in zip(l_outputs, reversed(r_outputs))]
        if merge_outputs:
            outputs = nd.stack(*outputs, num_args=len(outputs), axis=axis)
        states = l_states + r_states
        return outputs, states
