"""Gluon fused RNN layers.

Port of /root/reference/python/mxnet/gluon/rnn/rnn_layer.py: RNN, LSTM, GRU
backed by the fused ``RNN`` op — on the reference that meant cuDNN
(GPU-only); here it's the lax.scan lowering (ops/rnn.py) with the input
projection batched onto the MXU, so the same layer runs everywhere.
"""
from __future__ import annotations

import numpy as _np

from ... import ndarray as nd
from ...ops.rnn import rnn_param_size
from ..block import HybridBlock

__all__ = ["RNN", "LSTM", "GRU"]


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, mode, **kwargs):
        super().__init__(**kwargs)
        assert layout in ("TNC", "NTC"), \
            "Invalid layout %s; must be one of ['TNC' or 'NTC']" % layout
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        psize = rnn_param_size(num_layers, input_size, hidden_size,
                               bidirectional, mode) if input_size else 0
        self.parameters = self.params.get(
            "parameters", shape=(psize,), allow_deferred_init=True)

    def state_info(self, batch_size=0):
        if self._mode == "lstm":
            return [{"shape": (self._num_layers * self._dir, batch_size,
                               self._hidden_size)},
                    {"shape": (self._num_layers * self._dir, batch_size,
                               self._hidden_size)}]
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size)}]

    def begin_state(self, batch_size=0, func=None, **kwargs):
        states = []
        for info in self.state_info(batch_size):
            if func is None:
                states.append(nd.zeros(**info))
            else:
                info.update(kwargs)
                states.append(func(**info))
        return states

    def infer_shape(self, x, *states):
        in_size = x.shape[-1]
        self._input_size = in_size
        self.parameters.shape = (rnn_param_size(
            self._num_layers, in_size, self._hidden_size, self._dir == 2,
            self._mode),)

    def __call__(self, inputs, states=None):
        skip_states = states is None
        if skip_states:
            batch = inputs.shape[self._layout.find("N")]
            states = self.begin_state(batch)
        if not isinstance(states, (list, tuple)):
            states = [states]
        out = super().__call__(inputs, *states)
        outputs, out_states = out[0], list(out[1:])
        if skip_states:
            return outputs
        return outputs, out_states

    def hybrid_forward(self, F, inputs, *states, **params):
        parameters = params["parameters"]
        if self._layout == "NTC":
            inputs = F.swapaxes(inputs, dim1=0, dim2=1)
        rnn_args = [inputs, parameters] + list(states)
        out = F.RNN(*rnn_args, state_size=self._hidden_size,
                    num_layers=self._num_layers,
                    bidirectional=self._dir == 2, mode=self._mode,
                    p=self._dropout, state_outputs=True)
        outputs = out[0]
        out_states = list(out[1:])
        if self._layout == "NTC":
            outputs = F.swapaxes(outputs, dim1=0, dim2=1)
        return tuple([outputs] + out_states)

    def __repr__(self):
        return "{}({}, {}, num_layers={}, dropout={}, bidirectional={})" \
            .format(self.__class__.__name__, self._input_size or "None",
                    self._hidden_size, self._num_layers, self._dropout,
                    self._dir == 2)


class RNN(_RNNLayer):
    """Elman RNN layer (reference rnn_layer.py:RNN)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False,
                 input_size=0, **kwargs):
        mode = "rnn_relu" if activation == "relu" else "rnn_tanh"
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, mode, **kwargs)


class LSTM(_RNNLayer):
    """LSTM layer (reference rnn_layer.py:LSTM)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, "lstm", **kwargs)


class GRU(_RNNLayer):
    """GRU layer (reference rnn_layer.py:GRU)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, "gru", **kwargs)
