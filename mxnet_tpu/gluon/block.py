"""Gluon Block / HybridBlock.

Port of /root/reference/python/mxnet/gluon/block.py (Block :115,
HybridBlock :283, hybridize→CachedOp :361-363), TPU-native:

- Imperative (non-hybridized) calls run eager NDArray ops on the autograd
  tape, exactly like the reference.
- ``hybridize()`` builds a **CachedOp = one jitted XLA program** for the
  whole block: the block's ``hybrid_forward`` is traced with a functional
  namespace (``F`` = raw-jnp shim over the op registry) over input + param
  tracers; BatchNorm-style auxiliary state updates are captured during
  tracing and returned as extra outputs, then written back — the same
  contract the reference's CachedOp had with mutable aux NDArrays
  (src/c_api/c_api_ndarray.cc:616-651).  Backward goes through the
  imperative tape as a single VJP of the fused program.

Deferred parameter shapes (zeros in shape) resolve on the first eager
forward via per-layer ``infer_shape`` hooks, mirroring the reference's
deferred init.
"""
from __future__ import annotations

import threading

import jax
import jax.numpy as jnp

from .. import autograd
from .. import ndarray as nd
from ..base import MXNetError
from ..ndarray.ndarray import NDArray, imperative_invoke
from ..ops import get_op
from ..ops.registry import OpDef
from .parameter import Parameter, ParameterDict, DeferredInitializationError

__all__ = ["Block", "HybridBlock", "SymbolBlock"]


# ---------------------------------------------------------------------------
# Functional namespace for tracing (F when hybridized)
# ---------------------------------------------------------------------------

_TRACE_STATE = threading.local()


class _TraceCtx:
    def __init__(self, param_tracers, rng, train, symbolic=False):
        self.param_tracers = param_tracers
        self.rng = rng
        self.train = train
        self.symbolic = symbolic  # tracers are Symbols, F emits graph nodes
        self.counter = 0
        self.aux_updates = []  # (id(aux_tracer), new_value)


def _trace_ctx():
    return getattr(_TRACE_STATE, "ctx", None)


class _JnpF:
    """F for traced execution: registry ops over raw jnp arrays."""

    def __getattr__(self, name):
        op = get_op(name)

        def call(*args, **params):
            ctx = _trace_ctx()
            args = list(args)
            if op.takes_train:
                params["_train"] = ctx.train if ctx else False
            if op.needs_rng:
                if ctx is not None:
                    key = jax.random.fold_in(ctx.rng, ctx.counter)
                    ctx.counter += 1
                else:
                    from .. import random as _random
                    key = _random.next_key()
                args.append(key)
            out = op.fn(*args, **op.canon_params(params))
            flat = list(out) if isinstance(out, (tuple, list)) else [out]
            n_vis = op.num_outputs(params)
            vis, extra = flat[:n_vis], flat[n_vis:]
            if extra and ctx is not None:
                # trailing aux inputs correspond 1:1 to the extras
                aux_args = args[len(args) - len(extra) -
                                (1 if op.needs_rng else 0):
                                len(args) - (1 if op.needs_rng else 0)]
                for a, v in zip(aux_args, extra):
                    ctx.aux_updates.append((id(a), v))
            if len(vis) == 1:
                return vis[0]
            return tuple(vis)
        call.__name__ = name
        return call


_F_JNP = _JnpF()


class _SymF:
    """F for SYMBOLIC hybridize tracing: registry ops emitting Symbol
    graph nodes, so a HybridBlock lowers through the graph rewrite
    pipeline (mxnet_tpu.graph) exactly like a Module bind.  Aux states
    ride as positional inputs (symbol._apply_op fills aux slots)."""

    def __getattr__(self, name):
        from ..symbol import symbol as _sym
        return _sym.make_symbol_function(get_op(name), name)


_F_SYM = _SymF()


# ---------------------------------------------------------------------------
# Name scoping
# ---------------------------------------------------------------------------

class _BlockScope:
    """Name/prefix management (reference block.py:29)."""

    _current = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                prefix = _global_count(hint) + "_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            prefix = "%s%d_" % (hint, count)
            current._counter[hint] = count + 1
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        if self._block._empty_prefix:
            return self
        self._old_scope = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        return self

    def __exit__(self, ptype, value, trace):
        if self._block._empty_prefix:
            return
        _BlockScope._current.value = self._old_scope


_GLOBAL_COUNTERS = {}


def _global_count(hint):
    count = _GLOBAL_COUNTERS.get(hint, 0)
    _GLOBAL_COUNTERS[hint] = count + 1
    return "%s%d" % (hint, count)


# ---------------------------------------------------------------------------
# Block
# ---------------------------------------------------------------------------

class Block:
    """Base of all layers and models (reference gluon/block.py:115)."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(
            prefix, params, self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") \
            else self._prefix
        self._scope = _BlockScope(self)
        self._children = []
        self._reg_params = {}

    def _alias(self):
        return self.__class__.__name__.lower()

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join(
            "  ({key}): {block}".format(
                key=i, block=_indent(str(block), 2))
            for i, block in enumerate(self._children))
        return s.format(name=self.__class__.__name__, modstr=modstr) \
            if self._children else self.__class__.__name__ + "()"

    def __setattr__(self, name, value):
        if hasattr(self, name):
            existing = getattr(self, name)
            if isinstance(existing, (Parameter, Block)) and \
                    not isinstance(value, type(existing)):
                raise TypeError("Changing attribute type for %s from %s "
                                "to %s is not allowed." %
                                (name, type(existing), type(value)))
        if isinstance(value, Block):
            self.register_child(value)
        elif isinstance(value, Parameter):
            assert name not in self._reg_params or \
                self._reg_params[name] is value, \
                "Overriding Parameter attribute %s is not allowed." % name
            self._reg_params[name] = value
        super().__setattr__(name, value)

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        return self._scope

    @property
    def params(self):
        return self._params

    def collect_params(self):
        ret = ParameterDict(self._params.prefix)
        ret.update(self.params)
        for child in self._children:
            ret.update(child.collect_params())
        return ret

    def save_params(self, filename):
        self.collect_params().save(filename, strip_prefix=self.prefix)

    def load_params(self, filename, ctx=None, allow_missing=False,
                    ignore_extra=False):
        self.collect_params().load(filename, ctx, allow_missing,
                                   ignore_extra, restore_prefix=self.prefix)

    def register_child(self, block):
        self._children.append(block)

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        self.collect_params().initialize(init, ctx, verbose,
                                         force_reinit=force_reinit)

    def hybridize(self, active=True):
        for child in self._children:
            child.hybridize(active)

    def cast(self, dtype):
        for child in self._children:
            child.cast(dtype)
        for _, param in self.params.items():
            param.cast(dtype)

    def __call__(self, *args, **kwargs):
        return self.forward(*args, **kwargs)

    def forward(self, *args, **kwargs):
        raise NotImplementedError


def _indent(s, num_spaces):
    lines = s.split("\n")
    first = lines.pop(0)
    return first + ("\n" + " " * num_spaces).join([""] + lines) \
        if lines else first


# ---------------------------------------------------------------------------
# HybridBlock
# ---------------------------------------------------------------------------

class HybridBlock(Block):
    """Block convertible to one fused XLA program (reference block.py:283)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._cached_op = None
        self._cached_param_list = None
        self._cached_graph_report = None  # rewrite-pipeline pass report

    def hybridize(self, active=True):
        self._active = active
        self._cached_op = None
        super().hybridize(active)

    def cast(self, dtype):
        self._cached_op = None
        super().cast(dtype)

    def register_child(self, block):
        if not isinstance(block, HybridBlock):
            raise ValueError(
                "Children of HybridBlock must also be HybridBlock, but %s "
                "has type %s." % (str(block), str(type(block))))
        super().register_child(block)
        self._cached_op = None

    # -- eager path --------------------------------------------------------
    def _call_eager(self, *args, **kwargs):
        try:
            params = {k: p.data() for k, p in self._reg_params.items()}
        except DeferredInitializationError:
            self._finish_deferred(*args)
            params = {k: p.data() for k, p in self._reg_params.items()}
        return self.hybrid_forward(nd, *args, **kwargs, **params)

    def _finish_deferred(self, *args):
        self.infer_shape(*args)
        for p in self._reg_params.values():
            if p._deferred_init is not None:
                p._finish_deferred_init()

    def infer_shape(self, *args):
        """Layers with deferred params override this to fill shapes."""
        raise MXNetError(
            "Deferred initialization failed because shape cannot be "
            "inferred for %s. Override infer_shape." % self.name)

    # -- traced path -------------------------------------------------------
    def _call_traced(self, *args, **kwargs):
        ctx = _trace_ctx()
        params = {}
        for k, p in self._reg_params.items():
            tracer = ctx.param_tracers.get(p.name)
            if tracer is None:
                raise MXNetError("parameter %s missing from trace" % p.name)
            params[k] = tracer
        F = _F_SYM if ctx is not None and ctx.symbolic else _F_JNP
        return self.hybrid_forward(F, *args, **kwargs, **params)

    def _build_symbolic_cached_op(self, nd_args, ordered, diff_params,
                                  aux_params):
        """Lower this block through the symbol graph + rewrite pipeline:
        trace ``hybrid_forward`` with a Symbol-emitting F, run
        ``graph.optimize`` over the result (conv→bn→act folding, dense
        fusion, constant folding, CSE/DCE — same passes as a Module
        bind), and evaluate the OPTIMIZED graph as the CachedOp body.
        Returns the OpDef, or None when this block cannot trace
        symbolically (shape introspection, raw-jnp math, kernels outside
        the op registry — e.g. the GPT attention stack) — then the
        jnp-tracing CachedOp below serves exactly as before."""
        from .. import graph as _graph
        from ..symbol import symbol as _sym
        try:
            in_syms = [_sym.Variable("in%d" % i)
                       for i in range(len(nd_args))]
            param_syms = {}
            for p in ordered:
                v = _sym.Variable(p.name)
                if p.grad_req == "null":
                    v._outputs[0][0].is_aux_var = True
                param_syms[p.name] = v
            prev = _trace_ctx()
            _TRACE_STATE.ctx = _TraceCtx(param_syms, None, True,
                                         symbolic=True)
            try:
                out = self._call_traced(*in_syms)
            finally:
                _TRACE_STATE.ctx = prev
            outs = list(out) if isinstance(out, (tuple, list)) else [out]
            if not outs or not all(isinstance(o, _sym.Symbol)
                                   for o in outs):
                return None
            sym = _sym.Group(outs) if len(outs) > 1 else outs[0]
            opt_sym, report = _graph.optimize(sym)
            eval_fn = _graph.make_eval_fn(_graph.Graph.from_symbol(opt_sym))
        except Exception:
            return None
        n_in = len(nd_args)
        in_names = ["in%d" % i for i in range(n_in)]
        diff_names = [p.name for p in diff_params]
        aux_names = [p.name for p in aux_params]
        n_out = len(sym._outputs)

        def cached_fn(*flat, _train=False):
            rng = flat[-1]
            arg_vals = dict(zip(in_names, flat[:n_in]))
            pvals = flat[n_in:-1]
            arg_vals.update(zip(diff_names, pvals[:len(diff_names)]))
            aux_vals = dict(zip(aux_names, pvals[len(diff_names):]))
            outs_v, new_aux = eval_fn(arg_vals, aux_vals, rng, _train)
            aux_out = [new_aux.get(n, aux_vals[n]) for n in aux_names]
            return tuple(outs_v) + tuple(aux_out)

        op = OpDef("_cachedop_%s" % self.name, cached_fn,
                   arg_names=tuple(in_names) + tuple(diff_names),
                   aux_names=tuple(aux_names),
                   num_outputs=n_out, mutate_aux=True,
                   needs_rng=True, takes_train=True)
        self._cached_graph_report = report
        return op

    def _build_cached_op(self, nd_args):
        plist = list(self.collect_params().values())
        diff_params = [p for p in plist if p.grad_req != "null"]
        aux_params = [p for p in plist if p.grad_req == "null"]
        ordered = diff_params + aux_params
        n_in = len(nd_args)
        n_aux = len(aux_params)
        outer = self

        from .. import graph as _graph
        if _graph.enabled():
            op = self._build_symbolic_cached_op(nd_args, ordered,
                                                diff_params, aux_params)
            if op is not None:
                self._cached_op = op
                self._cached_param_list = ordered
                return op

        def cached_fn(*flat, _train=False):
            # flat = inputs, diff params, aux params, rng
            rng = flat[-1]
            inputs = flat[:n_in]
            param_vals = flat[n_in:-1]
            tracers = {p.name: v for p, v in zip(ordered, param_vals)}
            prev = _trace_ctx()
            ctx = _TraceCtx(tracers, rng, _train)
            _TRACE_STATE.ctx = ctx
            try:
                out = outer._call_traced(*inputs)
            finally:
                _TRACE_STATE.ctx = prev
            outs = list(out) if isinstance(out, (tuple, list)) else [out]
            new_aux = []
            for p in aux_params:
                tr = tracers[p.name]
                upd = next((v for i_, v in ctx.aux_updates
                            if i_ == id(tr)), tr)
                new_aux.append(upd)
            return tuple(outs) + tuple(new_aux)

        # probe output count with an abstract eval
        probe_args = [jax.ShapeDtypeStruct(a.shape, a.dtype)
                      for a in nd_args]
        probe_params = [jax.ShapeDtypeStruct(p.data().shape,
                                             p.data().dtype)
                        for p in ordered]
        probe_rng = jax.ShapeDtypeStruct((2,), jnp.uint32)
        probe = jax.eval_shape(
            lambda *f: cached_fn(*f, _train=True),
            *probe_args, *probe_params, probe_rng)
        n_out = len(probe) - n_aux

        op = OpDef("_cachedop_%s" % self.name, cached_fn,
                   arg_names=tuple("in%d" % i for i in range(n_in)) +
                   tuple(p.name for p in diff_params),
                   aux_names=tuple(p.name for p in aux_params),
                   num_outputs=n_out, mutate_aux=True,
                   needs_rng=True, takes_train=True)
        self._cached_op = op
        self._cached_param_list = ordered
        return op

    def _call_cached(self, *args):
        try:
            for p in self.collect_params().values():
                p._check_initialized()
        except DeferredInitializationError:
            self._finish_deferred_recursive(*args)
        if self._cached_op is None:
            op = self._build_cached_op(args)
        else:
            op = self._cached_op
        inputs = list(args) + [p.data() for p in self._cached_param_list]
        return imperative_invoke(op, inputs, {})

    def _finish_deferred_recursive(self, *args):
        # one eager pass resolves all nested deferred shapes
        with autograd.pause():
            self.forward_eager_once(*args)

    def forward_eager_once(self, *args):
        self._active, saved = False, self._active
        try:
            self(*args)
        finally:
            self._active = saved

    # -- dispatch ----------------------------------------------------------
    def forward(self, *args, **kwargs):
        first = args[0] if args else None
        if isinstance(first, NDArray):
            # kwargs (e.g. loss pred_lengths) bypass the cached-op path —
            # the op registry is positional-only
            if self._active and not kwargs:
                return self._call_cached(*args)
            return self._call_eager(*args, **kwargs)
        if _trace_ctx() is not None:
            return self._call_traced(*args, **kwargs)
        # raw jnp arrays outside a trace: run functionally (inference)
        prev = _trace_ctx()
        from .. import random as _random
        ctx = _TraceCtx({p.name: p.data()._data
                         for p in self.collect_params().values()},
                        _random.next_key(), autograd.is_training())
        _TRACE_STATE.ctx = ctx
        try:
            return self._call_traced(*args)
        finally:
            _TRACE_STATE.ctx = prev

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class SymbolBlock(HybridBlock):
    """Wrap a Symbol + params as a Block (reference block.py:SymbolBlock)."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix="", params=params)
        from .. import symbol as sym_mod
        if isinstance(inputs, sym_mod.Symbol):
            inputs = [inputs]
        if isinstance(outputs, (list, tuple)):
            outputs = sym_mod.Group(outputs)
        self._symbol = outputs
        self._input_names = [i.name for i in inputs]
        arg_names = outputs.list_arguments()
        aux_names = set(outputs.list_auxiliary_states())
        for name in arg_names:
            if name not in self._input_names:
                self.params.get(name[len(self.params.prefix):]
                                if name.startswith(self.params.prefix)
                                else name, allow_deferred_init=True)
        for name in outputs.list_auxiliary_states():
            p = self.params.get(name[len(self.params.prefix):]
                                if name.startswith(self.params.prefix)
                                else name, grad_req="null",
                                allow_deferred_init=True)
        self._aux_names = aux_names

    def forward(self, *args):
        feed = dict(zip(self._input_names, args))
        arg_dict = {}
        aux_dict = {}
        for name, p in self.params.items():
            if name in self._aux_names:
                aux_dict[name] = p.data()
            else:
                arg_dict[name] = p.data()
        arg_dict.update(feed)
        exe = self._symbol.bind(args=arg_dict, aux_states=aux_dict,
                                grad_req="null")
        outs = exe.forward(is_train=autograd.is_training())
        return outs[0] if len(outs) == 1 else outs


def functionalize(net, *example_args, train=False):
    """Extract a pure, jittable function from a HybridBlock.

    The TPU-native analogue of exporting a CachedOp
    (/root/reference/src/c_api/c_api_ndarray.cc:616): returns
    ``(apply_fn, params)`` where ``apply_fn(params, *inputs, rng=None)``
    is a pure JAX function (safe under jit/grad/pjit) and ``params`` is the
    list of current parameter values (jax arrays) in the order apply_fn
    expects.  Differentiable parameters come first, then auxiliary states
    (BatchNorm moving stats); ``apply_fn`` returns (outputs_tuple,
    new_aux_tuple) so training loops can carry the aux updates.
    """
    nd_args = tuple(a if isinstance(a, NDArray) else NDArray(jnp.asarray(a))
                    for a in example_args)
    try:
        for p in net.collect_params().values():
            p._check_initialized()
    except DeferredInitializationError:
        net._finish_deferred_recursive(*nd_args)
    op = net._build_cached_op(nd_args)
    plist = net._cached_param_list
    n_aux = sum(1 for p in plist if p.grad_req == "null")
    n_out = op.num_outputs({})

    def apply_fn(params, *inputs, rng=None):
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        flat = op.fn(*inputs, *params, rng, _train=train)
        outs = flat[:n_out]
        new_aux = flat[n_out:]
        return outs, new_aux

    params = [p.data()._data for p in plist]
    apply_fn.param_names = [p.name for p in plist]
    apply_fn.num_aux = n_aux
    return apply_fn, params
