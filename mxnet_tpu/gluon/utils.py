"""Gluon utilities (reference python/mxnet/gluon/utils.py)."""
from __future__ import annotations

import hashlib
import math
import os

from .. import ndarray as nd

__all__ = ["split_data", "split_and_load", "shard_and_load",
           "clip_global_norm", "check_sha1", "download"]


def shard_and_load(data, ctx_list, batch_axis=0):
    """dp-shard one batch over the mesh formed by ``ctx_list``.

    TPU-native sibling of :func:`split_and_load`: where the reference split
    the batch into per-GPU slices for per-device executors
    (/root/reference/python/mxnet/gluon/utils.py:66), this returns ONE
    NDArray whose batch axis is sharded across the devices — the model runs
    once as a single SPMD program and XLA inserts the gradient all-reduce.
    Use with parameters initialized via ``initialize(ctx=ctx_list)``.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec
    from ..parallel.mesh import AXIS_DP, dp_mesh_from_ctx
    if not isinstance(data, nd.NDArray):
        data = nd.array(data)
    if not isinstance(ctx_list, (list, tuple)):
        ctx_list = [ctx_list]
    if len(ctx_list) == 1:
        return data.as_in_context(ctx_list[0])
    if data.shape[batch_axis] % len(ctx_list):
        raise ValueError(
            "batch axis %d of shape %s not divisible by %d devices"
            % (batch_axis, data.shape, len(ctx_list)))
    mesh = dp_mesh_from_ctx(ctx_list)
    spec = [None] * data.ndim
    spec[batch_axis] = AXIS_DP
    placed = jax.device_put(data._data,
                            NamedSharding(mesh, PartitionSpec(*spec)))
    return nd.NDArray(placed, ctx_list[0])


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Split along batch_axis into num_slice chunks (reference :28)."""
    size = data.shape[batch_axis]
    if size < num_slice:
        raise ValueError(
            "Too many slices for data with shape %s. Arguments are "
            "num_slice=%d and batch_axis=%d." %
            (str(data.shape), num_slice, batch_axis))
    if size % num_slice != 0:
        if even_split:
            raise ValueError(
                "data with shape %s cannot be evenly split into %d "
                "slices along axis %d. Use a batch size that's a multiple "
                "of %d or set even_split=False to allow uneven partial "
                "slices." % (str(data.shape), num_slice, batch_axis,
                             num_slice))
        step = int(math.ceil(size / num_slice))
        slices = [
            nd.slice_axis(data, axis=batch_axis, begin=i * step,
                          end=min((i + 1) * step, size))
            for i in range(num_slice)]
    else:
        step = size // num_slice
        slices = [
            nd.slice_axis(data, axis=batch_axis, begin=i * step,
                          end=(i + 1) * step)
            for i in range(num_slice)]
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split data and load each slice to a context (reference :66)."""
    if not isinstance(data, nd.NDArray):
        data = nd.array(data, ctx=ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [i.as_in_context(ctx) for i, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm):
    """Rescale arrays so their joint 2-norm ≤ max_norm (reference :89)."""
    assert len(arrays) > 0
    total_norm = 0.0
    for arr in arrays:
        total_norm += float(nd.sum(nd.square(arr)).asscalar())
    total_norm = math.sqrt(total_norm)
    scale = max_norm / (total_norm + 1e-8)
    if scale < 1.0:
        for arr in arrays:
            arr._set_data((arr * scale)._data)
    return total_norm


def check_sha1(filename, sha1_hash):
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None):
    """Download a file (reference :121). Disabled in air-gapped builds —
    raises with instructions rather than hanging on zero egress."""
    raise RuntimeError(
        "download() is unavailable in this offline build; place the file "
        "locally and pass its path instead (url was %s)" % url)
