"""Model zoo (reference python/mxnet/gluon/model_zoo/__init__.py)."""
from . import vision
from . import gpt
from .gpt import gpt2_tiny, gpt2_small, gpt2_medium, get_gpt
