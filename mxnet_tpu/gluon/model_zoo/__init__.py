"""Model zoo (reference python/mxnet/gluon/model_zoo/__init__.py)."""
from . import vision
