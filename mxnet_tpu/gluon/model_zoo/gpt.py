"""GPT-2-class decoder language models — the transformer flagship.

TPU-native addition: the 2017 reference predates attention entirely (its
sequence story is bucketing, /root/reference/python/mxnet/module/
bucketing_module.py), but a TPU framework's MFU headline lives in
transformer matmuls, so the model zoo carries a decoder LM family built
on the Pallas flash-attention kernel (ops/pallas/flash_attention.py)
through the Gluon layer API (nn.FlashSelfAttention).

Design notes (all MXU-motivated):
- pre-LN residual blocks (stable in bf16 without warmup tricks);
- gelu(tanh) MLP at 4x width — two large [T, d]x[d, 4d] matmuls XLA
  tiles straight onto the systolic array;
- weight-tied embedding/head: logits ride one [B·T, d] x [d, V]
  FullyConnected against the embedding table, so the V-sized matmul
  appears exactly once per step;
- vocab padded to a multiple of 128 by the factory functions (lane
  dimension of the MXU; 50257 → 50304 exactly like megatron-era configs).

Weights save/load in the reference's V2 binary format like every other
zoo model (ndarray/serialization.py), so the fine-tune workflow
(example/language-model) round-trips through ``Module.load``.
"""
from __future__ import annotations

import functools

from .. import nn
from ..block import HybridBlock

__all__ = ["GPTBlock", "GPTLM", "get_gpt", "gpt2_tiny",
           "gpt2_tiny_moe", "gpt2_small", "gpt2_medium",
           "pack_sequences", "packed_positions", "generate",
           "decode_params", "paged_decode_step", "paged_prefill",
           "paged_suffix_prefill", "sample_tokens"]


class GPTBlock(HybridBlock):
    """One pre-LN transformer decoder block.

    ``moe_experts > 0`` swaps the dense gelu MLP for a GShard-style
    top-1-gated mixture of experts (parallel/moe.py): off-mesh the
    experts run locally (``moe_dense``); after
    :meth:`GPTLM.expert_parallel` they shard over the ``ep`` mesh axis
    with all_to_all dispatch — the flagship's fifth mesh axis.

    Scope note: routing is top-1 with a capacity bound and NO auxiliary
    load-balancing loss — adequate at the tested scales (the gate
    trains through the combine weights); large-scale MoE pretraining
    conventionally adds a Switch-style balance term, which needs the
    per-block gate logits plumbed to the loss (a possible extension)."""

    def __init__(self, units, num_heads, mlp_ratio=4, dropout=0.0,
                 moe_experts=0, moe_capacity=2.0, **kwargs):
        super().__init__(**kwargs)
        self._dropout = dropout
        self._moe = int(moe_experts)
        self._moe_capacity = moe_capacity
        self._moe_mesh = None
        with self.name_scope():
            self.ln1 = nn.LayerNorm(in_channels=units, prefix="ln1_")
            self.attn = nn.FlashSelfAttention(units, num_heads,
                                              causal=True,
                                              in_units=units,
                                              prefix="attn_")
            self.ln2 = nn.LayerNorm(in_channels=units, prefix="ln2_")
            if self._moe:
                e, f = self._moe, mlp_ratio * units
                self.moe_gate = self.params.get(
                    "moe_gate_weight", shape=(units, e))
                self.moe_w1 = self.params.get("moe_fc1_weight",
                                              shape=(e, units, f))
                self.moe_b1 = self.params.get("moe_fc1_bias",
                                              shape=(e, f))
                self.moe_w2 = self.params.get("moe_fc2_weight",
                                              shape=(e, f, units))
                self.moe_b2 = self.params.get("moe_fc2_bias",
                                              shape=(e, units))
            else:
                self.fc1 = nn.Dense(mlp_ratio * units, flatten=False,
                                    in_units=units, prefix="fc1_")
                self.fc2 = nn.Dense(units, flatten=False,
                                    in_units=mlp_ratio * units,
                                    prefix="fc2_")

    def expert_parallel(self, mesh, axis="ep", batch_axis=None):
        """Shard this block's experts over ``mesh``'s ``axis`` —
        tokens all_to_all to their expert's device (parallel.moe_apply).
        Traced path only; ``mesh=None`` restores local experts."""
        self._moe_mesh = (None if mesh is None
                          else (mesh, axis, batch_axis))
        self._cached_op = None

    def _moe_forward(self, F, h, moe_params):
        import jax
        from ... import parallel as _par
        from ... import autograd as _ag
        gate_w, w1, b1, w2, b2 = moe_params
        if hasattr(h, "_data"):
            if _ag.is_recording():
                raise RuntimeError(
                    "MoE blocks do not support the imperative autograd "
                    "tape; train through functionalize/jit")
            if self._moe_mesh is not None:
                raise RuntimeError(
                    "imperative inference with expert_parallel active: "
                    "call expert_parallel(None) first (the ep shard_map "
                    "needs the jit/functionalize path)")

        def _raw(a):
            return a._data if hasattr(a, "_data") else a
        hj = _raw(h)
        b, t, d = hj.shape
        flat = hj.reshape(b * t, d)
        args = tuple(_raw(a) for a in (gate_w, w1, b1, w2, b2))
        if self._moe_mesh is None:
            out = _par.moe.moe_dense(
                flat, *args, capacity_factor=self._moe_capacity,
                act=jax.nn.gelu)
        else:
            mesh, axis, batch_axis = self._moe_mesh
            out = _par.moe_apply(
                flat, *args, mesh=mesh, axis=axis,
                batch_axis=batch_axis,
                capacity_factor=self._moe_capacity, act=jax.nn.gelu)
        out = out.reshape(b, t, d)
        if hasattr(h, "_data"):
            # imperative (inference) caller: rewrap so the residual add
            # stays in the NDArray domain
            from ...ndarray import NDArray
            return NDArray(out)
        return out

    def hybrid_forward(self, F, x, segments=None, moe_gate=None,
                       moe_w1=None, moe_b1=None, moe_w2=None,
                       moe_b2=None):
        if segments is None:
            h = self.attn(self.ln1(x))
        else:
            h = self.attn(self.ln1(x), segments)
        if self._dropout:
            h = F.Dropout(h, p=self._dropout)
        x = x + h
        if self._moe:
            h = self._moe_forward(F, self.ln2(x),
                                  (moe_gate, moe_w1, moe_b1, moe_w2,
                                   moe_b2))
        else:
            h = self.fc2(F.Activation(self.fc1(self.ln2(x)),
                                      act_type="gelu"))
        if self._dropout:
            h = F.Dropout(h, p=self._dropout)
        return x + h


class GPTLM(HybridBlock):
    """Decoder-only LM: token + learned position embeddings, N blocks,
    final LayerNorm, tied output head.

    Input: int token ids [B, T] (T ≤ max_len); output: logits [B, T, V].
    """

    def __init__(self, vocab_size, num_layers, units, num_heads,
                 max_len=1024, dropout=0.0, remat=False, moe_experts=0,
                 moe_capacity=2.0, **kwargs):
        super().__init__(**kwargs)
        self._vocab = vocab_size
        self._units = units
        self._max_len = max_len
        self._dropout = dropout
        self._remat = remat
        with self.name_scope():
            self.wte = self.params.get("wte_weight",
                                       shape=(vocab_size, units))
            self.wpe = self.params.get("wpe_weight",
                                       shape=(max_len, units))
            self.blocks = nn.HybridSequential(prefix="h_")
            with self.blocks.name_scope():
                for _ in range(num_layers):
                    self.blocks.add(GPTBlock(units, num_heads,
                                             dropout=dropout,
                                             moe_experts=moe_experts,
                                             moe_capacity=moe_capacity))
            self.ln_f = nn.LayerNorm(in_channels=units, prefix="lnf_")

    def expert_parallel(self, mesh, axis="ep", batch_axis=None):
        """MoE switch: every block's experts shard over ``mesh``'s
        ``axis`` (tokens all_to_all to their expert's device —
        parallel/moe.py); ``mesh=None`` restores local experts.  Only
        meaningful when built with ``moe_experts > 0``."""
        for blk in self.blocks._children:
            blk.expert_parallel(mesh, axis=axis, batch_axis=batch_axis)
        self._cached_op = None

    def sequence_parallel(self, mesh, axis="sp", batch_axis=None,
                          impl=None):
        """Long-context switch: every block's attention becomes RING
        attention over ``mesh``'s ``axis`` (sequence dim sharded,
        nearest-neighbour ICI hops — parallel/ring_attention.py), so
        ``gpt2_small(max_len=32k)`` trains on an sp mesh through this
        one call; packing segment ids keep riding the forward and are
        threaded through the ring hops.  Shard the [B, T] token batch
        with T over ``axis`` (and B over dp/``batch_axis`` if
        composing); everything outside attention is position-local, so
        XLA GSPMD keeps it sharded.  ``mesh=None`` restores the
        single-device flash kernel."""
        for blk in self.blocks._children:
            blk.attn.sequence_parallel(mesh, axis=axis,
                                       batch_axis=batch_axis, impl=impl)
            blk._cached_op = None
        self._cached_op = None

    def hybrid_forward(self, F, tokens, segments=None, wte=None,
                       wpe=None):
        t = tokens.shape[1]
        if t > self._max_len:
            raise ValueError("sequence length %d exceeds max_len %d"
                             % (t, self._max_len))
        h = F.Embedding(tokens, wte, input_dim=self._vocab,
                        output_dim=self._units)
        if segments is None:
            h = h + F.slice_axis(wpe, axis=0, begin=0, end=t)
        else:
            # packed rows: positions restart at each segment boundary so
            # every document trains with the same wpe rows it would see
            # standalone (segments are contiguous per row)
            pos = packed_positions(segments)
            h = h + F.Embedding(pos, wpe, input_dim=self._max_len,
                                output_dim=self._units)
        if self._dropout:
            h = F.Dropout(h, p=self._dropout)
        if self._remat and not hasattr(h, "_data"):
            # per-block rematerialisation: the backward recomputes each
            # block's activations instead of keeping them in HBM —
            # memory O(L·T·d) -> O(T·d) + one extra forward of FLOPs,
            # the standard long-sequence trade.  Applies on the TRACED
            # path only (hybrid values are jnp arrays there, which
            # jax.checkpoint needs); the imperative NDArray path records
            # op-by-op on the autograd tape, where remat has no meaning.
            import jax
            for blk in self.blocks._children:
                if segments is None:
                    h = jax.checkpoint(lambda x, b=blk: b(x))(h)
                else:
                    h = jax.checkpoint(
                        lambda x, s, b=blk: b(x, s))(h, segments)
        elif segments is None:
            h = self.blocks(h)
        else:
            # packed rows: thread the segment ids into every block's
            # attention (HybridSequential can't forward extra inputs)
            for blk in self.blocks._children:
                h = blk(h, segments)
        h = self.ln_f(h)
        # tied head: one [B·T, d] x [d, V] matmul against the embedding
        return F.FullyConnected(h, wte, num_hidden=self._vocab,
                                no_bias=True, flatten=False)


def _pad_vocab(v, mult=128):
    return (v + mult - 1) // mult * mult


def packed_positions(segments):
    """Per-row positions that RESTART at each segment boundary — the
    wpe rows a packed document sees equal its standalone ones.  ONE
    copy of this math: GPTLM's forward and the pipeline stage cutter
    (parallel/gpt_pp.py) both call it.  segments [B, T] -> int32 [B, T]."""
    import jax.numpy as jnp
    seg = segments if not hasattr(segments, "_data") else segments._data
    t = seg.shape[1]
    idx = jnp.arange(t)[None, :]
    change = jnp.concatenate(
        [jnp.ones_like(seg[:, :1], dtype=bool),
         seg[:, 1:] != seg[:, :-1]], axis=1)
    # lax.cummax, not jnp.maximum.accumulate: ufunc .accumulate methods
    # only exist in newer jax than this build (0.4.37)
    from jax import lax as _lax
    start = _lax.cummax(jnp.where(change, idx, 0), axis=1)
    return (idx - start).astype(jnp.int32)


def pack_sequences(docs, seq_len, pad_id=0):
    """Pack variable-length token sequences into fixed [N, seq_len] rows
    with segment ids — the TPU-first replacement for the reference's
    bucketing (static shapes keep ONE compiled program; the flash
    kernel's ``segment_ids`` mask keeps documents independent).

    ``docs``: iterable of 1-d int token arrays.  Returns (tokens,
    segments): int32 [N, seq_len] each.  Segments are 1-based per row;
    0 marks padding (give the attention mask a pad id no real segment
    uses and pad positions attend nothing real).

    A document that would not fit the current row's remaining space
    starts a FRESH row rather than being split — a split continuation
    restarts at position 0 with no attention to its earlier tokens
    (mid-document context truncation).  Only documents longer than
    ``seq_len`` itself are ever split (round-4 ADVICE).
    """
    import numpy as np
    rows, segs = [], []
    cur = np.full(seq_len, pad_id, np.int32)
    cur_seg = np.zeros(seq_len, np.int32)
    pos, seg_id = 0, 1
    for doc in docs:
        doc = np.asarray(doc, np.int32)
        if 0 < seq_len - pos < doc.size <= seq_len:
            rows.append(cur); segs.append(cur_seg)
            cur = np.full(seq_len, pad_id, np.int32)
            cur_seg = np.zeros(seq_len, np.int32)
            pos, seg_id = 0, 1
        while doc.size:
            if pos == seq_len:
                rows.append(cur); segs.append(cur_seg)
                cur = np.full(seq_len, pad_id, np.int32)
                cur_seg = np.zeros(seq_len, np.int32)
                pos, seg_id = 0, 1
            take = min(doc.size, seq_len - pos)
            cur[pos:pos + take] = doc[:take]
            cur_seg[pos:pos + take] = seg_id
            pos += take
            doc = doc[take:]
        seg_id += 1
    if pos:
        rows.append(cur); segs.append(cur_seg)
    return np.stack(rows), np.stack(segs)


# ---------------------------------------------------------------------------
# KV-cache incremental decoding
# ---------------------------------------------------------------------------

def _decode_params(net):
    """Index the net's current parameter values by layer for the decode
    path, walking the LIVE child blocks (``net.blocks[i].attn.qkv
    .weight`` etc.) — no name templates, so custom prefixes, subclassed
    blocks that keep the attribute layout, and ``use_bias=False`` all
    work, and a renamed child cannot silently desync generate() from
    the training forward (round-4 VERDICT weak #5 / ADVICE)."""
    import jax.numpy as jnp

    def g(param):
        return param.data()._data.astype(jnp.float32)

    def bias(dense):
        if dense.bias is None:
            return jnp.zeros((dense._units,), jnp.float32)
        return g(dense.bias)

    layers = []
    for blk in net.blocks._children:
        lp = {
            "ln1_g": g(blk.ln1.gamma), "ln1_b": g(blk.ln1.beta),
            "qkv_w": g(blk.attn.qkv.weight), "qkv_b": bias(blk.attn.qkv),
            "out_w": g(blk.attn.out_proj.weight),
            "out_b": bias(blk.attn.out_proj),
            "ln2_g": g(blk.ln2.gamma), "ln2_b": g(blk.ln2.beta)}
        if getattr(blk, "_moe", 0):
            lp["moe"] = tuple(g(p) for p in (
                blk.moe_gate, blk.moe_w1, blk.moe_b1, blk.moe_w2,
                blk.moe_b2))
        else:
            lp.update({"fc1_w": g(blk.fc1.weight),
                       "fc1_b": bias(blk.fc1),
                       "fc2_w": g(blk.fc2.weight),
                       "fc2_b": bias(blk.fc2)})
        layers.append(lp)
    return {"wte": g(net.wte), "wpe": g(net.wpe),
            "lnf_g": g(net.ln_f.gamma), "lnf_b": g(net.ln_f.beta),
            "layers": layers}


def _ln(x, g, b, eps=1e-5):
    import jax.numpy as jnp
    from jax import lax
    mu = x.mean(-1, keepdims=True)
    var = jnp.square(x - mu).mean(-1, keepdims=True)
    return (x - mu) * lax.rsqrt(var + eps) * g + b


def _block_qkv(lp, x, n_heads):
    """Shared per-layer front half: LN1 + fused head-major qkv.
    x [B, T, C] -> q, k, v [B, H, T, D] (layout from basic_layers.py's
    FlashSelfAttention; the ONE copy _prefill and _decode_one share)."""
    b, t, c = x.shape
    d = c // n_heads
    h = _ln(x, lp["ln1_g"], lp["ln1_b"])
    qkv = (h @ lp["qkv_w"].T + lp["qkv_b"]).reshape(b, t, n_heads, 3, d)
    qkv = qkv.transpose(0, 2, 1, 3, 4)           # [B, H, T, 3, D]
    return qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]


def _block_finish(lp, x, o):
    """Shared per-layer back half: attention output o [B, T, C] ->
    residual + LN2 + MLP (dense gelu or mixture of experts) +
    residual."""
    import jax
    x = x + o @ lp["out_w"].T + lp["out_b"]
    h = _ln(x, lp["ln2_g"], lp["ln2_b"])
    if "moe" in lp:
        from ...parallel.moe import moe_dense
        b, t, c = h.shape
        gate_w, w1, b1, w2, b2 = lp["moe"]
        # DROPLESS at inference (capacity == token count): GShard's
        # capacity dropping is a training-throughput trade whose queue
        # positions couple tokens across the batch — decode must stay
        # position-local to match the cache-free forward
        out = moe_dense(h.reshape(b * t, c), gate_w, w1, b1, w2, b2,
                        capacity_factor=float(w1.shape[0]),
                        act=jax.nn.gelu)
        return x + out.reshape(b, t, c)
    h = jax.nn.gelu(h @ lp["fc1_w"].T + lp["fc1_b"], approximate=True)
    return x + h @ lp["fc2_w"].T + lp["fc2_b"]


def _decode_one(p, tok, pos, caches, n_heads):
    """One decode step: tok [B] int32, pos scalar, caches list of
    (k_cache, v_cache) [B, H, T_max, D].  Returns (logits [B, V],
    new caches)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    x = p["wte"][tok][:, None] + lax.dynamic_index_in_dim(
        p["wpe"], pos, 0, keepdims=False)              # [B, 1, C]
    b, _, c = x.shape
    d = c // n_heads
    t_max = caches[0][0].shape[2]
    new_caches = []
    # keys at position > pos are zeros in the cache; mask them
    mask = (jnp.arange(t_max) <= pos)[None, None, :]
    for lp, (kc, vc) in zip(p["layers"], caches):
        q, k, v = _block_qkv(lp, x, n_heads)           # [B, H, 1, D]
        kc = lax.dynamic_update_index_in_dim(kc, k, pos, 2)
        vc = lax.dynamic_update_index_in_dim(vc, v, pos, 2)
        s = jnp.einsum("bhd,bhtd->bht", q[:, :, 0], kc) / jnp.sqrt(
            jnp.float32(d))
        s = jnp.where(mask, s, -1e30)
        pr = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bht,bhtd->bhd", pr, vc).reshape(b, 1, c)
        x = _block_finish(lp, x, o)
        new_caches.append((kc, vc))
    x = _ln(x[:, 0], p["lnf_g"], p["lnf_b"])
    return x @ p["wte"].T, new_caches


def _prefill(p, toks, t_max, n_heads):
    """One batched causal pass over the prompt: fills every layer's KV
    cache for positions [0, T0) and returns the last position's logits
    — replacing T0 sequential decode steps with one forward (the
    standard prefill/decode split; same parameter dict and layer math
    as ``_decode_one``, pinned together by the generate-vs-recompute
    equality tests)."""
    import jax
    import jax.numpy as jnp
    b, t0 = toks.shape
    x = p["wte"][toks] + p["wpe"][:t0][None]           # [B, T0, C]
    c = x.shape[-1]
    d = c // n_heads
    causal = jnp.tril(jnp.ones((t0, t0), bool))[None, None]
    pad_t = t_max - t0
    caches = []
    for lp in p["layers"]:
        q, k, v = _block_qkv(lp, x, n_heads)           # [B, H, T0, D]
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / jnp.sqrt(jnp.float32(d))
        s = jnp.where(causal, s, -1e30)
        pr = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhqk,bhkd->bhqd", pr, v)
        o = o.transpose(0, 2, 1, 3).reshape(b, t0, c)
        x = _block_finish(lp, x, o)
        kc = jnp.pad(k, ((0, 0), (0, 0), (0, pad_t), (0, 0)))
        vc = jnp.pad(v, ((0, 0), (0, 0), (0, pad_t), (0, 0)))
        caches.append((kc, vc))
    x = _ln(x[:, -1], p["lnf_g"], p["lnf_b"])          # [B, C]
    return x @ p["wte"].T, caches


def _filter_logits(logits, top_k, top_p):
    """Static top-k / nucleus filtering (jit-compatible: sort-based).
    Callers pass TEMPERATURE-SCALED logits — the nucleus must be the
    top_p mass of the actual sampling distribution."""
    import jax
    import jax.numpy as jnp
    if top_k:
        k = min(top_k, logits.shape[-1])
        kth = jax.lax.top_k(logits, k)[0][..., -1:]
        logits = jnp.where(logits < kth, -1e30, logits)
    if top_p:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest set whose mass >= top_p: keep entries whose cumsum
        # BEFORE them is < top_p
        keep_sorted = (cum - probs) < top_p
        cutoff = jnp.min(jnp.where(keep_sorted, sorted_logits, jnp.inf),
                         axis=-1, keepdims=True)
        logits = jnp.where(logits < cutoff, -1e30, logits)
    return logits


@functools.lru_cache(maxsize=32)
def _decode_runner(n_heads, greedy, n_new, t0, t_max,
                   top_k=0, top_p=0.0):
    """Build (once per static configuration) the jitted prefill+decode
    runner.  The prompt is consumed by ONE batched causal pass
    (``_prefill`` — fills all caches and yields the first new token's
    logits); only the n_new-1 truly sequential steps run in the
    ``lax.scan`` — long prompts cost one forward, not T0 scan
    iterations.  Params, prompt, key, and temperature are traced
    ARGUMENTS, so repeated generate() calls — and further training
    between them — hit jit's compile cache."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def pick(logits, key, temp):
        if greedy:
            return logits.argmax(-1).astype(jnp.int32), key
        key, sub = jax.random.split(key)
        scaled = _filter_logits(logits / temp, top_k, top_p)
        return (jax.random.categorical(sub, scaled, axis=-1)
                .astype(jnp.int32), key)

    def step(p, temp, carry, pos):
        caches, tok, key = carry
        logits, caches = _decode_one(p, tok, pos, caches, n_heads)
        nxt, key = pick(logits, key, temp)
        return (caches, nxt, key), nxt

    @jax.jit
    def run(p, prompt, key, temp):
        logits0, caches = _prefill(p, prompt, t_max, n_heads)
        first, key = pick(logits0, key, temp)
        if n_new == 1:
            return first[None]
        positions = jnp.arange(t0, t0 + n_new - 1)
        _, toks = lax.scan(functools.partial(step, p, temp),
                           (caches, first, key), positions)
        return jnp.concatenate([first[None], toks])  # [n_new, B]

    return run


def generate(net, prompt_ids, n_new, temperature=0.0, seed=0, top_k=0,
             top_p=0.0):
    """Autoregressive generation with a KV cache — ONE batched prefill
    pass over the prompt, then O(1) work per new token (vs the O(T²)
    full-context recompute).  The decode loop is one jitted
    ``lax.scan`` with static shapes (the cache is ``max_len`` long),
    TPU-friendly by construction; the compiled runner is cached per
    (shape, config), so repeated calls don't retrace.

    ``prompt_ids``: int array [B, T0]; returns int array
    [B, T0 + n_new].  temperature 0 = greedy; otherwise samples with
    ``jax.random`` (deterministic per ``seed``), optionally filtered to
    the ``top_k`` highest logits and/or the ``top_p`` nucleus.
    """
    import numpy as np
    import jax
    import jax.numpy as jnp

    prompt = jnp.asarray(np.asarray(prompt_ids), jnp.int32)
    bsz, t0 = prompt.shape
    t_max = net._max_len
    if n_new < 1:
        raise ValueError("n_new must be >= 1, got %d" % n_new)
    if t0 + n_new > t_max:
        raise ValueError("prompt %d + new %d exceeds max_len %d"
                         % (t0, n_new, t_max))
    n_heads = net.blocks._children[0].attn._num_heads
    p = _decode_params(net)

    greedy = temperature <= 0
    run = _decode_runner(n_heads, greedy, n_new, t0, t_max,
                         0 if greedy else int(top_k),
                         0.0 if greedy else float(top_p))
    toks = run(p, prompt, jax.random.PRNGKey(seed),
               jnp.float32(max(temperature, 1e-6)))
    return np.asarray(jnp.concatenate([prompt, toks.T], axis=1))


# ---------------------------------------------------------------------------
# paged / slot-addressable decoding (the serving runtime's model half)
# ---------------------------------------------------------------------------
#
# mxnet_tpu/serving/ keeps KV history in fixed-size pages with per-slot
# block tables (serving/kv_cache.py) so requests of any length share one
# decode program.  These two pure functions are the model's contract with
# that runtime: same parameter dict (_decode_params) and per-layer math
# (_block_qkv/_block_finish) as generate()'s dense-cache path — the
# equivalence tests in tests/test_serving.py pin the three paths (dense
# generate, paged decode, training forward) together.


def _apply_precision(p, policy):
    """Cast a decode-param tree per a PrecisionPolicy (None = as-is)."""
    if policy is None:
        return p
    out = {k: policy.cast_params(v, "embed" if k in ("wte", "wpe")
                                 else "final")
           for k, v in p.items() if k != "layers"}
    out["layers"] = [policy.cast_params(lp, "blocks.%d" % i)
                     for i, lp in enumerate(p["layers"])]
    return out


def decode_params(net, kv_heads=None, policy=None):
    """Public alias of the decode-path parameter indexer (fp32 values
    keyed by layer) — the tree ``paged_decode_step``/``paged_prefill``
    take as ``p``, and what :class:`mxnet_tpu.serving.ServingEngine`
    snapshots at construction.

    ``policy``: optional :class:`mxnet_tpu.precision.PrecisionPolicy`.
    Each transformer block's leaves are cast to the policy's resolved
    ``param`` dtype for ``blocks.<i>``; the embeddings and final LN
    resolve under ``embed`` / ``final`` — serving precision is one
    instance of the general per-layer policy, with the KV-page dtype
    (``policy.kv_dtype``) handled separately by the engine's pools.

    ``kv_heads``: serve with ``K_kv <= H`` KV heads (grouped-query /
    multi-query attention, ISSUE 15).  ``None`` or ``H`` keeps the
    trained multi-head layout bit-identical to before; a smaller value
    MEAN-POOLS each group's K/V projection rows (the standard
    MHA->GQA uptraining conversion, Ainslie et al.) so the serving KV
    pools shrink ``H / K_kv``-fold.  The converted layer dicts carry
    split ``q_w``/``k_w``/``v_w`` (+biases) instead of the fused
    ``qkv_w``."""
    p = _decode_params(net)
    if kv_heads is None:
        return _apply_precision(p, policy)
    n_heads = net.blocks._children[0].attn._num_heads
    kv_heads = int(kv_heads)
    if kv_heads == n_heads:
        return _apply_precision(p, policy)
    if kv_heads < 1 or n_heads % kv_heads:
        raise ValueError(
            "kv_heads must divide the model's %d query heads, got %d"
            % (n_heads, kv_heads))
    d = int(p["wte"].shape[1]) // n_heads
    g = n_heads // kv_heads
    for lp in p["layers"]:
        w = lp.pop("qkv_w").reshape(n_heads, 3, d, -1)
        b = lp.pop("qkv_b").reshape(n_heads, 3, d)
        lp["q_w"] = w[:, 0].reshape(n_heads * d, -1)
        lp["q_b"] = b[:, 0].reshape(n_heads * d)
        for name, idx in (("k", 1), ("v", 2)):
            lp[name + "_w"] = (w[:, idx].reshape(kv_heads, g, d, -1)
                               .mean(axis=1).reshape(kv_heads * d, -1))
            lp[name + "_b"] = (b[:, idx].reshape(kv_heads, g, d)
                               .mean(axis=1).reshape(kv_heads * d))
    return _apply_precision(p, policy)


def _block_qkv_kv(lp, x, n_heads):
    """Per-layer front half for the PAGED path: LN1 + projections with
    a possibly-reduced KV head count.  A fused-``qkv_w`` layer dict
    (``kv_heads == n_heads``) routes through :func:`_block_qkv`
    unchanged — bit-identical to the pre-GQA serving path; a split
    (GQA-converted) dict projects q at ``H`` heads and k/v at ``K_kv``.
    Returns ``q [B, H, T, D], k, v [B, K_kv, T, D]``."""
    if "qkv_w" in lp:
        return _block_qkv(lp, x, n_heads)
    b, t, c = x.shape
    d = c // n_heads
    kv_heads = lp["k_w"].shape[0] // d
    h = _ln(x, lp["ln1_g"], lp["ln1_b"])
    q = (h @ lp["q_w"].T + lp["q_b"]).reshape(b, t, n_heads, d)
    k = (h @ lp["k_w"].T + lp["k_b"]).reshape(b, t, kv_heads, d)
    v = (h @ lp["v_w"].T + lp["v_b"]).reshape(b, t, kv_heads, d)
    return (q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3))


def _bcast_kv(k, n_heads):
    """Broadcast ``K_kv`` KV heads over their query groups for a dense
    einsum ([B, K_kv, T, D] -> [B, H, T, D]); identity when the counts
    already agree (the fused multi-head path stays bit-identical)."""
    import jax.numpy as jnp
    kv_heads = k.shape[1]
    if kv_heads == n_heads:
        return k
    return jnp.repeat(k, n_heads // kv_heads, axis=1)


_KV_QMAX = 127.0


def _kv_quantized(kv_pages):
    """A per-layer entry is ``(k, v)`` for full-precision pools or
    ``(k, v, k_scales, v_scales)`` for int8 pools with fp32
    ``[num_pages, K_kv]`` absmax scales (ISSUE 20)."""
    return len(kv_pages[0]) == 4


def _quant_scatter(pool, scales, phys, offs, rows, mask):
    """Scatter one program's K or V rows into an INT8 page pool under
    per-page-per-KV-head absmax scales.

    ``phys``/``offs``: int32 [R] physical page + in-page offset per
    row; ``rows``: fp32 [R, K_kv, D]; ``mask``: bool [R] (False rows
    route to scratch page 0, same as the full-precision scatter).
    Scale discipline:

    - a page receiving a row at offset 0 is FRESH (just allocated —
      its payload and its scale slot are stale pool-reuse garbage):
      its scale resets to 0 first, so reuse can never leak a scale;
    - a page's scale GROWS monotonically while it is written:
      ``s_new = max(s_base, rowmax / 127)``, and the page's existing
      payload is re-expressed under the grown scale
      (``round(int8 * s_old / s_new)``) — an exact identity when the
      scale did not grow (ratio is exactly 1.0), one bounded rounding
      when it did.  The non-fresh writers are the decode/spec tail
      page and the copy-on-write page, both privately owned, so the
      whole-page rewrite can never race another reader;
    - new rows quantize under the page's FINAL scale, so scatter
      order within one call cannot matter.

    Returns ``(new_pool, new_scales)``.
    """
    import jax.numpy as jnp
    rows = rows * mask[:, None, None]
    tgt = jnp.where(mask, phys, 0)
    fresh_tgt = jnp.where(mask & (offs == 0), phys, 0)
    rowmax = jnp.abs(rows).max(-1)                     # [R, K_kv]
    s0 = scales.at[fresh_tgt].set(0.0)
    s_pre = s0[tgt]                                    # [R, K_kv]
    s1 = s0.at[tgt].max(rowmax / _KV_QMAX)
    s_post = s1[tgt]
    # duplicate rows landing in one page write IDENTICAL rescaled
    # payloads (same s_pre/s_post), so the duplicate-index scatter is
    # deterministic
    ratio = jnp.where(s_post > 0, s_pre / s_post, 0.0)
    old = pool[tgt].astype(jnp.float32)                # [R, page, KV, D]
    rescaled = jnp.clip(jnp.round(old * ratio[:, None, :, None]),
                        -_KV_QMAX, _KV_QMAX)
    p1 = pool.at[tgt].set(rescaled.astype(pool.dtype))
    q = jnp.clip(
        jnp.round(rows / jnp.maximum(s_post, 1e-30)[:, :, None]),
        -_KV_QMAX, _KV_QMAX)
    return p1.at[tgt, offs].set(q.astype(pool.dtype)), s1


def _filter_logits_per_slot(logits, top_k, top_p):
    """Per-slot dynamic top-k / nucleus filtering (jit-compatible:
    sort-based, ``top_k``/``top_p`` are TRACED [S] arrays — per-request
    sampling params are ordinary program inputs, never a recompile).
    0 disables either filter for that slot.  Callers pass TEMPERATURE-
    SCALED logits, mirroring :func:`_filter_logits`."""
    import jax
    import jax.numpy as jnp
    v = logits.shape[-1]
    # top-k: threshold at the k-th largest value of each row
    sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
    idx = jnp.clip(top_k - 1, 0, v - 1).astype(jnp.int32)
    kth = jnp.take_along_axis(sorted_desc, idx[:, None], axis=-1)
    logits = jnp.where((top_k[:, None] > 0) & (logits < kth), -1e30,
                       logits)
    # nucleus: smallest set whose mass >= top_p, on the (k-filtered)
    # sampling distribution — same rule as the static filter
    sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep_sorted = (cum - probs) < top_p[:, None]
    cutoff = jnp.min(jnp.where(keep_sorted, sorted_desc, jnp.inf),
                     axis=-1, keepdims=True)
    return jnp.where((top_p[:, None] > 0) & (logits < cutoff), -1e30,
                     logits)


def sample_tokens(logits, temps, top_ks, top_ps, keys):
    """Pick one token per slot from ``logits [S, V]`` under PER-SLOT
    sampling params (ISSUE 15): ``temps`` f32 [S] (<= 0 -> greedy
    argmax, bit-identical to the sampling-free path), ``top_ks`` int32
    [S], ``top_ps`` f32 [S] (0 disables), ``keys`` uint32 [S, 2] raw
    PRNG keys advanced FUNCTIONALLY — the returned ``new_keys`` is the
    only state, so the n-th token of a request depends on (seed, n)
    alone: same seed + params + prompt -> same tokens regardless of
    batch composition, join/leave, hot-swap, or failover re-decode.

    Returns ``(tokens int32 [S], new_keys uint32 [S, 2])``."""
    import jax
    import jax.numpy as jnp
    greedy = logits.argmax(-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
    filtered = _filter_logits_per_slot(scaled, top_ks, top_ps)
    split = jax.vmap(jax.random.split)(keys)        # [S, 2, 2]
    new_keys, subs = split[:, 0], split[:, 1]
    sampled = jax.vmap(jax.random.categorical)(subs, filtered) \
        .astype(jnp.int32)
    return jnp.where(temps > 0, sampled, greedy), new_keys


def paged_decode_step(p, tokens, positions, active, kv_pages,
                      block_tables, n_heads, sampling=None):
    """ONE decode step for every serving slot — the whole resident batch
    advances one token in one traced program.

    - ``tokens``: int32 [S] — each slot's current token (garbage where
      inactive);
    - ``positions``: int32 [S] — the position this token occupies (== the
      slot's context length before this step);
    - ``active``: bool [S] — slot occupancy mask.  Inactive slots write
      their K/V to physical page 0 (the allocator's scratch page) and
      attend over nothing, so occupancy changes can NEVER perturb a
      resident slot's math (bit-checked by tests);
    - ``kv_pages``: list of per-layer ``(k_pages, v_pages)``, each
      [num_pages, page_size, K_kv, D] — donated by the caller's jit.
      ``K_kv < n_heads`` is grouped-query attention: the layer dicts
      must be the matching :func:`decode_params` conversion.  Pools
      may be any float dtype (bf16 halves bytes, values cast on
      scatter); an entry of ``(k, v, k_scales, v_scales)`` with int8
      pools selects QUANTIZED storage (ISSUE 20): absmax
      quantize-on-scatter here, dequant inside the paged kernel (see
      :func:`_quant_scatter`) — every paged program in this module
      accepts the same entry forms;
    - ``block_tables``: int32 [S, max_pages_per_seq];
    - ``sampling``: None for greedy argmax (the pre-ISSUE-15 contract,
      bit-identical), or ``(temps [S], top_ks [S], top_ps [S],
      keys [S, 2])`` per-slot params (see :func:`sample_tokens`).

    Returns ``(logits [S, V] fp32, next_tokens [S] int32, new_kv_pages)``
    without sampling, or ``(logits, next_tokens, new_keys,
    new_kv_pages)`` with it.
    """
    import jax.numpy as jnp

    s_n = tokens.shape[0]
    page_size = kv_pages[0][0].shape[1]
    from ...ops.pallas.paged_attention import paged_attention

    x = p["wte"][tokens][:, None] + p["wpe"][positions][:, None]
    c = x.shape[-1]
    # where each slot's new K/V lands: (physical page, in-page offset);
    # inactive slots are routed to scratch page 0
    logical = positions // page_size
    phys = jnp.where(active,
                     jnp.take_along_axis(block_tables, logical[:, None],
                                         axis=1)[:, 0], 0)
    offs = positions % page_size
    # the kernel masks keys at position >= ctx; this step's own token is
    # key position `positions`, so the inclusive context is positions+1
    ctx = jnp.where(active, positions + 1, 0).astype(jnp.int32)
    quantized = _kv_quantized(kv_pages)
    new_pages = []
    for lp, entry in zip(p["layers"], kv_pages):
        q, k, v = _block_qkv_kv(lp, x, n_heads)     # q [S, H, 1, D]
        if quantized:
            kc, vc, ks, vs = entry                  # k/v [S, K_kv, 1, D]
            kc, ks = _quant_scatter(kc, ks, phys, offs, k[:, :, 0, :],
                                    active)
            vc, vs = _quant_scatter(vc, vs, phys, offs, v[:, :, 0, :],
                                    active)
            o = paged_attention(q[:, :, 0, :], kc, vc, block_tables,
                                ctx, k_scales=ks, v_scales=vs)
            new_pages.append((kc, vc, ks, vs))
        else:
            kc, vc = entry
            kc = kc.at[phys, offs].set(
                k[:, :, 0, :].astype(kc.dtype))
            vc = vc.at[phys, offs].set(
                v[:, :, 0, :].astype(vc.dtype))
            o = paged_attention(q[:, :, 0, :], kc, vc, block_tables,
                                ctx)
            new_pages.append((kc, vc))
        x = _block_finish(lp, x, o.reshape(s_n, 1, c))
    h = _ln(x[:, 0], p["lnf_g"], p["lnf_b"])
    logits = h @ p["wte"].T
    if sampling is None:
        return logits, logits.argmax(-1).astype(jnp.int32), new_pages
    temps, top_ks, top_ps, keys = sampling
    # an all-greedy resident batch must not pay the sampling math
    # (vocab sorts + categorical per slot): cond executes ONE branch.
    # A sampled request is resident in every step that produces one of
    # its tokens, so its key still advances exactly once per token —
    # the per-request determinism law is composition-independent.
    from jax import lax
    nxt, new_keys = lax.cond(
        jnp.any(temps > 0),
        lambda: sample_tokens(logits, temps, top_ks, top_ps, keys),
        lambda: (logits.argmax(-1).astype(jnp.int32), keys))
    return logits, nxt, new_keys, new_pages


def _spec_accept_greedy(logits, tokens, draft_valid):
    """Greedy prefix acceptance for one speculative-verify pass:
    ``greedy_next[s, i]`` is the target's argmax continuation after
    query position ``i``; draft token ``tokens[s, i+1]`` is accepted
    iff every earlier draft matched AND it equals ``greedy_next[s, i]``.
    The emitted chain is ``greedy_next[s, :n_new]`` — position
    ``accepted_len`` is the free correction/bonus token, so ANY draft
    content (including poisoned garbage) still yields the exact greedy
    stream.  Returns ``(greedy_next [S, K], accepted_len [S])``."""
    import jax.numpy as jnp
    greedy_next = logits.argmax(-1).astype(jnp.int32)      # [S, K]
    match = (greedy_next[:, :-1] == tokens[:, 1:]) & draft_valid
    accepted = jnp.cumprod(match.astype(jnp.int32),
                           axis=1).sum(axis=1)
    return greedy_next, accepted


def _spec_sample(logits, tokens, draft_valid, temps, top_ks, top_ps,
                 keys):
    """Rejection-sampling verification of the draft tokens (sampled
    slots).  The drafter is DETERMINISTIC (it proposes draft ``d`` with
    probability 1), so the accept test is ``u < p_i[d]`` against the
    slot's filtered/temperature target distribution at position ``i``,
    and the residual distribution on rejection is ``p_i`` with ``d``
    masked out (renormalized inside ``categorical``).  The PRNG chain
    advances ONE split per emitted token — the i-th token of the step
    draws from the key after i splits, so the n-th token of a request
    still depends on (seed, n, context) alone and per-request streams
    reproduce across batch composition, churn, hot-swap, and failover
    re-decode (for a FIXED spec configuration; spec-on sampled streams
    need not match spec-off — only greedy is bit-pinned).

    Returns ``(emitted [S, K], accepted_len [S], keys_after [K, S, 2])``
    where ``keys_after[i]`` is the chain state after emitting ``i + 1``
    tokens."""
    import jax
    import jax.numpy as jnp
    s_n, k1, v = logits.shape
    rep = lambda a: jnp.repeat(a, k1, axis=0)
    scaled = logits / jnp.maximum(temps, 1e-6)[:, None, None]
    filtered = _filter_logits_per_slot(
        scaled.reshape(s_n * k1, v), rep(top_ks),
        rep(top_ps)).reshape(s_n, k1, v)
    vocab = jnp.arange(v)
    cur = keys
    emit, cont, keys_after = [], [], []
    for i in range(k1):
        sp = jax.vmap(jax.random.split)(cur)           # [S, 2, 2]
        cur, sub = sp[:, 0], sp[:, 1]
        keys_after.append(cur)
        sp2 = jax.vmap(jax.random.split)(sub)
        k_u, k_r = sp2[:, 0], sp2[:, 1]
        f_i = filtered[:, i]                           # [S, V]
        if i < k1 - 1:
            d_i = tokens[:, i + 1]
            probs = jax.nn.softmax(f_i, axis=-1)
            p_d = jnp.take_along_axis(probs, d_i[:, None],
                                      axis=-1)[:, 0]
            u = jax.vmap(lambda kk: jax.random.uniform(kk, ()))(k_u)
            accept = (u < p_d) & draft_valid[:, i]
            masked = jnp.where(vocab[None, :] == d_i[:, None], -1e30,
                               f_i)
            resample = jax.vmap(jax.random.categorical)(
                k_r, masked).astype(jnp.int32)
            direct = jax.vmap(jax.random.categorical)(
                k_r, f_i).astype(jnp.int32)
            emit.append(jnp.where(draft_valid[:, i],
                                  jnp.where(accept, d_i, resample),
                                  direct))
            cont.append(accept)
        else:
            # the bonus position: no draft beyond it, sample directly
            emit.append(jax.vmap(jax.random.categorical)(
                k_r, f_i).astype(jnp.int32))
            cont.append(jnp.zeros(s_n, bool))
    emit = jnp.stack(emit, axis=1)                     # [S, K]
    cont = jnp.stack(cont, axis=1)
    accepted = jnp.cumprod(cont.astype(jnp.int32), axis=1).sum(axis=1)
    return emit, accepted, jnp.stack(keys_after)


def paged_spec_decode_step(p, tokens, positions, active, draft_len,
                           kv_pages, block_tables, n_heads,
                           sampling=None):
    """ONE speculative decode step for every serving slot: the slot's
    last emitted token PLUS up to ``K - 1`` draft tokens run through
    the target model together, and the longest verified prefix (plus
    the free correction/bonus token) is emitted — up to ``K`` tokens
    per slot from ONE dispatch, same donated-program discipline as
    :func:`paged_decode_step` (occupancy and per-slot draft length are
    masks, never shapes).

    - ``tokens``: int32 [S, K] — ``tokens[s, 0]`` is the slot's current
      (last emitted) token, ``tokens[s, 1:]`` the drafted continuation
      (garbage past ``draft_len[s]``);
    - ``positions``: int32 [S, K] — consecutive positions starting at
      the slot's context length - 1 (host-clamped into the wpe table);
    - ``active``: bool [S]; ``draft_len``: int32 [S] in
      ``[0, K - 1]`` — how many draft tokens are real this step
      (``0`` degenerates to the plain single-token decode step);
    - ``sampling``: None for greedy, or the per-slot
      ``(temps, top_ks, top_ps, keys)`` arrays.

    Every query position's K/V is scattered into the slot's pages
    before attention (rows past ``draft_len`` go to scratch); query
    ``i`` attends through position ``positions[s, i]`` — the
    per-position causal mask of batched verification
    (``paged_attention_multi``).  Rejected draft positions need no
    physical rollback: their page offsets sit beyond the slot's
    committed context, so every later step masks them and the next
    tokens overwrite them in place.

    Returns ``(logits [S, K, V], out_tokens [S, K], n_new [S],
    new_kv_pages)`` — the emitted tokens are ``out_tokens[s, :n_new[s]]``
    — or, with ``sampling``, ``(logits, out_tokens, n_new, new_keys,
    new_kv_pages)``.
    """
    import jax.numpy as jnp

    s_n, k1 = tokens.shape
    page_size = kv_pages[0][0].shape[1]
    from ...ops.pallas.paged_attention import paged_attention_multi

    qpos = jnp.arange(k1)
    # query-row validity: the slot is live and the row is the current
    # token (i == 0) or a real draft (i <= draft_len)
    qmask = active[:, None] & (qpos[None, :] <= draft_len[:, None])
    x = p["wte"][tokens] + p["wpe"][positions]          # [S, K, C]
    c = x.shape[-1]
    logical = positions // page_size
    phys = jnp.where(qmask,
                     jnp.take_along_axis(block_tables, logical, axis=1),
                     0)
    offs = positions % page_size
    ctx = jnp.where(qmask, positions + 1, 0).astype(jnp.int32)
    quantized = _kv_quantized(kv_pages)
    flat = lambda a: a.reshape(s_n * k1)
    new_pages = []
    for lp, entry in zip(p["layers"], kv_pages):
        q, k, v = _block_qkv_kv(lp, x, n_heads)   # q [S, H, K, D]
        kr = k.transpose(0, 2, 1, 3)              # [S, K, K_kv, D]
        vr = v.transpose(0, 2, 1, 3)
        if quantized:
            kc, vc, ks, vs = entry
            kc, ks = _quant_scatter(
                kc, ks, flat(phys), flat(offs),
                kr.reshape((s_n * k1,) + kr.shape[2:]), flat(qmask))
            vc, vs = _quant_scatter(
                vc, vs, flat(phys), flat(offs),
                vr.reshape((s_n * k1,) + vr.shape[2:]), flat(qmask))
            o = paged_attention_multi(q.transpose(0, 2, 1, 3), kc, vc,
                                      block_tables, ctx, k_scales=ks,
                                      v_scales=vs)  # [S, K, H, D]
            new_pages.append((kc, vc, ks, vs))
        else:
            kc, vc = entry
            kc = kc.at[phys, offs].set(kr.astype(kc.dtype))
            vc = vc.at[phys, offs].set(vr.astype(vc.dtype))
            o = paged_attention_multi(q.transpose(0, 2, 1, 3), kc, vc,
                                      block_tables, ctx)
            new_pages.append((kc, vc))
        x = _block_finish(lp, x, o.reshape(s_n, k1, c))
    h = _ln(x, p["lnf_g"], p["lnf_b"])
    logits = h @ p["wte"].T                            # [S, K, V]
    draft_valid = qmask[:, 1:]          # draft at input column i+1
    greedy_next, acc_g = _spec_accept_greedy(logits, tokens,
                                             draft_valid)
    n_new_g = jnp.where(active, acc_g + 1, 0).astype(jnp.int32)
    if sampling is None:
        return logits, greedy_next, n_new_g, new_pages
    temps, top_ks, top_ps, keys = sampling
    from jax import lax

    def _sampled():
        emit, acc_s, keys_after = _spec_sample(
            logits, tokens, draft_valid, temps, top_ks, top_ps, keys)
        n_new_s = jnp.where(active, acc_s + 1, 0).astype(jnp.int32)
        sampled_row = temps > 0
        out = jnp.where(sampled_row[:, None], emit, greedy_next)
        n_new = jnp.where(sampled_row, n_new_s, n_new_g)
        # key after the last emitted token; untouched for greedy or
        # inactive slots
        sel = jnp.take_along_axis(
            keys_after.transpose(1, 0, 2),
            jnp.clip(n_new - 1, 0, k1 - 1)[:, None, None]
            .astype(jnp.int32), axis=1)[:, 0]
        new_keys = jnp.where((sampled_row & active)[:, None], sel,
                             keys)
        return out, n_new, new_keys

    out_tokens, n_new, new_keys = lax.cond(
        jnp.any(temps > 0), _sampled,
        lambda: (greedy_next, n_new_g, keys))
    return logits, out_tokens, n_new, new_keys, new_pages


def _first_token(logits, sampling, new_pages):
    """Shared prefill tail: greedy 3-tuple, or per-request sampled
    4-tuple with the functionally-advanced key (scalar flavor of
    :func:`sample_tokens`; greedy requests skip the sampling math via
    cond)."""
    import jax.numpy as jnp
    from jax import lax
    if sampling is None:
        return logits, logits.argmax(-1).astype(jnp.int32), new_pages
    temp, top_k, top_p, key = sampling

    def _sampled():
        tok, new_key = sample_tokens(
            logits[None], jnp.reshape(temp, (1,)).astype(jnp.float32),
            jnp.reshape(top_k, (1,)).astype(jnp.int32),
            jnp.reshape(top_p, (1,)).astype(jnp.float32), key[None])
        return tok[0], new_key[0]

    tok, new_key = lax.cond(
        temp > 0, _sampled,
        lambda: (logits.argmax(-1).astype(jnp.int32), key))
    return logits, tok, new_key, new_pages


def paged_prefill(p, tokens, prompt_len, block_table_row, kv_pages,
                  n_heads, sampling=None):
    """Admit one request: a single batched causal pass over its (padded)
    prompt that scatters every position's K/V into the slot's pages and
    returns the last prompt position's logits — the first generated
    token costs one forward, not ``prompt_len`` decode steps.

    - ``tokens``: int32 [T_pad] — prompt padded to the engine's static
      prefill length (one compiled program for every prompt length);
    - ``prompt_len``: int32 scalar (traced — no per-length recompiles);
    - ``block_table_row``: int32 [max_pages_per_seq] for this slot;
    - ``sampling``: None for greedy, or scalar ``(temperature, top_k,
      top_p, key)`` for the request's first token.

    Pad positions (>= prompt_len) are masked out of attention and their
    K/V is scattered to scratch page 0.  Returns ``(logits [V] fp32,
    first_token int32, new_kv_pages)`` (plus the advanced key before
    ``new_kv_pages`` when sampling).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    t_pad = tokens.shape[0]
    page_size = kv_pages[0][0].shape[1]
    x = (p["wte"][tokens] + p["wpe"][:t_pad])[None]   # [1, T_pad, C]
    c = x.shape[-1]
    d = c // n_heads
    pos = jnp.arange(t_pad)
    valid = pos < prompt_len
    mask = (jnp.tril(jnp.ones((t_pad, t_pad), bool))
            & valid[None, :])[None, None]
    phys = jnp.where(valid, block_table_row[pos // page_size], 0)
    offs = pos % page_size
    quantized = _kv_quantized(kv_pages)
    new_pages = []
    for lp, entry in zip(p["layers"], kv_pages):
        kc, vc = entry[0], entry[1]
        q, k, v = _block_qkv_kv(lp, x, n_heads)   # [1, H|K_kv, T_pad, D]
        kd, vd = _bcast_kv(k, n_heads), _bcast_kv(v, n_heads)
        st = jnp.einsum("bhqd,bhkd->bhqk", q, kd) / jnp.sqrt(
            jnp.float32(d))
        st = jnp.where(mask, st, -1e30)
        pr = jax.nn.softmax(st, axis=-1)
        o = jnp.einsum("bhqk,bhkd->bhqd", pr, vd)
        o = o.transpose(0, 2, 1, 3).reshape(1, t_pad, c)
        if quantized:
            ks, vs = entry[2], entry[3]
            kc, ks = _quant_scatter(kc, ks, phys, offs,
                                    k[0].transpose(1, 0, 2), valid)
            vc, vs = _quant_scatter(vc, vs, phys, offs,
                                    v[0].transpose(1, 0, 2), valid)
            new_pages.append((kc, vc, ks, vs))
        else:
            kc = kc.at[phys, offs].set(
                k[0].transpose(1, 0, 2).astype(kc.dtype))
            vc = vc.at[phys, offs].set(
                v[0].transpose(1, 0, 2).astype(vc.dtype))
            new_pages.append((kc, vc))
        x = _block_finish(lp, x, o)
    h = _ln(x[0], p["lnf_g"], p["lnf_b"])             # [T_pad, C]
    last = lax.dynamic_index_in_dim(h, prompt_len - 1, 0,
                                    keepdims=False)
    logits = last @ p["wte"].T
    return _first_token(logits, sampling, new_pages)


def paged_suffix_prefill(p, tokens, prompt_len, prefix_len,
                         block_table_row, cow_src, cow_dst, kv_pages,
                         n_heads, sampling=None):
    """Prefix-cache-aware admission (ISSUE 15): prefill ONLY the
    un-cached suffix of a prompt whose leading ``prefix_len`` tokens'
    K/V already sit in pages mapped by ``block_table_row`` (shared
    full pages + optionally one copy-on-write page).

    - ``tokens``: int32 [T_pad] — the SUFFIX tokens
      (``prompt[prefix_len:]``), padded to the engine's static prefill
      length; suffix position ``i`` is absolute position
      ``prefix_len + i``;
    - ``prompt_len`` / ``prefix_len``: int32 scalars, both TRACED — one
      compiled program serves every hit length, and ``prefix_len == 0``
      is a cache miss (full prefill) in the same program;
    - ``cow_src`` / ``cow_dst``: int32 physical page ids.  The program
      copies page ``cow_src`` into ``cow_dst`` per layer FIRST — the
      copy-on-write for a prefix that ends mid-page: the donor page
      stays immutable for its other readers while this request's
      suffix tokens overwrite the copy's tail.  Pass scratch (0) for
      both when no COW is needed (a scratch self-copy is a no-op);
    - suffix queries attend over the cached prefix (gathered from the
      pages through the block table, masked at ``prefix_len``) PLUS
      the causal window of the suffix itself, in one joint softmax.

    Returns like :func:`paged_prefill`: the logits are the LAST PROMPT
    position's, so the first generated token is produced here (the
    suffix is always >= 1 token — a fully-cached prompt still runs its
    final position through the model).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    t_pad = tokens.shape[0]
    page_size = kv_pages[0][0].shape[1]
    mp = block_table_row.shape[0]
    t_ctx = mp * page_size
    suffix_len = prompt_len - prefix_len
    positions = prefix_len + jnp.arange(t_pad)
    x = (p["wte"][tokens] + p["wpe"][positions])[None]  # [1, T_pad, C]
    c = x.shape[-1]
    d = c // n_heads
    i = jnp.arange(t_pad)
    valid = i < suffix_len
    # suffix-vs-suffix: causal within the window, pads masked
    mask_suf = (jnp.tril(jnp.ones((t_pad, t_pad), bool))
                & valid[None, :])[None, None]
    # suffix-vs-cached-prefix: every suffix query sees every cached key
    pre_valid = jnp.arange(t_ctx) < prefix_len
    mask_pre = pre_valid[None, None, None, :]
    phys = jnp.where(valid, block_table_row[positions // page_size], 0)
    offs = positions % page_size
    quantized = _kv_quantized(kv_pages)
    new_pages = []
    for entry_i, lp in enumerate(p["layers"]):
        entry = kv_pages[entry_i]
        kc, vc = entry[0], entry[1]
        # copy-on-write FIRST: the gather below must see the copy
        kc = kc.at[cow_dst].set(kc[cow_src])
        vc = vc.at[cow_dst].set(vc[cow_src])
        if quantized:
            # the copy carries the donor page's SCALE row with its
            # bytes — a COW page dequantizes identically to its donor
            ks, vs = entry[2], entry[3]
            ks = ks.at[cow_dst].set(ks[cow_src])
            vs = vs.at[cow_dst].set(vs[cow_src])
            kg = (kc[block_table_row].astype(jnp.float32)
                  * ks[block_table_row][:, None, :, None])
            vg = (vc[block_table_row].astype(jnp.float32)
                  * vs[block_table_row][:, None, :, None])
        else:
            kg = kc[block_table_row].astype(jnp.float32)
            vg = vc[block_table_row].astype(jnp.float32)
        q, k, v = _block_qkv_kv(lp, x, n_heads)
        kd, vd = _bcast_kv(k, n_heads), _bcast_kv(v, n_heads)
        # cached prefix K/V, gathered through the block table:
        # [mp, page, K_kv, D] -> [1, H, t_ctx, D]
        kp = _bcast_kv(kg.reshape(
            t_ctx, -1, d).transpose(1, 0, 2)[None], n_heads)
        vp = _bcast_kv(vg.reshape(
            t_ctx, -1, d).transpose(1, 0, 2)[None], n_heads)
        # positions past the cached prefix read scratch/unwritten pages
        # whose contents are GARBAGE — a NaN there (e.g. a hot-swap
        # canary's torn-weight writes to scratch) would poison the
        # output through 0 * NaN even though its softmax weight is
        # exactly zero.  Zero the V rows, not just the scores.
        vp = jnp.where(pre_valid[None, None, :, None], vp, 0.0)
        scale = jnp.sqrt(jnp.float32(d))
        st_pre = jnp.where(mask_pre,
                           jnp.einsum("bhqd,bhkd->bhqk", q, kp) / scale,
                           -1e30)
        st_suf = jnp.where(mask_suf,
                           jnp.einsum("bhqd,bhkd->bhqk", q, kd) / scale,
                           -1e30)
        pr = jax.nn.softmax(jnp.concatenate([st_pre, st_suf], axis=-1),
                            axis=-1)
        o = jnp.einsum("bhqk,bhkd->bhqd", pr,
                       jnp.concatenate([vp, vd], axis=2))
        o = o.transpose(0, 2, 1, 3).reshape(1, t_pad, c)
        if quantized:
            # the COW page is the only written page with pre-existing
            # content; _quant_scatter's grow-only rescale handles it
            # (fresh pages start at an offs == 0 row and reset)
            kc, ks = _quant_scatter(kc, ks, phys, offs,
                                    k[0].transpose(1, 0, 2), valid)
            vc, vs = _quant_scatter(vc, vs, phys, offs,
                                    v[0].transpose(1, 0, 2), valid)
            new_pages.append((kc, vc, ks, vs))
        else:
            kc = kc.at[phys, offs].set(
                k[0].transpose(1, 0, 2).astype(kc.dtype))
            vc = vc.at[phys, offs].set(
                v[0].transpose(1, 0, 2).astype(vc.dtype))
            new_pages.append((kc, vc))
        x = _block_finish(lp, x, o)
    h = _ln(x[0], p["lnf_g"], p["lnf_b"])             # [T_pad, C]
    last = lax.dynamic_index_in_dim(h, suffix_len - 1, 0,
                                    keepdims=False)
    logits = last @ p["wte"].T
    return _first_token(logits, sampling, new_pages)


def get_gpt(num_layers, units, num_heads, vocab_size=50257, max_len=1024,
            dropout=0.0, remat=False, moe_experts=0, **kwargs):
    """Build a GPTLM with the vocab padded to the MXU lane width."""
    return GPTLM(_pad_vocab(vocab_size), num_layers, units, num_heads,
                 max_len=max_len, dropout=dropout, remat=remat,
                 moe_experts=moe_experts, **kwargs)


def gpt2_tiny_moe(moe_experts=4, **kwargs):
    """2-layer test-scale MoE config (every block's MLP is a top-1
    mixture of ``moe_experts`` experts — the flagship's ep-axis form)."""
    kwargs.setdefault("vocab_size", 256)
    kwargs.setdefault("max_len", 128)
    return get_gpt(2, 128, 4, moe_experts=moe_experts, **kwargs)


def gpt2_tiny(**kwargs):
    """2-layer test-scale config (CI / CPU oracle checks)."""
    kwargs.setdefault("vocab_size", 256)
    kwargs.setdefault("max_len", 128)
    return get_gpt(2, 128, 4, **kwargs)


def gpt2_small(**kwargs):
    """124M-parameter class (12 x 768, 12 heads)."""
    kwargs.setdefault("max_len", 2048)
    return get_gpt(12, 768, 12, **kwargs)


def gpt2_medium(**kwargs):
    """350M-parameter class (24 x 1024, 16 heads)."""
    kwargs.setdefault("max_len", 2048)
    return get_gpt(24, 1024, 16, **kwargs)
