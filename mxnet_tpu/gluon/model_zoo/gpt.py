"""GPT-2-class decoder language models — the transformer flagship.

TPU-native addition: the 2017 reference predates attention entirely (its
sequence story is bucketing, /root/reference/python/mxnet/module/
bucketing_module.py), but a TPU framework's MFU headline lives in
transformer matmuls, so the model zoo carries a decoder LM family built
on the Pallas flash-attention kernel (ops/pallas/flash_attention.py)
through the Gluon layer API (nn.FlashSelfAttention).

Design notes (all MXU-motivated):
- pre-LN residual blocks (stable in bf16 without warmup tricks);
- gelu(tanh) MLP at 4x width — two large [T, d]x[d, 4d] matmuls XLA
  tiles straight onto the systolic array;
- weight-tied embedding/head: logits ride one [B·T, d] x [d, V]
  FullyConnected against the embedding table, so the V-sized matmul
  appears exactly once per step;
- vocab padded to a multiple of 128 by the factory functions (lane
  dimension of the MXU; 50257 → 50304 exactly like megatron-era configs).

Weights save/load in the reference's V2 binary format like every other
zoo model (ndarray/serialization.py), so the fine-tune workflow
(example/language-model) round-trips through ``Module.load``.
"""
from __future__ import annotations

from .. import nn
from ..block import HybridBlock

__all__ = ["GPTBlock", "GPTLM", "get_gpt", "gpt2_tiny", "gpt2_small",
           "gpt2_medium"]


class GPTBlock(HybridBlock):
    """One pre-LN transformer decoder block."""

    def __init__(self, units, num_heads, mlp_ratio=4, dropout=0.0,
                 **kwargs):
        super().__init__(**kwargs)
        self._dropout = dropout
        with self.name_scope():
            self.ln1 = nn.LayerNorm(in_channels=units, prefix="ln1_")
            self.attn = nn.FlashSelfAttention(units, num_heads,
                                              causal=True,
                                              in_units=units,
                                              prefix="attn_")
            self.ln2 = nn.LayerNorm(in_channels=units, prefix="ln2_")
            self.fc1 = nn.Dense(mlp_ratio * units, flatten=False,
                                in_units=units, prefix="fc1_")
            self.fc2 = nn.Dense(units, flatten=False,
                                in_units=mlp_ratio * units, prefix="fc2_")

    def hybrid_forward(self, F, x):
        h = self.attn(self.ln1(x))
        if self._dropout:
            h = F.Dropout(h, p=self._dropout)
        x = x + h
        h = self.fc2(F.Activation(self.fc1(self.ln2(x)),
                                  act_type="gelu"))
        if self._dropout:
            h = F.Dropout(h, p=self._dropout)
        return x + h


class GPTLM(HybridBlock):
    """Decoder-only LM: token + learned position embeddings, N blocks,
    final LayerNorm, tied output head.

    Input: int token ids [B, T] (T ≤ max_len); output: logits [B, T, V].
    """

    def __init__(self, vocab_size, num_layers, units, num_heads,
                 max_len=1024, dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        self._vocab = vocab_size
        self._units = units
        self._max_len = max_len
        self._dropout = dropout
        with self.name_scope():
            self.wte = self.params.get("wte_weight",
                                       shape=(vocab_size, units))
            self.wpe = self.params.get("wpe_weight",
                                       shape=(max_len, units))
            self.blocks = nn.HybridSequential(prefix="h_")
            with self.blocks.name_scope():
                for _ in range(num_layers):
                    self.blocks.add(GPTBlock(units, num_heads,
                                             dropout=dropout))
            self.ln_f = nn.LayerNorm(in_channels=units, prefix="lnf_")

    def hybrid_forward(self, F, tokens, wte, wpe):
        t = tokens.shape[1]
        if t > self._max_len:
            raise ValueError("sequence length %d exceeds max_len %d"
                             % (t, self._max_len))
        h = F.Embedding(tokens, wte, input_dim=self._vocab,
                        output_dim=self._units)
        h = h + F.slice_axis(wpe, axis=0, begin=0, end=t)
        if self._dropout:
            h = F.Dropout(h, p=self._dropout)
        h = self.blocks(h)
        h = self.ln_f(h)
        # tied head: one [B·T, d] x [d, V] matmul against the embedding
        return F.FullyConnected(h, wte, num_hidden=self._vocab,
                                no_bias=True, flatten=False)


def _pad_vocab(v, mult=128):
    return (v + mult - 1) // mult * mult


def get_gpt(num_layers, units, num_heads, vocab_size=50257, max_len=1024,
            dropout=0.0, **kwargs):
    """Build a GPTLM with the vocab padded to the MXU lane width."""
    return GPTLM(_pad_vocab(vocab_size), num_layers, units, num_heads,
                 max_len=max_len, dropout=dropout, **kwargs)


def gpt2_tiny(**kwargs):
    """2-layer test-scale config (CI / CPU oracle checks)."""
    kwargs.setdefault("vocab_size", 256)
    kwargs.setdefault("max_len", 128)
    return get_gpt(2, 128, 4, **kwargs)


def gpt2_small(**kwargs):
    """124M-parameter class (12 x 768, 12 heads)."""
    kwargs.setdefault("max_len", 2048)
    return get_gpt(12, 768, 12, **kwargs)


def gpt2_medium(**kwargs):
    """350M-parameter class (24 x 1024, 16 heads)."""
    kwargs.setdefault("max_len", 2048)
    return get_gpt(24, 1024, 16, **kwargs)
