"""Gluon: the imperative neural-network API
(reference python/mxnet/gluon/__init__.py)."""
from .parameter import Parameter, ParameterDict, DeferredInitializationError
from .block import Block, HybridBlock, SymbolBlock
from .trainer import Trainer
from . import nn
from . import rnn
from . import loss
from . import data
from . import utils
from . import model_zoo
from .utils import split_data, split_and_load, clip_global_norm
