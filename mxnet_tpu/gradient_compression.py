"""2-bit gradient compression, TPU-native.

The v0.11 reference tree exposes no gradient-compression API (it landed
upstream right after this snapshot, as ``kvstore.set_gradient_compression``
with the 2-bit scheme); this framework implements that surface for real
rather than warning it away.  Scheme (matching the upstream semantics):

each worker keeps a per-key *residual* ``r``; for every element::

    v = g + r
    send  +threshold  if v >=  threshold   (code 1)
    send  -threshold  if v <= -threshold   (code 2)
    send   0          otherwise            (code 0)
    r' = v - sent

so quantization error is carried into the next step and the update is
unbiased over time.

TPU-native design: quantize + residual update + bit-packing is ONE jitted
XLA program on the local device (no host round-trip); the cross-worker
exchange moves packed ``uint8`` codes — 4 elements per byte, 16x smaller
than fp32 — over the worker mesh; decode-and-sum across workers is a
second jitted program whose worker-axis reduction XLA lowers to the
collective.  The reference-era design shipped quantized blobs through
ps-lite servers; here the "server sum" is the same psum that carries the
uncompressed path.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .aot_cache import donation_cache_guard

__all__ = ["TwoBitCompression", "create_compressor"]

_SHIFTS = (0, 2, 4, 6)  # 4 two-bit codes per byte


# compiles once per distinct gradient size, donated: every one of those
# compiles must stay out of jax's persistent cache on backends where
# replaying a donated executable deserialized corrupts the heap
# (ROBUSTNESS.md §8; the guard defers its backend probe to first call,
# so this import stays side-effect free)
@donation_cache_guard
@functools.partial(jax.jit, donate_argnums=(1,))
def _compress_step(flat_grad, residual, threshold):
    """codes+residual in one fused program; returns (packed uint8, r')."""
    v = flat_grad.astype(jnp.float32) + residual
    pos = v >= threshold
    neg = v <= -threshold
    codes = jnp.where(pos, jnp.uint8(1), jnp.where(neg, jnp.uint8(2),
                                                   jnp.uint8(0)))
    sent = jnp.where(pos, threshold, jnp.where(neg, -threshold, 0.0))
    new_residual = v - sent
    n = codes.shape[0]
    n4 = -(-n // 4) * 4
    codes = jnp.pad(codes, (0, n4 - n)).reshape(n4 // 4, 4)
    packed = (codes[:, 0] | (codes[:, 1] << 2) |
              (codes[:, 2] << 4) | (codes[:, 3] << 6))
    return packed, new_residual


def _decode(packed, threshold, size):
    """packed uint8 (..., nbytes) -> float32 values (..., size)."""
    bits = (packed[..., None] >> jnp.array(_SHIFTS, dtype=jnp.uint8)) & 3
    flat = bits.reshape(bits.shape[:-2] + (-1,))[..., :size]
    return jnp.where(flat == 1, threshold,
                     jnp.where(flat == 2, -threshold, 0.0))


_decode_jit = jax.jit(_decode, static_argnums=(2,))


class TwoBitCompression:
    """2-bit quantization with on-device residuals.

    One instance serves a whole KVStore; residuals are keyed by the
    caller.  All state lives on device as float32.
    """

    type = "2bit"

    def __init__(self, threshold=0.5):
        threshold = float(threshold)
        if threshold <= 0:
            raise ValueError("2bit compression threshold must be > 0, got %s"
                             % threshold)
        self.threshold = threshold
        self._residuals = {}
        self._decode_sum_jit = None

    def reset_state(self):
        """Drop all world-coupled state: the error-feedback residuals
        (each rank's residual encodes quantization error against a sum
        over a SPECIFIC worker set — after an elastic world-size change
        it would silently corrupt the first compressed push) and the
        decode-sum program (its ``out_shardings`` bake in the old worker
        mesh).  Called by ``KVStore._check_world`` on membership change;
        losing the residuals costs one step of quantization error, the
        same price a fresh rank pays."""
        self._residuals.clear()
        self._decode_sum_jit = None

    # -- local (single-process) path ------------------------------------
    def compress(self, key, data):
        """Quantize ``data`` (a jax.Array) against key's residual.

        Returns packed uint8 codes of shape (ceil(size/4),); the residual
        for ``key`` is updated in place (on device, donated buffer).
        """
        flat = data.reshape(-1)
        res = self._residuals.get(key)
        if res is None or res.shape != flat.shape:
            res = jnp.zeros(flat.shape, jnp.float32)
        packed, new_res = _compress_step(flat, res,
                                         jnp.float32(self.threshold))
        self._residuals[key] = new_res
        return packed

    def decompress(self, packed, shape, dtype):
        size = int(np.prod(shape)) if shape else 1
        vals = _decode_jit(packed, jnp.float32(self.threshold), size)
        return vals.reshape(shape).astype(dtype)

    def quantize_local(self, key, data):
        """compress+decompress for the non-distributed store: the merged
        gradient is replaced by its quantized image, residual carried."""
        packed = self.compress(key, data)
        return self.decompress(packed, data.shape, data.dtype)

    # -- distributed path ------------------------------------------------
    def allreduce(self, keys, raws, gather):
        """Sum each worker's quantized contribution across the mesh.

        Wire format per key: (num_workers, ceil(size/4)) uint8 — each
        process contributes its packed row via ``gather`` (the KVStore's
        worker-mesh scaffold, kvstore.py:_worker_gather); a single jitted
        program decodes every row and sums over the worker axis (XLA
        emits the collective), returning replicated float sums.
        """
        from jax.sharding import NamedSharding, PartitionSpec as P
        packed = [self.compress(k, x) for k, x in zip(keys, raws)]
        mesh, packed_globals = gather(packed)
        metas = [(tuple(x.shape), x.dtype,
                  int(np.prod(x.shape)) if x.ndim else 1) for x in raws]
        sizes = tuple(m[2] for m in metas)
        if self._decode_sum_jit is None:
            def _decode_sum(xs, threshold, sizes):
                return tuple(
                    jnp.sum(_decode(x, threshold, s), axis=0)
                    for x, s in zip(xs, sizes))
            self._decode_sum_jit = jax.jit(
                _decode_sum, static_argnums=(2,),
                out_shardings=NamedSharding(mesh, P()))
        summed = self._decode_sum_jit(tuple(packed_globals),
                                      jnp.float32(self.threshold), sizes)
        return [s.reshape(shape).astype(dtype).addressable_data(0)
                for s, (shape, dtype, _) in zip(summed, metas)]


def create_compressor(params):
    """Build a compressor from ``set_gradient_compression`` params."""
    params = dict(params or {})
    ctype = params.pop("type", "none")
    if ctype in (None, "none"):
        return None
    if ctype == "2bit":
        return TwoBitCompression(threshold=params.pop("threshold", 0.5))
    raise ValueError("unsupported gradient compression type %r "
                     "(supported: 'none', '2bit')" % (ctype,))
