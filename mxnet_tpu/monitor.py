"""Monitor: tap intermediate op outputs for debugging (mx.monitor).

Port of /root/reference/python/mxnet/monitor.py:33 — the reference
installs an executor monitor callback fired per op by the engine
(graph_executor.cc:1399-1419).  Under XLA the graph is one fused program,
so ``install`` switches the executor into an interpret-mode tap: node
outputs are evaluated eagerly (uncompiled) on monitored forwards.  Slow —
it is a debugging tool, same as the reference's.
"""
from __future__ import annotations

import logging
import re

from .ndarray.ndarray import NDArray

__all__ = ["Monitor", "StepStatsMonitor"]


class StepStatsMonitor(object):
    """Periodic reporter over profiler.step_stats() — dispatch count,
    compile count, and the step-time EMA maintained by the fused train
    step.  Usable directly as a ``batch_end_callback`` in fit(); a healthy
    fused loop shows dispatches growing by exactly 1 per step and zero
    steady-state compiles (see PERF.md, "Fused train step").
    """

    def __init__(self, interval=50, logger=None, phases=True):
        self.interval = max(1, int(interval))
        self.logger = logger or logging
        self.phases = phases
        self._nseen = 0
        self._last = None

    def __call__(self, param=None):
        from . import profiler as _profiler
        self._nseen += 1
        if self._nseen % self.interval:
            return
        stats = _profiler.step_stats()
        prev = self._last or {"dispatch_count": 0, "compile_count": 0,
                              "skipped_steps": 0}
        ema = stats["step_time_ema_s"]
        skipped = stats.get("skipped_steps", 0) - \
            prev.get("skipped_steps", 0)
        self.logger.info(
            "step[%d] dispatches +%d compiles +%d%s step_time_ema %s",
            self._nseen,
            stats["dispatch_count"] - prev["dispatch_count"],
            stats["compile_count"] - prev["compile_count"],
            " SKIPPED +%d (non-finite grads)" % skipped if skipped else "",
            "%.2f ms" % (ema * 1e3) if ema is not None else "n/a")
        if self.phases:
            self._log_phases()
        self._last = stats

    def _log_phases(self):
        """One compact line of telemetry's cumulative phase-time
        breakdown (mean ms per call / call count for the costliest
        phases) — where a step's wall time actually goes."""
        from . import telemetry as _telemetry
        phases = _telemetry.report()["phases"]
        top = sorted(((n, p) for n, p in phases.items() if p["count"]),
                     key=lambda np: -np[1]["sum"])[:4]
        if top:
            self.logger.info(
                "phases " + "  ".join(
                    "%s %.2fms/call x%d" % (n, 1e3 * p["sum"] / p["count"],
                                            p["count"])
                    for n, p in top))


class Monitor(object):
    """Collect (step, node_name, stat) every `interval` batches."""

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            def asum_stat(x):
                """returns |x|/size(x), async execution."""
                return x.abs().mean() if hasattr(x, "abs") else abs(x).mean()
            stat_func = asum_stat
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort

        def stat_helper(name, array):
            if not self.activated or not self.re_prog.match(name):
                return
            self.queue.append((self.step, name, self.stat_func(array)))
        self.stat_helper = stat_helper

    def install(self, exe):
        """Install the tap on an executor (reference monitor.py:install)."""
        exe.set_monitor_callback(self.stat_helper, monitor_all=True)
        self.exes.append(exe)

    def tic(self):
        """Start collecting for this batch if the interval elapsed."""
        if self.step % self.interval == 0:
            for exe in self.exes:
                for array in exe.arg_arrays:
                    array.wait_to_read() if hasattr(array, "wait_to_read") \
                        else None
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        """Stop collecting; returns [(step, name, stat_str)]."""
        if not self.activated:
            return []
        for exe in self.exes:
            for array in exe.arg_arrays:
                if hasattr(array, "wait_to_read"):
                    array.wait_to_read()
        for exe in self.exes:
            for name, array in zip(exe._arg_names, exe.arg_arrays):
                if self.re_prog.match(name):
                    self.queue.append((self.step, name,
                                       self.stat_func(array)))
        self.activated = False
        res = []
        if self.sort:
            self.queue.sort(key=lambda x: x[1])
        for n, k, v_list in self.queue:
            if isinstance(v_list, NDArray):
                v_list = [v_list]
            if not isinstance(v_list, list):
                v_list = [v_list]
            s = ""
            for v in v_list:
                if isinstance(v, NDArray):
                    v = v.asnumpy()
                s += "%s " % str(v)
            res.append((n, k, s.strip()))
        self.queue = []
        return res

    def toc_print(self):
        """toc + log the results."""
        res = self.toc()
        for n, k, v in res:
            logging.info("Batch: %7d %30s %s", n, k, v)
        return res
