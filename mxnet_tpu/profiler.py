"""Profiler (mx.profiler): chrome://tracing dump + jax.profiler bridge.

Port of /root/reference/python/mxnet/profiler.py (:27-55) over the
reference's engine profiler (src/engine/profiler.{h,cc}: OprExecStat per
engine op, DumpProfile writes chrome tracing JSON).  TPU-native shape:

- step-level events are recorded by the Executor around each compiled
  program invocation (forward/backward/fused step) — the XLA analogue of
  the engine's per-op blocks, since ops fuse into one program;
- ``profiler_set_config(filename=...)`` + ``dump_profile()`` write the
  same chrome://tracing JSON format (load in chrome://tracing or perfetto);
- for intra-program (per-fusion/per-op) detail, ``profiler_set_state`` can
  also drive ``jax.profiler`` traces into ``<filename>.jaxtrace/`` —
  viewable in TensorBoard/XProf (set ``use_jax_profiler=True``).

Env autostart: MXNET_PROFILER_AUTOSTART=1 (reference env_var.md:101-108).
"""
from __future__ import annotations

import json
import os
import threading
import time

__all__ = ["profiler_set_config", "profiler_set_state", "dump_profile",
           "State", "set_config", "set_state", "pause", "resume",
           "count_dispatch", "count_compile", "note_step",
           "note_skipped_step", "step_stats", "reset_step_stats",
           "instrument"]

_lock = threading.Lock()
_state = "stop"
_mode = "symbolic"
_filename = "profile.json"
_use_jax = False
_events = []
_t0_us = None
_paused = False


class State:
    stop = "stop"
    run = "run"


def profiler_set_config(mode="symbolic", filename="profile.json",
                        use_jax_profiler=False):
    """Configure the profiler (reference profiler.py:27).

    mode: 'symbolic' (executor-level events) or 'all' (also imperative op
    calls; identical here since both run compiled programs)."""
    global _mode, _filename, _use_jax
    with _lock:
        _mode = mode
        _filename = filename
        _use_jax = use_jax_profiler


def profiler_set_state(state="stop"):
    """Start ('run') or stop ('stop') collecting (reference :43).

    jax is only imported when the jax-profiler bridge is actually
    requested (use_jax_profiler), so MXNET_PROFILER_AUTOSTART=1 at
    import time cannot drag in (or crash on) a backend."""
    global _state, _t0_us
    with _lock:
        if state == _state:
            return
        if state == "run":
            _events.clear()
            _t0_us = time.perf_counter_ns() // 1000
            if _use_jax:
                import jax
                logdir = _filename + ".jaxtrace"
                os.makedirs(logdir, exist_ok=True)
                try:
                    jax.profiler.start_trace(logdir)
                except RuntimeError:
                    pass
        elif state == "stop":
            if _use_jax:
                import jax
                try:
                    jax.profiler.stop_trace()
                except RuntimeError:
                    pass
        else:
            raise ValueError("state must be 'run' or 'stop'")
        _state = state


def pause():
    """Temporarily skip recording (reference profiler.py:pause)."""
    global _paused
    _paused = True


def resume():
    global _paused
    _paused = False


def is_running():
    return _state == "run" and not _paused


def record_event(name, start_us, dur_us, cat="operator", tid=None,
                 args=None):
    """Append one duration event (called by the Executor hot path and
    telemetry spans only when is_running()).  Appends under ``_lock``:
    dump_profile/profiler_set_state read/clear the buffer under the same
    lock, and spans record from prefetch worker threads too — an
    unlocked append could race a concurrent clear."""
    if not is_running():
        return
    ev = {
        "name": name, "cat": cat, "ph": "X",
        "ts": start_us - (_t0_us or 0), "dur": dur_us,
        "pid": os.getpid(),
        "tid": tid if tid is not None else threading.get_ident() & 0xffff,
    }
    if args is not None:
        ev["args"] = args
    with _lock:
        _events.append(ev)


class _timed(object):
    """Context manager the Executor wraps compiled calls in; forces device
    sync at exit so durations are real (only while profiling)."""

    def __init__(self, name, sync_arrays=()):
        self.name = name
        self.sync_arrays = sync_arrays

    def __enter__(self):
        self.active = is_running()
        if self.active:
            self.start = time.perf_counter_ns() // 1000
        return self

    def __exit__(self, *exc):
        if self.active:
            for a in self.sync_arrays:
                try:
                    a.block_until_ready()
                except Exception:
                    pass
            end = time.perf_counter_ns() // 1000
            record_event(self.name, self.start, end - self.start)
        return False


# -- step instrumentation (always on; a few integer adds per batch) --------
#
# The reference engine could count pushed ops per step; under XLA the
# equivalent health metric is "how many compiled programs did this batch
# dispatch, and did any of them recompile".  The fused fit path targets
# exactly ONE dispatch per steady-state step (vs N params + 1 today), and
# these counters are how bench.py / tools/perf_probe/steptrace.py prove it.
_step_lock = threading.Lock()
_dispatch_count = 0
_compile_count = 0
_step_count = 0
_skipped_step_count = 0
_step_ema_s = None
_last_step_t = None
_EMA_ALPHA = 0.1


def count_dispatch(n=1):
    """Record n compiled-program dispatches (XLA executions).  Called by
    the Executor around every jitted invocation and by imperative_invoke
    for each eager op — so (dispatches per step) is comparable between the
    fused and unfused train paths.  Lock-free on purpose: this sits on the
    per-op hot path, and a GIL-raced increment merely miscounts telemetry
    under concurrent eager threads."""
    global _dispatch_count
    _dispatch_count += n


def count_compile(n=1):
    """Record n XLA compilations (first execution of a (program, shape)
    key).  Steady state should add zero."""
    global _compile_count
    _compile_count += n


def note_step():
    """Mark a train-step boundary; maintains an EMA of inter-step wall
    time.  The first call only arms the clock."""
    global _step_count, _step_ema_s, _last_step_t
    now = time.perf_counter()
    with _step_lock:
        if _last_step_t is not None:
            dt = now - _last_step_t
            _step_ema_s = dt if _step_ema_s is None else \
                (1 - _EMA_ALPHA) * _step_ema_s + _EMA_ALPHA * dt
            _step_count += 1
        _last_step_t = now


def note_skipped_step():
    """Record one divergence-guard skip: the fused step ran (and counted
    its dispatch) but the all-finite check vetoed the parameter update.
    A healthy run keeps this at 0; a rising count with training still
    progressing means occasional bad batches are being absorbed."""
    global _skipped_step_count
    with _step_lock:
        _skipped_step_count += 1


def step_stats():
    """Snapshot {dispatch_count, compile_count, steps, skipped_steps,
    step_time_ema_s}."""
    with _step_lock:
        return {"dispatch_count": _dispatch_count,
                "compile_count": _compile_count,
                "steps": _step_count,
                "skipped_steps": _skipped_step_count,
                "step_time_ema_s": _step_ema_s}


def reset_step_stats():
    global _dispatch_count, _compile_count, _step_count, \
        _skipped_step_count, _step_ema_s, _last_step_t
    # settle pending flight records against the OLD counters, then
    # re-baseline so the next record's delta starts from zero —
    # reset_step_stats and telemetry.reset compose in either order
    t = _telemetry()
    t._drain_steps()
    with _step_lock:
        _dispatch_count = 0
        _compile_count = 0
        _step_count = 0
        _skipped_step_count = 0
        _step_ema_s = None
        _last_step_t = None
    t._rebaseline()


_telemetry_mod = None


def _telemetry():
    global _telemetry_mod
    if _telemetry_mod is None:
        from . import telemetry
        _telemetry_mod = telemetry
    return _telemetry_mod


def instrument(fn, first_call_compiles=True):
    """Dispatch/compile accounting around a jitted program whose input
    shapes are fixed for its lifetime (executor programs are bound to one
    shape set; fused Trainer programs rebuild on shape change) — so the
    first invocation IS its one XLA compile, and every invocation is one
    dispatch.

    ``first_call_compiles=False`` is for programs that arrive already
    compiled — an AOT executable deserialized from the warm-start cache
    (executor.make_fit_step): its first call dispatches without
    compiling, and charging a phantom compile would hide exactly the
    warm-vs-cold signal BENCH_MODE=restart measures.

    Steady-state recompiles — the cache key silently missing after
    warmup, the exact failure the 1-compile contract exists to catch —
    are invisible to the first-call heuristic, so post-warmup calls are
    bracketed by telemetry's monotonic jax.monitoring backend-compile
    event count: any compile event landing inside an instrumented call
    feeds count_compile too."""
    compiled = []

    def wrapper(*args):
        count_dispatch()
        if not compiled:
            compiled.append(True)
            if first_call_compiles:
                count_compile()
            return fn(*args)
        t = _telemetry()
        pre = t._xla_compiles
        out = fn(*args)
        post = t._xla_compiles
        if post != pre:
            count_compile(post - pre)
        return out
    return wrapper


def dump_profile():
    """Write the chrome tracing JSON (reference profiler.py:55 /
    src/engine/profiler.cc:152).  Snapshot under the lock, write via the
    checkpoint layer's atomic tmp+fsync+os.replace so a crash mid-dump
    can never leave a torn trace at the final path."""
    with _lock:
        doc = {"traceEvents": list(_events), "displayTimeUnit": "ms"}
        fname = _filename
    from .checkpoint import _plain_atomic_write
    _plain_atomic_write(fname, json.dumps(doc).encode("utf-8"))
    return fname


# aliases matching later-era reference spellings kept by examples
set_config = profiler_set_config
set_state = profiler_set_state

if os.environ.get("MXNET_PROFILER_AUTOSTART", "0") == "1":
    profiler_set_state("run")
