"""Profiler (mx.profiler): chrome://tracing dump + jax.profiler bridge.

Port of /root/reference/python/mxnet/profiler.py (:27-55) over the
reference's engine profiler (src/engine/profiler.{h,cc}: OprExecStat per
engine op, DumpProfile writes chrome tracing JSON).  TPU-native shape:

- step-level events are recorded by the Executor around each compiled
  program invocation (forward/backward/fused step) — the XLA analogue of
  the engine's per-op blocks, since ops fuse into one program;
- ``profiler_set_config(filename=...)`` + ``dump_profile()`` write the
  same chrome://tracing JSON format (load in chrome://tracing or perfetto);
- for intra-program (per-fusion/per-op) detail, ``profiler_set_state`` can
  also drive ``jax.profiler`` traces into ``<filename>.jaxtrace/`` —
  viewable in TensorBoard/XProf (set ``use_jax_profiler=True``).

Env autostart: MXNET_PROFILER_AUTOSTART=1 (reference env_var.md:101-108).
"""
from __future__ import annotations

import json
import os
import threading
import time

__all__ = ["profiler_set_config", "profiler_set_state", "dump_profile",
           "State", "set_config", "set_state", "pause", "resume"]

_lock = threading.Lock()
_state = "stop"
_mode = "symbolic"
_filename = "profile.json"
_use_jax = False
_events = []
_t0_us = None
_paused = False


class State:
    stop = "stop"
    run = "run"


def profiler_set_config(mode="symbolic", filename="profile.json",
                        use_jax_profiler=False):
    """Configure the profiler (reference profiler.py:27).

    mode: 'symbolic' (executor-level events) or 'all' (also imperative op
    calls; identical here since both run compiled programs)."""
    global _mode, _filename, _use_jax
    with _lock:
        _mode = mode
        _filename = filename
        _use_jax = use_jax_profiler


def profiler_set_state(state="stop"):
    """Start ('run') or stop ('stop') collecting (reference :43)."""
    global _state, _t0_us
    import jax
    with _lock:
        if state == _state:
            return
        if state == "run":
            _events.clear()
            _t0_us = time.perf_counter_ns() // 1000
            if _use_jax:
                logdir = _filename + ".jaxtrace"
                os.makedirs(logdir, exist_ok=True)
                try:
                    jax.profiler.start_trace(logdir)
                except RuntimeError:
                    pass
        elif state == "stop":
            if _use_jax:
                try:
                    jax.profiler.stop_trace()
                except RuntimeError:
                    pass
        else:
            raise ValueError("state must be 'run' or 'stop'")
        _state = state


def pause():
    """Temporarily skip recording (reference profiler.py:pause)."""
    global _paused
    _paused = True


def resume():
    global _paused
    _paused = False


def is_running():
    return _state == "run" and not _paused


def record_event(name, start_us, dur_us, cat="operator", tid=None):
    """Append one duration event (called by the Executor hot path only
    when is_running())."""
    if not is_running():
        return
    _events.append({
        "name": name, "cat": cat, "ph": "X",
        "ts": start_us - (_t0_us or 0), "dur": dur_us,
        "pid": os.getpid(),
        "tid": tid if tid is not None else threading.get_ident() & 0xffff,
    })


class _timed(object):
    """Context manager the Executor wraps compiled calls in; forces device
    sync at exit so durations are real (only while profiling)."""

    def __init__(self, name, sync_arrays=()):
        self.name = name
        self.sync_arrays = sync_arrays

    def __enter__(self):
        self.active = is_running()
        if self.active:
            self.start = time.perf_counter_ns() // 1000
        return self

    def __exit__(self, *exc):
        if self.active:
            for a in self.sync_arrays:
                try:
                    a.block_until_ready()
                except Exception:
                    pass
            end = time.perf_counter_ns() // 1000
            record_event(self.name, self.start, end - self.start)
        return False


def dump_profile():
    """Write the chrome tracing JSON (reference profiler.py:55 /
    src/engine/profiler.cc:152)."""
    with _lock:
        doc = {"traceEvents": list(_events), "displayTimeUnit": "ms"}
        with open(_filename, "w") as f:
            json.dump(doc, f)
    return _filename


# aliases matching later-era reference spellings kept by examples
set_config = profiler_set_config
set_state = profiler_set_state

if os.environ.get("MXNET_PROFILER_AUTOSTART", "0") == "1":
    profiler_set_state("run")
