"""ctypes bindings for the native runtime library (libmxtpu.so).

The reference keeps its data pipeline in C++ behind a flat C ABI
(/root/reference/src/io/, include/mxnet/c_api.h); this module is the
TPU-native analogue: it loads ``native/libmxtpu.so`` (built from
``src/mxtpu/``) and exposes the RecordIO + threaded image-pipeline entry
points. If the library is missing it is built on demand with ``make``;
if that fails, callers fall back to the pure-Python paths (recordio.py,
image.py use PIL).
"""
from __future__ import annotations

import ctypes
import os
import shutil
import subprocess
import tempfile
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_HERE, "native", "libmxtpu.so")
_SRC_DIR = os.path.normpath(os.path.join(_HERE, "..", "src"))

_lib = None
_lib_lock = threading.Lock()
_tried = False


def _declare(lib):
    c = ctypes
    lib.MXTGetLastError.restype = c.c_char_p
    lib.MXTRecordIOReaderCreate.restype = c.c_void_p
    lib.MXTRecordIOReaderCreate.argtypes = [c.c_char_p]
    lib.MXTRecordIOReaderNext.restype = c.c_int
    lib.MXTRecordIOReaderNext.argtypes = [
        c.c_void_p, c.POINTER(c.c_char_p), c.POINTER(c.c_uint64)]
    lib.MXTRecordIOReaderSeek.argtypes = [c.c_void_p, c.c_uint64]
    lib.MXTRecordIOReaderReset.argtypes = [c.c_void_p]
    lib.MXTRecordIOReaderFree.argtypes = [c.c_void_p]
    lib.MXTRecordIOWriterCreate.restype = c.c_void_p
    lib.MXTRecordIOWriterCreate.argtypes = [c.c_char_p]
    lib.MXTRecordIOWriterWrite.restype = c.c_int64
    lib.MXTRecordIOWriterWrite.argtypes = [c.c_void_p, c.c_char_p, c.c_uint64]
    lib.MXTRecordIOWriterFree.argtypes = [c.c_void_p]
    lib.MXTImageIterCreate.restype = c.c_void_p
    lib.MXTImageIterCreate.argtypes = [
        c.c_char_p, c.c_char_p, c.c_int, c.c_int, c.c_int, c.c_int, c.c_int,
        c.c_int, c.c_uint64, c.c_int, c.c_int, c.c_int, c.c_int, c.c_int,
        c.c_float, c.c_float, c.c_float, c.POINTER(c.c_float),
        c.POINTER(c.c_float), c.c_int]
    lib.MXTImageDetIterCreate.restype = c.c_void_p
    lib.MXTImageDetIterCreate.argtypes = [
        c.c_char_p, c.c_char_p, c.c_int, c.c_int, c.c_int, c.c_int, c.c_int,
        c.c_int, c.c_int, c.c_uint64, c.c_int, c.c_int, c.c_int, c.c_int,
        c.c_float, c.c_float, c.c_float, c.c_float, c.c_float, c.c_float,
        c.POINTER(c.c_float), c.POINTER(c.c_float), c.c_int]
    lib.MXTImageIterNext.restype = c.c_int
    lib.MXTImageIterNext.argtypes = [
        c.c_void_p, c.POINTER(c.c_float), c.POINTER(c.c_float)]
    lib.MXTImageIterNumSamples.restype = c.c_int
    lib.MXTImageIterNumSamples.argtypes = [c.c_void_p]
    lib.MXTImageIterNumErrors.restype = c.c_uint64
    lib.MXTImageIterNumErrors.argtypes = [c.c_void_p]
    lib.MXTImageIterReset.restype = c.c_int
    lib.MXTImageIterReset.argtypes = [c.c_void_p]
    lib.MXTImageIterFree.argtypes = [c.c_void_p]
    lib.MXTDecodeJPEG.restype = c.c_int
    lib.MXTDecodeJPEG.argtypes = [
        c.c_char_p, c.c_uint64, c.c_void_p,
        c.POINTER(c.c_int), c.POINTER(c.c_int)]
    lib.MXTResizeBilinear.restype = c.c_int
    lib.MXTResizeBilinear.argtypes = [
        c.c_void_p, c.c_int, c.c_int, c.c_int, c.c_void_p, c.c_int, c.c_int]
    return lib


def get_lib():
    """Returns the loaded native library, building it if necessary, or
    None when the native toolchain is unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lib_lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB_PATH) and os.path.isdir(_SRC_DIR):
            try:
                subprocess.run(["make", "-C", _SRC_DIR], check=True,
                               capture_output=True, timeout=300)
            except Exception:
                return None
        if os.path.exists(_LIB_PATH):
            try:
                _lib = _declare(ctypes.CDLL(_LIB_PATH))
            except AttributeError:
                # a STALE prebuilt .so lacking newly-declared symbols
                # (dlsym miss) — rebuild once rather than killing every
                # native-IO caller
                try:
                    _lib = _rebuild_stale_lib()
                except Exception:
                    _lib = None
            except OSError:
                _lib = None
        return _lib


def _rebuild_stale_lib():
    """Recover from a stale libmxtpu.so already mapped in this process.

    Two traps in the naive rebuild-in-place-and-re-CDLL fix (ADVICE r5):
    (1) make relinking over a .so currently mapped by this or another
    process can SIGBUS readers of the truncated file — so the rebuild
    links to a temporary path on the same filesystem and os.replace()s it
    into place atomically (the old image stays mapped, unharmed);
    (2) dlopen caches by pathname, so re-CDLLing _LIB_PATH just returns
    the stale in-process image — so we load from a process-unique copy,
    whose pathname dlopen has never seen.
    """
    lib_dir = os.path.dirname(_LIB_PATH)
    build_dir = tempfile.mkdtemp(prefix=".mxtpu_rebuild_", dir=lib_dir)
    try:
        tmp_out = os.path.join(build_dir, "libmxtpu.so")
        subprocess.run(["make", "-C", _SRC_DIR, "-B", "OUT=%s" % tmp_out],
                       check=True, capture_output=True, timeout=300)
        os.replace(tmp_out, _LIB_PATH)  # same fs: atomic
    finally:
        shutil.rmtree(build_dir, ignore_errors=True)
    fd, unique = tempfile.mkstemp(prefix="libmxtpu_%d_" % os.getpid(),
                                  suffix=".so")
    os.close(fd)
    try:
        shutil.copy2(_LIB_PATH, unique)
        return _declare(ctypes.CDLL(unique))
    finally:
        # the mapping outlives the unlink on Linux; no on-disk litter
        try:
            os.unlink(unique)
        except OSError:
            pass


def available():
    return get_lib() is not None


def last_error():
    lib = get_lib()
    return lib.MXTGetLastError().decode() if lib is not None else ""
