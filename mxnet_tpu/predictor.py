"""Deployment-shaped inference entry.

The analogue of the reference's C predict API
(/root/reference/include/mxnet/c_predict_api.h,
src/c_api/c_predict_api.cc): load a symbol JSON + a .params blob, bind a
forward-only executor for fixed input shapes, then set input → forward →
get output, with zero training machinery (no labels, no gradients, no
optimizer).  The compiled program is cached per input shape, so repeated
`forward` calls are single XLA executions — the deployment story the C
API existed for.

    pred = Predictor.from_checkpoint("resnet", 0, {"data": (1, 3, 224, 224)})
    pred.forward(data=batch)
    probs = pred.get_output(0)

`Predictor(symbol_json_str, param_bytes, ...)` mirrors MXPredCreate's
buffer-based signature for serving stacks that ship bytes, not files.
"""
from __future__ import annotations

import numpy as _np

from . import context as ctx_mod
from . import ndarray as nd
from .base import MXNetError
from .ndarray.utils import load_frombuffer
from .symbol import load_json

__all__ = ["Predictor"]


class Predictor:
    def __init__(self, symbol_json, param_bytes, input_shapes, ctx=None,
                 type_dict=None):
        """symbol_json: JSON string; param_bytes: reference-format .params
        bytes (arg:/aux: prefixed); input_shapes: {name: shape}
        (MXPredCreate's input_keys/input_shape_* pair)."""
        if ctx is None:
            ctx = ctx_mod.current_context()
        self._ctx = ctx
        self._symbol = load_json(symbol_json)
        params = load_frombuffer(param_bytes) if param_bytes else {}
        arg_params, aux_params = {}, {}
        for k, v in params.items():
            if k.startswith("arg:"):
                arg_params[k[4:]] = v
            elif k.startswith("aux:"):
                aux_params[k[4:]] = v
        self._input_names = list(input_shapes.keys())
        self._type_dict = dict(type_dict) if type_dict else None
        self._exec = self._symbol.simple_bind(
            ctx, grad_req="null", type_dict=type_dict,
            **{k: tuple(v) for k, v in input_shapes.items()})
        self._exec.copy_params_from(arg_params, aux_params,
                                    allow_extra_params=True)
        # reshape-time validation targets real weights only — inputs and
        # label variables (the reference's *_label naming convention)
        # legitimately change shape with the batch
        self._param_names = {
            n for n in self._exec.arg_dict
            if n not in self._input_names and not n.endswith("_label")}
        self._outputs = None

    @classmethod
    def from_checkpoint(cls, prefix, epoch, input_shapes, ctx=None,
                        type_dict=None):
        """Load `prefix-symbol.json` + `prefix-%04d.params` (the
        two-artifact contract, reference python/mxnet/model.py:340)
        through :class:`~mxnet_tpu.checkpoint.CheckpointManager` — NOT a
        bare ``open()``: the manager drains any in-flight async
        checkpoint writes and verifies the epoch's sha256 manifest
        first, so a serving replica pointed at a LIVE training job's
        prefix can never bind a torn or still-being-written checkpoint
        (manifest-less pre-manager checkpoints still load via the
        legacy parse-probe path).  ``epoch=None`` follows the newest
        complete checkpoint."""
        from .checkpoint import CheckpointManager
        mgr = CheckpointManager(prefix)
        _, arg_params, aux_params = mgr.load(epoch)
        try:
            with open(mgr.symbol_path()) as f:
                sym_json = f.read()
        except OSError as e:
            raise MXNetError(
                "checkpoint prefix %s has no symbol file %s: %s"
                % (prefix, mgr.symbol_path(), e)) from e
        pred = cls(sym_json, None, input_shapes, ctx=ctx,
                   type_dict=type_dict)
        pred._exec.copy_params_from(arg_params, aux_params,
                                    allow_extra_params=True)
        return pred

    def set_input(self, name, value):
        """MXPredSetInput: stage one named input."""
        if name not in self._input_names:
            raise MXNetError("unknown input %r; declared inputs: %s"
                             % (name, self._input_names))
        arr = value if isinstance(value, nd.NDArray) else nd.array(value)
        self._exec.arg_dict[name]._set_data(arr._data)

    def forward(self, **inputs):
        """MXPredForward: run the compiled forward program."""
        for name, value in inputs.items():
            self.set_input(name, value)
        self._outputs = self._exec.forward(is_train=False)
        return self._outputs

    def get_output(self, index=0):
        """MXPredGetOutput: fetch output `index` as numpy."""
        if self._outputs is None:
            raise MXNetError("call forward() before get_output()")
        return self._outputs[index].asnumpy()

    @property
    def output_shapes(self):
        return [tuple(o.shape) for o in (self._outputs or [])]

    def reshape(self, input_shapes):
        """MXPredReshape: rebind for new input shapes, keeping weights.

        Validates like the reference's MXPredReshape: a param whose
        inferred shape changes under the new input shapes (e.g. a
        flatten→FC weight at a new spatial size) is an error — the
        generic Executor.reshape would silently zero it."""
        kwargs = {k: tuple(v) for k, v in input_shapes.items()}
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**kwargs)
        for name, shape in zip(self._symbol.list_arguments(), arg_shapes):
            if name in kwargs or name not in self._param_names:
                continue
            cur = self._exec.arg_dict[name]
            if tuple(cur.shape) != tuple(shape):
                raise ValueError(
                    "reshape: param %r changes shape %s -> %s under the "
                    "new input shapes; rebuild the predictor instead"
                    % (name, tuple(cur.shape), tuple(shape)))
        for name, shape in zip(self._symbol.list_auxiliary_states(),
                               aux_shapes):
            cur = self._exec.aux_dict[name]
            if tuple(cur.shape) != tuple(shape):
                raise ValueError(
                    "reshape: aux %r changes shape %s -> %s under the "
                    "new input shapes" % (name, tuple(cur.shape),
                                          tuple(shape)))
        self._exec = self._exec.reshape(**kwargs)
        self._input_names = list(input_shapes.keys())
        self._outputs = None

    def clone_reshaped(self, input_shapes):
        """A NEW predictor bound for ``input_shapes`` that shares nothing
        mutable with this one (the C ABI's MXPredReshape contract: the
        original handle stays fully usable).  Weights are copied from the
        live executor, so params set after construction carry over."""
        kwargs = {k: tuple(v) for k, v in input_shapes.items()}
        clone = Predictor.__new__(Predictor)
        clone._ctx = self._ctx
        clone._symbol = self._symbol
        clone._input_names = list(input_shapes.keys())
        clone._type_dict = self._type_dict
        clone._exec = self._symbol.simple_bind(self._ctx, grad_req="null",
                                               type_dict=self._type_dict,
                                               **kwargs)
        # weights transfer device-side, no host round-trip; jax buffers
        # are immutable, so sharing them is safe — set_input/_set_data
        # rebind pointers, never write through
        clone._param_names = set(self._param_names)
        for k, v in self._exec.arg_dict.items():
            if k in input_shapes or k not in clone._exec.arg_dict:
                continue
            dst = clone._exec.arg_dict[k]
            if v._data.shape != dst._data.shape:
                if k not in self._param_names:
                    continue  # free variable (label): fresh zeros are fine
                # reshape-time validation (the reference MXPredReshape
                # errors when a param's inferred shape changes, ADVICE r3)
                raise ValueError(
                    "clone_reshaped: param %r changes shape %s -> %s "
                    "under the new input shapes; rebuild the predictor "
                    "instead" % (k, tuple(v._data.shape),
                                 tuple(dst._data.shape)))
            dst._set_data(v._data.astype(dst._data.dtype))
        for k, v in self._exec.aux_dict.items():
            if k in clone._exec.aux_dict:
                dst = clone._exec.aux_dict[k]
                if v._data.shape != dst._data.shape:
                    raise ValueError(
                        "clone_reshaped: aux %r changes shape %s -> %s "
                        "under the new input shapes" %
                        (k, tuple(v._data.shape), tuple(dst._data.shape)))
                dst._set_data(v._data.astype(dst._data.dtype))
        clone._outputs = None
        return clone

    def predict(self, data, input_name=None):
        """One-call convenience: set the (single) input, forward, return
        output 0 — the c_predict_api quick path."""
        name = input_name or self._input_names[0]
        self.forward(**{name: data})
        return self.get_output(0)
