"""Shard-set manifests: the on-disk contract of the streaming data plane.

A *shard set* is an ordered list of record shards (RecordIO ``.rec`` or
JSONL) published under one JSON manifest (``shardset.json``, schema
``mxtpu-shardset-1``).  The manifest — not the directory listing — is
the unit of trust, exactly like the checkpoint layer's per-epoch
manifests (ROBUSTNESS.md §1): a shard exists for readers only once its
entry (record count, byte size, sha256) is committed, and the manifest
itself is published atomically, so a torn or in-flight shard write is
simply invisible.

The manifest is **append-aware**: a live writer keeps publishing new
shards mid-job (each publish bumps ``version`` and re-commits the whole
document atomically), readers ``refresh()`` and see strictly more
shards — existing entries are immutable by contract, enforced on
reload.  ``seal()`` marks the stream finished (``closed: true``) so a
follow-mode consumer knows "no new shards" is the end, not a lull.

DATA.md documents the schema, sizing guidance, and the exact-once
assignment laws layered on top (mxnet_tpu/stream/assignment.py).
"""
from __future__ import annotations

import glob as _glob
import hashlib
import json
import os

from ..base import MXNetError

__all__ = ["SCHEMA", "ShardSet", "ShardSetWriter", "load_shard_set",
           "discover", "count_records"]

SCHEMA = "mxtpu-shardset-1"

_FORMATS = ("recordio", "jsonl")


def _sha256_file(path):
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def count_records(path, fmt):
    """Walk one shard file and count complete records (the discovery
    path for manifest-less shard files).  A torn tail stops the count at
    the last complete record — discovery never claims records a reader
    could not deliver."""
    if fmt == "jsonl":
        n = 0
        with open(path, "rb") as f:
            data = f.read()
        for ln in data.split(b"\n"):
            if ln.strip():
                n += 1
        if data and not data.endswith(b"\n"):
            n -= 1  # unterminated final line: a torn tail, not a record
        return max(0, n)
    from .. import recordio as _recordio
    reader = _recordio.MXRecordIO(path, "r")
    n = 0
    try:
        while True:
            try:
                if reader.read() is None:
                    break
            except MXNetError:
                break  # torn tail: count stops at the last whole record
            n += 1
    finally:
        reader.close()
    return n


def _infer_format(path):
    ext = os.path.splitext(path)[1].lower()
    if ext in (".jsonl", ".json", ".txt"):
        return "jsonl"
    return "recordio"


class ShardSet:
    """Read-side view of one shard-set manifest (or of a globbed,
    manifest-less set — see :func:`discover`).

    - ``shards``: list of dicts ``{path (absolute), format,
      num_records, bytes, sha256}`` in publication order.
    - ``refresh()``: re-read the manifest; returns True when new shards
      appeared.  Existing entries must be an unchanged prefix (the
      append-only contract) — anything else raises, because a reader
      holding (shard, offset) cursors into a *rewritten* history would
      silently read the wrong records.
    - ``closed``: the writer sealed the stream.
    """

    def __init__(self, manifest_path=None, shards=None, version=0,
                 closed=False):
        self.manifest_path = manifest_path
        self.shards = list(shards or [])
        self.version = version
        self.closed = closed
        self._stat = None
        if manifest_path is not None:
            self._load(initial=True)

    @property
    def sizes(self):
        return [s["num_records"] for s in self.shards]

    @property
    def total_records(self):
        return sum(self.sizes)

    def _load(self, initial=False):
        path = self.manifest_path
        try:
            with open(path, "rb") as f:
                data = f.read()
                # fstat the handle actually READ: a path-stat after the
                # read can land past a concurrent os.replace and pin
                # the NEW file's signature against the OLD content —
                # refresh() would then no-op forever
                st = os.fstat(f.fileno())
            doc = json.loads(data.decode("utf-8"))
        except OSError as e:
            if initial:
                raise MXNetError(
                    "cannot read shard-set manifest %s: %s" % (path, e))
            return False  # mid-publish race: keep the current view
        except ValueError as e:
            raise MXNetError(
                "shard-set manifest %s is not valid JSON: %s" % (path, e))
        if not str(doc.get("schema", "")).startswith("mxtpu-shardset-"):
            raise MXNetError(
                "%s is not a shard-set manifest (schema %r)"
                % (path, doc.get("schema")))
        root = os.path.dirname(os.path.abspath(path))
        shards = []
        for ent in doc.get("shards", []):
            ent = dict(ent)
            if not os.path.isabs(ent["path"]):
                ent["path"] = os.path.join(root, ent["path"])
            shards.append(ent)
        if not initial:
            # append-only contract: the committed history never mutates
            old = [(s["path"], s["num_records"], s.get("sha256"))
                   for s in self.shards]
            new = [(s["path"], s["num_records"], s.get("sha256"))
                   for s in shards[:len(old)]]
            if new != old:
                raise MXNetError(
                    "shard-set manifest %s rewrote committed shard "
                    "entries (append-only contract): cursors into the "
                    "old history are meaningless" % path)
        grew = len(shards) > len(self.shards)
        self.shards = shards
        self.version = int(doc.get("version", 0))
        self.closed = bool(doc.get("closed", False))
        self._stat = (st.st_size, st.st_mtime_ns, st.st_ino)
        return grew

    def refresh(self):
        """Re-read the manifest if it changed on disk; True when new
        shards were appended (the follow-mode wakeup signal)."""
        if self.manifest_path is None:
            return False
        try:
            st = os.stat(self.manifest_path)
            sig = (st.st_size, st.st_mtime_ns, st.st_ino)
        except OSError:
            return False
        if sig == self._stat:
            return False
        return self._load()

    def validate(self, shard_index=None):
        """Full sha256 verification of one shard (or all).  Not on the
        read hot path — openers check byte size only; this is the audit
        tool (and the test hook)."""
        idx = range(len(self.shards)) if shard_index is None \
            else [shard_index]
        for i in idx:
            ent = self.shards[i]
            try:
                if os.path.getsize(ent["path"]) != ent.get("bytes"):
                    return False
            except OSError:
                return False
            digest = ent.get("sha256")
            if digest and _sha256_file(ent["path"]) != digest:
                return False
        return True


def load_shard_set(path):
    """Open a shard-set manifest (a file path, or a directory holding
    ``shardset.json``)."""
    if os.path.isdir(path):
        path = os.path.join(path, "shardset.json")
    return ShardSet(manifest_path=path)


def discover(pattern, fmt=None):
    """Build an in-memory ShardSet from a glob over manifest-less shard
    files, sorted by name (record counts come from walking each file —
    a torn tail counts up to the last whole record).  For one-off reads
    of legacy .rec directories; real streams should publish a manifest
    (the writer below) so counts/digests are committed, not re-derived."""
    paths = sorted(_glob.glob(pattern))
    if not paths:
        raise MXNetError("shard glob %r matched no files" % pattern)
    shards = []
    for p in paths:
        f = fmt or _infer_format(p)
        shards.append({
            "path": os.path.abspath(p), "format": f,
            "num_records": count_records(p, f),
            "bytes": os.path.getsize(p), "sha256": None,
        })
    return ShardSet(shards=shards, version=len(shards), closed=True)


class ShardSetWriter:
    """Publish shards into a shard set, append-aware.

    Each ``write_*_shard`` writes the shard file, then re-commits the
    manifest atomically (via the checkpoint layer's plain atomic writer:
    tmp + fsync + ``os.replace`` — without the ``ckpt.write.*`` fault
    sites or ckpt telemetry, which belong to checkpoints, not data).
    A writer crash mid-shard leaves an unreferenced partial file that no
    reader ever sees; a crash mid-publish leaves the previous manifest.

    Re-opening an existing manifest resumes appending after its last
    committed shard.
    """

    def __init__(self, root, name="shardset.json"):
        self.root = os.fspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.manifest_path = os.path.join(self.root, name)
        if os.path.exists(self.manifest_path):
            ss = ShardSet(manifest_path=self.manifest_path)
            if ss.closed:
                raise MXNetError(
                    "shard set %s is sealed (closed: true) — appending "
                    "to a closed stream would violate readers that "
                    "already saw the end" % self.manifest_path)
            self._shards = ss.shards
            self._version = ss.version
        else:
            self._shards = []
            self._version = 0
        self._closed = False

    @property
    def num_shards(self):
        return len(self._shards)

    def _publish(self):
        from ..checkpoint import _plain_atomic_write
        self._version += 1
        doc = {
            "schema": SCHEMA, "version": self._version,
            "closed": self._closed,
            "shards": [dict(s, path=os.path.relpath(s["path"], self.root))
                       for s in self._shards],
        }
        _plain_atomic_write(self.manifest_path,
                            json.dumps(doc, indent=1).encode("utf-8"))

    @staticmethod
    def _fsync_path(path):
        try:
            fd = os.open(path, os.O_RDONLY)
        except OSError:
            return
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    def _commit(self, path, fmt, num_records):
        # shard DATA reaches the platter before the manifest commits:
        # the manifest is fsync-published (atomic_write), so without
        # this a power loss could leave a committed manifest vouching
        # for records still in the page cache — exactly the torn state
        # the manifest exists to make invisible
        self._fsync_path(path)
        self._shards.append({
            "path": os.path.abspath(path), "format": fmt,
            "num_records": int(num_records),
            "bytes": os.path.getsize(path),
            "sha256": _sha256_file(path),
        })
        self._publish()
        return self._shards[-1]

    def _next_name(self, ext):
        return os.path.join(self.root,
                            "shard-%06d%s" % (len(self._shards), ext))

    def write_recordio_shard(self, records, name=None):
        """Write ``records`` (an iterable of bytes payloads) as one
        indexed RecordIO shard (+ ``.idx`` sidecar, so readers seek to a
        record in O(1)) and commit it to the manifest."""
        from .. import recordio as _recordio
        path = name or self._next_name(".rec")
        idx_path = os.path.splitext(path)[0] + ".idx"
        w = _recordio.MXIndexedRecordIO(idx_path, path, "w")
        n = 0
        try:
            for rec in records:
                w.write_idx(n, rec)
                n += 1
        finally:
            w.close()
        # the .idx sidecar is a performance hint (readers fall back to
        # a sequential walk when it is short), but a torn one should
        # still be rare — fsync it alongside the data _commit fsyncs
        self._fsync_path(idx_path)
        return self._commit(path, "recordio", n)

    def write_jsonl_shard(self, records, name=None):
        """Write ``records`` (dicts/lists/strings; non-strings are JSON-
        encoded) as one JSONL shard and commit it to the manifest.
        String records must be exactly one non-empty line: an embedded
        newline or a blank string would break the one-line-one-record
        bijection the committed ``num_records`` (and every reader
        range) is defined over — rejected here, never mis-counted."""
        path = name or self._next_name(".jsonl")
        n = 0
        with open(path, "w", encoding="utf-8") as f:
            for rec in records:
                line = rec if isinstance(rec, str) else json.dumps(rec)
                line = line.rstrip("\n")
                if "\n" in line or not line.strip():
                    raise MXNetError(
                        "jsonl record %d is %s — one record must be "
                        "exactly one non-empty line (JSON-encode "
                        "payloads with newlines)"
                        % (n, "empty" if not line.strip()
                           else "multi-line"))
                f.write(line + "\n")
                n += 1
            f.flush()
            os.fsync(f.fileno())
        return self._commit(path, "jsonl", n)

    def append_existing(self, path, fmt=None, num_records=None):
        """Commit an already-written shard file (counted by walking it
        when ``num_records`` is not given)."""
        fmt = fmt or _infer_format(path)
        if fmt not in _FORMATS:
            raise MXNetError("unknown shard format %r" % fmt)
        if num_records is None:
            num_records = count_records(path, fmt)
        return self._commit(path, fmt, num_records)

    def seal(self):
        """Mark the stream finished: ``closed: true`` in the manifest.
        A follow-mode reader that has consumed everything stops instead
        of polling forever."""
        self._closed = True
        self._publish()
