"""Streaming data plane: sharded ingest for real, growing, on-disk data.

Three layers (DATA.md is the user contract):

- :mod:`manifest` — the shard-set manifest format: an append-aware,
  atomically-published list of RecordIO/JSONL shards with committed
  record counts and content digests (``ShardSetWriter`` publishes,
  ``load_shard_set``/``discover`` read, ``seal()`` ends a stream).
- :mod:`assignment` — the exact-once (shard, offset)-range laws
  extending ``elastic.shard_for_epoch`` to disk streams: epoch-mode
  contiguous position cuts, follow-mode per-shard partitions, and the
  world-agnostic cursor-resume algebra (``CursorStore`` persists one
  consistent cursor snapshot per checkpoint generation).
- :mod:`loader` — ``StreamLoader``: a background decode worker pool
  feeding the PR-1 ``DataLoader`` prefetcher unchanged, with io.*
  telemetry, torn-tail skip-and-count, and the ``io.shard.torn`` /
  ``io.decode.error`` / ``io.decode.slow`` fault sites.
"""
from . import assignment
from . import manifest
from .assignment import (CursorStore, follow_resume, ranges_for_epoch,
                         resume_spans, span_for_rank)
from .manifest import ShardSet, ShardSetWriter, discover, load_shard_set
from .loader import StreamLoader
from .fit import StreamTrainIter

__all__ = ["assignment", "manifest", "CursorStore", "follow_resume",
           "ranges_for_epoch", "resume_spans", "span_for_rank",
           "ShardSet", "ShardSetWriter", "discover", "load_shard_set",
           "StreamLoader", "StreamTrainIter"]
