"""StreamLoader: shard sets → decoded batches, through a worker pool.

The PR-1 ``gluon.data.DataLoader`` owns the *device* half of the input
pipeline: a double-buffered prefetcher overlapping batchify + host→
device transfer with device compute.  This module adds the *disk* half
in front of it — and feeds the **same** prefetcher, unchanged:

    shards on disk → decode worker pool → ordered record stream →
    batchify → ``_PrefetchIter`` (h2d overlap, ``data`` watchdog lease,
    ``data.*`` fault sites) → training loop

- **Workers** decode RecordIO/JSONL records into samples off the
  consumer thread (``MXTPU_STREAM_WORKERS``, default 2) — threads by
  default, forked processes with ``MXTPU_STREAM_WORKER_MODE=process``
  (decode is numpy/bytes work; it must never touch jax).  Queues are
  bounded; results re-order by sequence number so the delivered record
  order is bit-deterministic regardless of worker scheduling.
- **Assignment** comes from ``stream.assignment``: epoch mode applies
  the exact-once (shard, offset)-range laws; follow mode consumes an
  appending stream shard-by-shard, each shard partitioned across the
  current world.  ``cursor()`` exposes the consumed position in the
  world-agnostic resume form; folding happens when a batch is
  *delivered to the consumer*, so a cursor never claims records whose
  batches died in the prefetch queue.
- **Robustness**: a torn shard tail (crashed writer) is skipped and
  counted (``io.torn_records`` — no silent caps), worker exceptions
  re-raise at the consumption point with the worker's traceback, and
  the ``io.shard.torn`` / ``io.decode.error`` / ``io.decode.slow``
  fault sites drill each path deterministically.
- **Telemetry** (OBSERVABILITY.md): ``io.shard_open`` / ``io.decode`` /
  ``io.queue_wait`` phases, ``io.records`` / ``io.bytes`` /
  ``io.torn_records`` counters, ``io.shards_open`` gauge — the input-
  stall half of ``job_report.py``'s straggler blame.

DATA.md is the user-facing contract (env knobs, sizing, semantics).
"""
from __future__ import annotations

import json as _json
import logging
import os
import queue as _queue
import struct as _struct
import threading
import time
import traceback

from .. import fault as _fault
from .. import telemetry as _telemetry
from .. import watchdog as _watchdog
from ..base import MXNetError
from ..recordio import _LEN_MASK as _REC_LEN_MASK
from ..recordio import _MAGIC as _REC_MAGIC
from . import assignment as _assign
from .manifest import ShardSet, load_shard_set

__all__ = ["StreamLoader"]


def _env_int(name, default):
    try:
        return int(os.environ.get(name, ""))
    except ValueError:
        return default


def _env_float(name, default):
    try:
        return float(os.environ.get(name, ""))
    except ValueError:
        return default


# -- shard readers (worker side) --------------------------------------------

class _RecordIOShardReader:
    """Range reads over one RecordIO shard.  Prefers the ``.idx``
    sidecar: a contiguous record range becomes ONE seek + ONE read of
    the covering byte span, parsed in memory (frame validation per
    record, zero syscalls per record — the difference between ~4 µs and
    ~0.5 µs a record, which matters because worker threads share the
    consumer's GIL).  Falls back to a position-tracking sequential walk
    when the sidecar is missing or short.  A torn record ends the
    shard: the remainder of the requested range comes back as a torn
    count, never as garbage."""

    def __init__(self, shard):
        from .. import recordio as _recordio
        path = shard["path"]
        idx_path = os.path.splitext(path)[0] + ".idx"
        self._reader = None
        self._indexed = None
        self._offsets = None
        if os.path.isfile(idx_path):
            r = _recordio.MXIndexedRecordIO(idx_path, path, "r")
            if len(r.keys) >= shard.get("num_records", 0):
                offs = [r.idx[k] for k in r.keys]
                if offs == sorted(offs):
                    self._indexed = r
                    self._offsets = offs
                else:
                    r.close()  # unsorted offsets: no contiguous spans
            else:
                r.close()  # short sidecar (torn idx): walk sequentially
        if self._indexed is None:
            self._reader = _recordio.MXRecordIO(path, "r")
            self._pos = 0

    def _parse_blob(self, blob, path, base, count):
        """Frame-validated record parse of one in-memory byte span."""
        out = []
        pos = 0
        n = len(blob)
        for _ in range(count):
            if pos + 8 > n:
                return out, "truncated record header in %s at offset " \
                    "%d — torn tail from a crashed writer?" \
                    % (path, base + pos)
            magic, lrec = _struct.unpack_from("<II", blob, pos)
            if magic != _REC_MAGIC:
                return out, "invalid record magic 0x%08x in %s at " \
                    "offset %d" % (magic, path, base + pos)
            length = lrec & _REC_LEN_MASK
            if pos + 8 + length > n:
                return out, "truncated record payload in %s at offset " \
                    "%d — torn tail from a crashed writer?" \
                    % (path, base + pos)
            out.append(blob[pos + 8:pos + 8 + length])
            pos += 8 + length + ((-length) % 4)
        return out, None

    def read_range(self, start, stop):
        if self._indexed is not None:
            base = self._offsets[start]
            f = self._indexed.handle
            f.seek(base)
            if stop < len(self._offsets):
                blob = f.read(self._offsets[stop] - base)
            else:
                blob = f.read()
            out, err = self._parse_blob(
                blob, self._indexed.uri, base, stop - start)
            return out, (stop - start - len(out)) if err else 0, err
        r = self._reader
        if start < self._pos:
            r.reset()
            self._pos = 0
        out = []
        try:
            while self._pos < start:
                if r.read() is None:
                    return out, stop - start, \
                        "shard ended at record %d (< range start %d)" \
                        % (self._pos, start)
                self._pos += 1
            while self._pos < stop:
                rec = r.read()
                if rec is None:
                    return out, stop - self._pos, \
                        "shard ended at record %d of claimed range" \
                        % self._pos
                out.append(rec)
                self._pos += 1
            return out, 0, None
        except MXNetError as e:
            torn = stop - max(self._pos, start)
            # the torn record leaves the file position mid-frame: reset
            # so a later range re-walks from 0 and hits the same torn
            # point deterministically instead of reading garbage
            r.reset()
            self._pos = 0
            return out, torn, str(e)

    def close(self):
        for r in (self._indexed, self._reader):
            if r is not None:
                r.close()


class _JsonlShardReader:
    """Range reads over one JSONL shard (lines cached on open — stream
    shards are sized to fit host memory per DATA.md).  An unterminated
    final line is a torn tail and is never returned as a record."""

    def __init__(self, shard):
        with open(shard["path"], "rb") as f:
            data = f.read()
        lines = [ln for ln in data.split(b"\n") if ln.strip()]
        self._torn_tail = bool(data) and not data.endswith(b"\n")
        if self._torn_tail and lines:
            lines = lines[:-1]
        self._lines = lines
        self._path = shard["path"]

    def read_range(self, start, stop):
        n = len(self._lines)
        out = [self._lines[i].decode("utf-8")
               for i in range(start, min(stop, n))]
        torn = max(0, stop - max(start, n))
        err = None
        if torn:
            err = "jsonl shard %s holds %d whole line(s), range asked " \
                  "up to %d%s" % (self._path, n, stop,
                                  " (unterminated torn tail)"
                                  if self._torn_tail else "")
        return out, torn, err

    def close(self):
        self._lines = None


def _open_reader(shard):
    if shard.get("format") == "jsonl":
        return _JsonlShardReader(shard)
    return _RecordIOShardReader(shard)


def _default_decode(shard_format):
    if shard_format == "jsonl":
        return _json.loads
    return lambda raw: raw


# -- the decode worker pool --------------------------------------------------

_READER_CACHE_CAP = 8  # open readers per worker; LRU beyond this


def _run_task(task, decode_fn, decode_batch_fn, readers, worker_id):
    """One decode task on a worker: open (cached) → range read → decode.
    Returns ``(gen, seq, samples, meta)``; every failure mode that is
    not a torn tail raises (the pool converts it into the consumer
    re-raise)."""
    gen, seq, shard, shard_idx, start, stop = task
    meta = {"shard": shard_idx, "worker": worker_id, "torn": 0,
            "bytes": 0, "open_s": None, "decode_s": 0.0,
            "torn_err": None, "readers_open": len(readers)}
    if start >= stop:
        return gen, seq, [], meta
    if _fault.trigger("io.shard.torn"):
        # the drill: the whole range reads as a torn tail — skipped and
        # counted by the consumer, exactly like a real crashed-writer
        # truncation
        meta["torn"] = stop - start
        meta["torn_err"] = "[fault injection] site io.shard.torn fired " \
                           "for %s[%d:%d]" % (shard["path"], start, stop)
        return gen, seq, [], meta
    key = shard["path"]
    reader = readers.get(key)
    if reader is None:
        t0 = time.perf_counter()
        reader = _open_reader(shard)
        meta["open_s"] = time.perf_counter() - t0
        if len(readers) >= _READER_CACHE_CAP:
            old_key, old = next(iter(readers.items()))
            old.close()
            del readers[old_key]
        readers[key] = reader
    else:
        # LRU touch: re-insert at the back so active shards survive
        del readers[key]
        readers[key] = reader
    meta["readers_open"] = len(readers)
    raws, torn, torn_err = reader.read_range(start, stop)
    meta["torn"], meta["torn_err"] = torn, torn_err
    _fault.delay_if("io.decode.slow")
    _fault.check("io.decode.error",
                 "decode worker failure at %s[%d:%d]"
                 % (shard["path"], start, stop))
    t0 = time.perf_counter()
    if decode_batch_fn is not None:
        # vectorized task decode (one numpy pass over the whole chunk
        # instead of a Python call per record — the GIL these workers
        # share with the consumer is the scarce resource)
        samples = list(decode_batch_fn(raws))
        if len(samples) != len(raws):
            raise MXNetError(
                "decode_batch_fn returned %d samples for %d records"
                % (len(samples), len(raws)))
    else:
        decode = decode_fn or _default_decode(shard.get("format"))
        samples = [decode(raw) for raw in raws]
    meta["decode_s"] = time.perf_counter() - t0
    meta["bytes"] = sum(len(raw) for raw in raws)
    return gen, seq, samples, meta


def _worker_loop(worker_id, tasks, results, decode_fn, decode_batch_fn,
                 ship_exc):
    """Shared worker body (thread or forked process).  The first
    failure ships out as an error item — the exception object itself in
    thread mode (its ``__traceback__`` carries the worker frames for
    the consumer re-raise), ONLY the pre-formatted traceback strings in
    process mode (``ship_exc=False``): tracebacks don't pickle, and an
    exception object with an unpicklable attribute would be dropped by
    the mp queue's feeder thread — the error item must never be lost to
    its own transport."""
    readers = {}
    try:
        while True:
            task = tasks.get()
            if task is None:
                return
            try:
                results.put(_run_task(task, decode_fn, decode_batch_fn,
                                      readers, worker_id))
            except BaseException as e:  # noqa: BLE001 — re-raised there
                results.put(("__err__", task[0],
                             e if ship_exc else None,
                             traceback.format_exc(),
                             "%s: %s" % (type(e).__name__, e)))
                return
    finally:
        for r in readers.values():
            try:
                r.close()
            except Exception:
                pass


class _DecodePool:
    """N decode workers around bounded queues, shared across a loader's
    iterations (readers stay open, threads stay warm — a per-epoch
    respawn would re-pay thread spin-up and shard opens every epoch).
    Items are tagged with an iteration *generation*: ``begin()`` bumps
    it and drops whatever an abandoned iteration left queued, so stale
    in-flight results can never leak into the next epoch's order.

    ``mode`` is ``thread`` (default) or ``process`` (``fork`` — workers
    inherit the parent's decode closure and fault rules; they must
    never touch jax, and on platforms without fork the pool falls back
    to threads)."""

    def __init__(self, decode_fn, decode_batch_fn, num_workers, mode,
                 depth):
        self.num_workers = max(1, int(num_workers))
        self.depth = max(1, int(depth))
        self.window = self.depth + self.num_workers
        self.mode = mode
        self.gen = 0
        self._workers = []
        # a worker exits permanently after its first error; that exit
        # is recorded HERE (set when its __err__ item is consumed, any
        # generation) rather than inferred from is_alive() — the error
        # item lands on the queue BEFORE the thread terminates, so an
        # aliveness probe right after the re-raise races the scheduler
        self._degraded = False
        # items a SUPERSEDED consumer dequeued that belong to a newer
        # iteration: pushed back here (never dropped — the live
        # consumer would wait forever on the stolen sequence number)
        self._returns = []
        self._returns_lock = threading.Lock()
        if mode == "process":
            import multiprocessing as mp
            try:
                ctx = mp.get_context("fork")
            except ValueError:
                logging.warning(
                    "mxnet_tpu.stream: no fork start method on this "
                    "platform — decode workers fall back to threads")
                self.mode = mode = "thread"
        if mode == "process":
            self._tasks = ctx.Queue()
            self._results = ctx.Queue(maxsize=self.depth)
            spawn = lambda i: ctx.Process(  # noqa: E731
                target=_worker_loop,
                args=(i, self._tasks, self._results, decode_fn,
                      decode_batch_fn, False), daemon=True)
        else:
            self._tasks = _queue.Queue()
            self._results = _queue.Queue(maxsize=self.depth)
            spawn = lambda i: threading.Thread(  # noqa: E731
                target=_worker_loop,
                args=(i, self._tasks, self._results, decode_fn,
                      decode_batch_fn, True),
                daemon=True, name="mxtpu-stream-decode-%d" % i)
        for i in range(self.num_workers):
            w = spawn(i)
            w.start()
            self._workers.append(w)

    def begin(self):
        """Start a new iteration: bump the generation and drop tasks an
        abandoned iteration left queued (results already in flight are
        discarded by the generation filter in :meth:`get`).  Tasks
        already tagged with the NEW generation survive the drain — the
        epoch prefetch-ahead path submits the next epoch's first
        chunks under ``gen + 1`` before the iteration that will
        consume them begins, and dropping them would strand their
        sequence numbers forever."""
        self.gen += 1
        keep = []
        while True:
            try:
                item = self._tasks.get_nowait()
            except _queue.Empty:
                break
            if item[0] >= self.gen:
                keep.append(item)
        for item in keep:
            self._tasks.put(item)
        return self.gen

    def submit(self, gen, task_tail):
        self._tasks.put((gen,) + task_tail)

    def alive(self):
        return any(w.is_alive() for w in self._workers)

    def full_strength(self):
        """No worker has errored out and every worker is alive — a pool
        that survived an error is degraded and the loader rebuilds it
        at the next iteration rather than silently running at reduced
        decode throughput forever."""
        return bool(self._workers) and not self._degraded and \
            all(w.is_alive() for w in self._workers)

    @staticmethod
    def _item_gen(item):
        return item[1] if item[0] == "__err__" else item[0]

    def _take_return(self, gen):
        """Pop a pushed-back item of generation ``gen`` (pruning older
        leftovers an abandoned iteration will never collect)."""
        with self._returns_lock:
            self._returns = [i for i in self._returns
                             if self._item_gen(i) >= gen]
            for k, item in enumerate(self._returns):
                if self._item_gen(item) == gen:
                    return self._returns.pop(k)
        return None

    def _push_return(self, item):
        with self._returns_lock:
            self._returns.append(item)

    def get(self, gen):
        """Next result of generation ``gen`` (any order).  Stale-
        generation items are dropped; a NEWER-generation item here
        means another iteration superseded this consumer (one live
        iteration per loader — documented contract): the item is
        pushed back for the live consumer — never dropped — and THIS
        caller raises.  Raises the worker's failure at the consumption
        point — thread mode re-raises the original exception object
        (worker frames intact), process mode wraps the shipped
        traceback text.  A silently-dead worker pool (killed child)
        surfaces as MXNetError instead of a hang."""
        while True:
            item = self._take_return(gen)
            if item is None:
                try:
                    item = self._results.get(timeout=0.5)
                except _queue.Empty:
                    if not self.alive() and self._results.empty():
                        raise MXNetError(
                            "stream decode worker pool died without "
                            "reporting an error (killed process?)")
                    continue
            item_gen = self._item_gen(item)
            if item_gen > gen:
                # a newer-generation item in this consumer's hands:
                # hand it back either way — but it only means THIS
                # consumer is superseded when a newer iteration
                # actually began (pool.gen moved past ours).  The
                # other source of ahead-of-generation items is the
                # epoch prefetch-ahead (next epoch's chunks decoded
                # under gen+1 while this iteration drains its tail):
                # those belong to the NEXT consumer, not to anyone
                # superseding us.
                self._push_return(item)
                if self.gen > gen:
                    raise MXNetError(
                        "stream iteration superseded: a newer "
                        "iteration of this StreamLoader was started "
                        "(one live iteration per loader)")
                continue
            if isinstance(item, tuple) and item and item[0] == "__err__":
                _, err_gen, exc, tb_text, summary = item
                self._degraded = True  # its worker exits after this item
                if err_gen < gen:
                    # an abandoned iteration's worker died on a stale
                    # task: the pool shrank, but this iteration's data
                    # was never touched by it
                    logging.warning(
                        "mxnet_tpu.stream: decode worker died on a "
                        "stale-generation task: %s", summary)
                    continue
                if isinstance(exc, BaseException):
                    raise exc  # thread mode: original object + traceback
                raise MXNetError(
                    "stream decode worker failed: %s\n--- worker "
                    "traceback ---\n%s" % (summary, tb_text))
            if item_gen < gen:
                continue  # stale result from an abandoned iteration
            return item[1], item[2], item[3]

    def close(self):
        """Retire the workers: sentinel per worker, drain the bounded
        result queue so nobody stays wedged on a full put, bounded
        joins (a process that ignores them is terminated)."""
        for _ in self._workers:
            try:
                self._tasks.put(None)
            except Exception:
                pass
        deadline = time.monotonic() + 5.0
        for w in self._workers:
            while w.is_alive() and time.monotonic() < deadline:
                # keep the result queue draining so a worker blocked on
                # put() can reach its sentinel
                try:
                    self._results.get_nowait()
                    continue
                except _queue.Empty:
                    pass
                w.join(timeout=0.05)
            if w.is_alive() and hasattr(w, "terminate"):
                w.terminate()
        while True:
            try:
                self._results.get_nowait()
            except _queue.Empty:
                break
        with self._returns_lock:
            self._returns = []
        self._workers = []


# -- the loader --------------------------------------------------------------

class StreamLoader:
    """Batches from a shard set, exact-once across the elastic world.

    Two modes:

    - ``mode="epoch"`` (default): one finite pass per epoch over the
      shard set as pinned at ``set_epoch`` time, shards ordered by the
      epoch permutation, this rank's contiguous position span read as
      (shard, offset) ranges.  ``set_epoch(e)`` re-pins (an appending
      manifest is picked up at the next epoch); ``resume=`` takes a
      full cursor set and continues the interrupted epoch at ANY world
      size.
    - ``mode="follow"``: a continual stream — shards consumed once in
      publication order, each partitioned across the world; blocks
      (polling ``refresh()``) while the writer is ahead, ends when the
      manifest is sealed.  ``resume=`` re-partitions every old rank's
      un-consumed remainder.

    ``decode_fn(raw)`` maps one raw record (RecordIO payload bytes /
    JSONL line string) to a sample (anything the batchify accepts);
    defaults: raw bytes for RecordIO, ``json.loads`` for JSONL.

    Iteration yields device-prefetched batches through the PR-1
    ``_PrefetchIter`` (prefetch depth per ``MXTPU_DATA_PREFETCH``);
    ``cursor()`` is the world-agnostic resume stamp, advanced only when
    a batch is *delivered* to the caller.
    """

    def __init__(self, shard_set, batch_size, decode_fn=None,
                 decode_batch_fn=None, mode="epoch", epoch=0, rank=None,
                 world_size=None, seed=None, num_workers=None,
                 worker_mode=None, queue_depth=None, chunk_records=None,
                 prefetch=None, last_batch="keep", poll_secs=None,
                 batchify_fn=None, resume=None):
        from ..gluon.data import dataloader as _dl
        if isinstance(shard_set, str):
            shard_set = load_shard_set(shard_set)
        if not isinstance(shard_set, ShardSet):
            raise MXNetError("shard_set must be a ShardSet or a "
                             "manifest path, got %r" % (shard_set,))
        if mode not in ("epoch", "follow"):
            raise MXNetError("mode must be 'epoch' or 'follow'")
        if last_batch not in ("keep", "discard"):
            raise MXNetError("last_batch must be 'keep' or 'discard'")
        self._set = shard_set
        self._batch_size = int(batch_size)
        self._decode_fn = decode_fn
        self._decode_batch_fn = decode_batch_fn
        self._pool = None
        self._mode = mode
        if rank is None or world_size is None:
            from .. import elastic as _elastic
            mem = _elastic.membership()
            rank = mem["rank"] if rank is None else rank
            world_size = mem["world_size"] if world_size is None \
                else world_size
        self._rank, self._world = int(rank), int(world_size)
        self._seed = seed
        self._workers = num_workers if num_workers is not None \
            else _env_int("MXTPU_STREAM_WORKERS", 2)
        self._worker_mode = worker_mode or os.environ.get(
            "MXTPU_STREAM_WORKER_MODE", "thread")
        self._depth = queue_depth if queue_depth is not None \
            else _env_int("MXTPU_STREAM_QUEUE_DEPTH", 4)
        self._chunk = max(1, chunk_records if chunk_records is not None
                          else _env_int("MXTPU_STREAM_CHUNK_RECORDS", 64))
        self._prefetch = max(0, int(
            prefetch if prefetch is not None else _dl._default_prefetch()))
        self._last_batch = last_batch
        self._poll_secs = poll_secs if poll_secs is not None \
            else _env_float("MXTPU_STREAM_POLL_SECS", 0.2)
        self._batchify = batchify_fn or _dl.default_batchify_fn
        self._dl = _dl
        self._torn_warned = set()
        self._open_by_worker = {}
        # epoch-boundary prefetch-ahead (ISSUE 14 satellite): once this
        # rank's epoch-N spans are exhausted, the otherwise-idle decode
        # pool starts on epoch N+1's first chunks under the NEXT
        # iteration generation; set_epoch's re-pin is validated against
        # the speculation before the results are consumed (generation
        # tagging makes a wrong guess safe — it is simply discarded)
        self._epoch_prefetch = _env_int("MXTPU_STREAM_EPOCH_PREFETCH",
                                        1) > 0
        self._spec = None
        if mode == "epoch":
            self.set_epoch(epoch, resume=resume)
        else:
            self._shard_idx = 0
            self._consumed = 0
            self._assigned = {}
            if resume is not None:
                self._shard_idx, self._assigned = _assign.follow_resume(
                    resume, self._set.sizes, self._rank, self._world)

    # -- assignment state ----------------------------------------------------
    def set_epoch(self, epoch, resume=None):
        """Pin epoch ``epoch``'s assignment against the CURRENT shard
        list (refreshing the manifest first — this is where an appended
        shard enters coverage).  ``resume`` is a complete cursor set
        from a prior attempt of the SAME epoch: the remainder is
        re-partitioned for this rank at this world size — against the
        SHARD-SET SNAPSHOT the cursors were cut under (stamped into
        every epoch cursor), never the refreshed one: positions are
        meaningless under a different shard count/permutation, so a
        manifest that grew mid-epoch enters coverage at the NEXT epoch,
        and one that rewrote committed history is rejected."""
        if self._mode != "epoch":
            raise MXNetError("set_epoch on a follow-mode StreamLoader")
        self._set.refresh()
        self._epoch = int(epoch)
        self._sizes = self._set.sizes
        if resume is not None:
            for c in resume:
                if c.get("epoch") != self._epoch:
                    raise MXNetError(
                        "resume cursor is for epoch %s, not %d"
                        % (c.get("epoch"), self._epoch))
            snaps = {tuple(c.get("sizes") or ()) for c in resume}
            if len(snaps) != 1:
                raise MXNetError(
                    "resume cursors disagree on the shard-set snapshot "
                    "— not one consistent generation")
            snap = list(snaps.pop())
            if snap:
                if snap != self._sizes[:len(snap)]:
                    raise MXNetError(
                        "shard set changed incompatibly under the "
                        "cursors (snapshot sizes %s vs current %s): "
                        "committed history was rewritten, positions "
                        "cannot be mapped" % (snap, self._sizes))
                self._sizes = snap
            self._spans = _assign.resume_spans(resume, self._rank,
                                               self._world)
        else:
            lo, hi = _assign.span_for_rank(
                sum(self._sizes), self._rank, self._world)
            self._spans = [(lo, hi)] if hi > lo else []
        self._consumed = 0

    def cursor(self):
        """The world-agnostic resume stamp of what this loader has
        DELIVERED (batches handed to the caller — never prefetch-queue
        residents).  Pair it with the checkpoint the same cadence
        writes: ``CursorStore.save(generation, loader.cursor())``."""
        base = {"rank": self._rank, "world_size": self._world,
                "mode": self._mode}
        if self._mode == "epoch":
            base.update({"epoch": self._epoch,
                         "spans": [list(p) for p in self._spans],
                         "consumed": self._consumed,
                         # the snapshot positions are relative to — a
                         # resume must re-pin to exactly this view
                         "sizes": list(self._sizes)})
            return base
        sizes = self._set.sizes
        s = self._shard_idx
        if s < len(sizes):
            # membership check, NOT `or`: an empty override means "this
            # rank owns nothing of this shard" — falling through to the
            # fresh law would re-consume records another rank owns
            if str(s) in self._assigned:
                spans = self._assigned[str(s)]
            else:
                spans = [list(p) for p in _assign.follow_spans(
                    sizes[s], self._rank, self._world)]
        else:
            spans = []
        base.update({
            "shard": s, "spans": [list(p) for p in spans],
            "consumed": self._consumed,
            "assigned": {k: v for k, v in self._assigned.items()
                         if int(k) >= s},
        })
        return base

    def _fold(self, attrib):
        """Advance the durable cursor over delivered/ skipped records —
        called exactly when a batch crosses into the caller's hands."""
        if self._mode == "epoch":
            self._consumed += sum(n for _s, n in attrib)
            return
        for shard, n in attrib:
            if shard != self._shard_idx:
                for k in [k for k in self._assigned if int(k) < shard]:
                    del self._assigned[k]
                self._shard_idx = shard
                self._consumed = 0
            self._consumed += n

    # -- task generation -----------------------------------------------------
    def _chunks(self, ranges):
        for shard_idx, start, stop in ranges:
            shard = self._set.shards[shard_idx]
            for a in range(start, stop, self._chunk):
                yield (shard, shard_idx, a, min(a + self._chunk, stop))

    def _task_iter(self):
        if self._mode == "epoch":
            spans = _assign.slice_spans(
                self._spans, self._consumed,
                sum(b - a for a, b in self._spans))
            ranges = _assign.spans_to_ranges(self._sizes, self._epoch,
                                             spans, self._seed)
            for task in self._chunks(ranges):
                yield task
            return
        # follow mode: local pointers start at the durable cursor and
        # run ahead; the durable state advances at delivery (self._fold)
        s, skip = self._shard_idx, self._consumed
        while True:
            sizes = self._set.sizes
            if s >= len(sizes):
                if self._set.refresh():
                    continue
                if self._set.closed:
                    return
                yield None  # lull: writer hasn't published more yet
                continue
            # membership check, NOT `or`: an empty override means this
            # rank owns nothing of shard s (see cursor())
            if str(s) in self._assigned:
                spans = [tuple(p) for p in self._assigned[str(s)]]
            else:
                spans = _assign.follow_spans(sizes[s], self._rank,
                                             self._world)
            total = sum(b - a for a, b in spans)
            rem = _assign.slice_spans(spans, min(skip, total), total)
            if rem:
                for task in self._chunks([(s, a, b) for a, b in rem]):
                    yield task
            else:
                # a shard this rank owns nothing of must still advance
                # the cursor — as an IN-ORDER marker through the result
                # stream, never by mutating the durable state from this
                # read-ahead generator (deliveries for earlier shards
                # may still be in flight behind it)
                yield ("__skip__", s)
            s, skip = s + 1, 0

    # -- the ordered record/batch stream -------------------------------------
    def _ensure_pool(self):
        if self._pool is not None and self._pool.full_strength():
            return self._pool
        if self._pool is not None:
            self._pool.close()
        self._pool = _DecodePool(self._decode_fn, self._decode_batch_fn,
                                 self._workers, self._worker_mode,
                                 self._depth)
        return self._pool

    def close(self):
        """Retire the worker pool.  Idempotent; GC calls it too (also
        on a half-constructed instance whose __init__ raised before
        the pool slot existed), but a long-lived process cycling
        loaders should call it (or use the loader as a context
        manager) rather than waiting for GC."""
        pool = getattr(self, "_pool", None)
        self._pool = None
        if pool is not None:
            pool.close()

    __del__ = close

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- epoch-boundary prefetch-ahead ---------------------------------------
    def _spec_matches(self, spec, pool):
        """Does a recorded speculation describe EXACTLY the iteration
        about to run?  Inputs fully determine the task stream
        (``spans_to_ranges`` is pure), so matching inputs means the
        pre-submitted chunks are the iteration's true prefix."""
        return (spec is not None and self._mode == "epoch"
                and spec["pool"] == id(pool)
                and spec["epoch"] == self._epoch
                and spec["sizes"] == self._sizes
                and spec["spans"] == self._spans
                and spec["rank"] == self._rank
                and spec["world"] == self._world
                and self._consumed == 0)

    def _speculate(self, pool, gen):
        """This rank's epoch-N spans are exhausted and the pool is
        about to idle through ``set_epoch``: submit epoch N+1's first
        assigned chunks (the fresh law — a resume or a grown manifest
        invalidates the guess at the next iteration) under ``gen+1``,
        the generation the NEXT iteration's ``begin()`` will mint."""
        if not (self._epoch_prefetch and self._mode == "epoch"
                and pool.full_strength()):
            return
        next_epoch = self._epoch + 1
        sizes = self._sizes
        lo, hi = _assign.span_for_rank(sum(sizes), self._rank,
                                       self._world)
        spans = [(lo, hi)] if hi > lo else []
        if not spans:
            return
        ranges = _assign.spans_to_ranges(sizes, next_epoch, spans,
                                         self._seed)
        tasks, keys = [], []
        for task in self._chunks(ranges):
            if len(tasks) >= pool.window:
                break
            tasks.append(task)
            keys.append((task[1], task[2], task[3]))
        if not tasks:
            return
        for seq, task in enumerate(tasks):
            pool.submit(gen + 1, (seq,) + task)
        _telemetry.counter("io.epoch_prefetch").inc(len(tasks))
        self._spec = {"pool": id(pool), "gen": gen + 1,
                      "epoch": next_epoch, "sizes": list(sizes),
                      "spans": [(lo, hi)], "rank": self._rank,
                      "world": self._world, "keys": keys}

    def _adopt_speculation(self, pool, gen):
        """Called at iteration start (after ``begin()``): if the
        recorded speculation IS this iteration's prefix, return its
        chunk keys (the first ``len(keys)`` tasks are already in the
        pool under this generation); otherwise discard it — one more
        ``begin()`` makes the stale results unconsumable."""
        spec, self._spec = self._spec, None
        if spec is None:
            return gen, []
        if spec["gen"] == gen and self._spec_matches(spec, pool):
            _telemetry.counter("io.epoch_prefetch_hits").inc(
                len(spec["keys"]))
            return gen, spec["keys"]
        return pool.begin(), []

    def _results(self, pool, gen, preloaded=()):
        """Submit tasks into the pool (bounded window) and yield result
        items strictly in sequence order — byte-deterministic delivery
        no matter how workers interleave.  ``preloaded`` chunk keys
        were already submitted under this generation by the previous
        iteration's epoch prefetch-ahead: the iterator's first tasks
        are verified against them and NOT re-submitted."""
        tasks = self._task_iter()
        reorder = {}
        next_seq = 0
        submitted = len(preloaded)
        exhausted = False
        first_wait = True
        speculated = False
        for key in preloaded:
            t = next(tasks, None)
            actual = None if t is None or t[0] == "__skip__" \
                else (t[1], t[2], t[3])
            if actual != key:
                # inputs matched, so the pure task derivation cannot
                # diverge — reaching here is an internal bug, and
                # serving a mis-attributed chunk would silently break
                # exact-once; fail loudly instead
                raise MXNetError(
                    "epoch prefetch-ahead speculation diverged from "
                    "the live task stream (%r vs %r) — internal "
                    "invariant broken" % (key, actual))
        while True:
            while not exhausted and submitted - next_seq < pool.window:
                try:
                    t = next(tasks)
                except StopIteration:
                    exhausted = True
                    break
                if t is None:
                    break  # stream lull — no task to hand out yet
                if t[0] == "__skip__":
                    # zero-record shard for this rank: a local in-order
                    # marker, no pool round-trip
                    reorder[submitted] = ([], {
                        "shard": t[1], "worker": -1, "torn": 0,
                        "bytes": 0, "open_s": None, "decode_s": 0.0,
                        "torn_err": None, "readers_open": 0})
                    submitted += 1
                    continue
                pool.submit(gen, (submitted,) + t)
                submitted += 1
            if exhausted and not speculated:
                # the pool would idle through set_epoch: start on the
                # next epoch's first chunks while this iteration's
                # tail drains (their results are tagged gen+1 — the
                # next iteration consumes or discards them)
                speculated = True
                self._speculate(pool, gen)
            if next_seq == submitted:
                if exhausted:
                    return
                if pool.gen != gen:
                    # superseded mid-lull: an abandoned producer must
                    # not poll (and keep the "data" lease alive) forever
                    raise MXNetError(
                        "stream iteration superseded: a newer "
                        "iteration of this StreamLoader was started "
                        "(one live iteration per loader)")
                # follow-mode lull: the writer is ahead of us.  This
                # loop just POLLED the manifest — demonstrable liveness
                # — so renew the consumer's "data" lease (primary=False,
                # like the prefetcher's per-batch renewal): an armed
                # watchdog must not declare a healthy continual job
                # hung because its upstream paused between publishes
                _watchdog.renew("data", phase="stream-lull",
                                primary=False)
                time.sleep(self._poll_secs)
                continue
            while next_seq not in reorder:
                t0 = time.perf_counter()
                seq, samples, meta = pool.get(gen)
                dt = time.perf_counter() - t0
                # the FIRST wait of an iteration covers ramp-up —
                # startup, not steady state (the steptrace warmup
                # convention); it gets its own phase so the p99 of
                # io.queue_wait states the steady-state starvation
                # contract BENCH_MODE=stream asserts
                _telemetry.observe_phase(
                    "io.pool_spinup" if first_wait else "io.queue_wait",
                    dt)
                first_wait = False
                reorder[seq] = (samples, meta)
            samples, meta = reorder.pop(next_seq)
            next_seq += 1
            self._note(meta, samples)
            yield samples, meta

    def _note(self, meta, samples):
        """Consumer-side telemetry fold: counters plus the worker-
        measured phase durations (workers may be separate PROCESSES
        whose registries die with them, so durations ride the result
        and land in this process's histograms)."""
        if samples:
            _telemetry.counter("io.records").inc(len(samples))
        if meta["bytes"]:
            _telemetry.counter("io.bytes").inc(meta["bytes"])
        if meta["open_s"] is not None:
            _telemetry.observe_phase("io.shard_open", meta["open_s"])
        if samples or meta["decode_s"]:
            _telemetry.observe_phase("io.decode", meta["decode_s"])
        self._open_by_worker[meta["worker"]] = meta["readers_open"]
        _telemetry.gauge("io.shards_open").set(
            sum(self._open_by_worker.values()))
        if meta["torn"]:
            _telemetry.counter("io.torn_records").inc(meta["torn"])
            shard = meta["shard"]
            if shard not in self._torn_warned:
                self._torn_warned.add(shard)
                logging.warning(
                    "mxnet_tpu.stream: skipping %d torn record(s) in "
                    "shard %d (%s) — counted in io.torn_records",
                    meta["torn"], shard, meta["torn_err"])

    def _make_batches(self):
        """The producer generator ``_PrefetchIter`` wraps: yields
        ``(batch, attrib)`` pairs — the attribution rides OUTSIDE the
        batch so the delivery-side wrapper can fold the cursor exactly
        when the caller receives the batch."""
        pool = self._ensure_pool()
        gen = pool.begin()
        gen, preloaded = self._adopt_speculation(pool, gen)
        batches = _telemetry.counter("data.batches")
        B = self._batch_size
        try:
            # attribution entries are [shard, records, samples]:
            # decoded chunks carry records == samples, torn tails carry
            # records > 0 with 0 samples, skip markers 0/0 — so a batch
            # boundary can be cut at B SAMPLES while the cursor folds
            # RECORDS (torn records advance it without data)
            buf, attrib = [], []
            for samples, meta in self._results(pool, gen, preloaded):
                shard = meta["shard"]
                if samples:
                    buf.extend(samples)
                    attrib.append([shard, len(samples), len(samples)])
                if meta["torn"]:
                    attrib.append([shard, meta["torn"], 0])
                elif not samples:
                    # skip marker (a shard this rank owns nothing of):
                    # zero-record attribution advances the shard pointer
                    # in delivery order
                    attrib.append([shard, 0, 0])
                while len(buf) >= B:
                    with _telemetry.span("data.batchify", cat="data"):
                        out = self._batchify(buf[:B])
                    del buf[:B]
                    # cut the attribution at the batch's last sample;
                    # markers positioned after it ride the next batch
                    take, left, need = [], [], B
                    for shard_i, n_rec, n_smp in attrib:
                        if need == 0:
                            left.append([shard_i, n_rec, n_smp])
                        elif n_smp <= need:
                            take.append((shard_i, n_rec))
                            need -= n_smp
                        else:
                            take.append((shard_i, need))
                            left.append([shard_i, n_rec - need,
                                         n_smp - need])
                            need = 0
                    attrib = left
                    batches.inc()
                    yield out, take
            tail = [(s, n) for s, n, _smp in attrib]
            if buf and self._last_batch == "keep":
                with _telemetry.span("data.batchify", cat="data"):
                    out = self._batchify(buf)
                batches.inc()
                yield out, tail
            elif tail:
                # trailing torn records (or a discarded partial batch)
                # still count as covered — deliver the attribution on
                # an empty marker so the cursor reaches the end
                yield None, tail
        finally:
            # the pool persists across iterations (warm threads, open
            # readers); begin() on the next pass discards anything this
            # one left in flight
            pass

    def __iter__(self):
        bare = self._prefetch == 0
        if bare:
            inner = self._make_batches()
        else:
            inner = self._dl._PrefetchIter(self._make_batches,
                                           self._prefetch)

        def deliver():
            # prefetch=0 has no _PrefetchIter to own the "data" lease
            # lifecycle, so this wrapper does: renew per delivered
            # batch, release at iteration end — otherwise the lull
            # branch's renewal would CREATE a lease nothing ever
            # renews or retires, and an armed watchdog would kill a
            # healthy streaming job for it
            try:
                for batch, attrib in inner:
                    self._fold(attrib)
                    if batch is not None:
                        if bare:
                            _watchdog.renew("data", phase="data",
                                            primary=False)
                        yield batch
            finally:
                if bare:
                    _watchdog.release("data")
        return deliver()

    def __len__(self):
        if self._mode != "epoch":
            raise TypeError("a follow-mode stream has no length")
        n = sum(b - a for a, b in self._spans)
        if self._last_batch == "discard":
            return n // self._batch_size
        return (n + self._batch_size - 1) // self._batch_size
