"""``Module.fit(train_data=StreamLoader)`` sugar (ROADMAP item 5
follow-up, ISSUE 14 satellite).

:class:`StreamTrainIter` adapts an epoch-mode
:class:`~mxnet_tpu.stream.loader.StreamLoader` to the ``DataIter``
contract the training loop speaks (``provide_data`` /
``provide_label`` / ``reset`` / iteration yielding ``DataBatch``), so

    mod.fit(train_data=stream_loader, num_epoch=3, ...)

just works — ``BaseModule.fit`` wraps a bare StreamLoader in this
adapter automatically.  The pieces:

- **shape discovery** — ``provide_data`` peeks ONE batch (kept, and
  yielded first in epoch 0 — the cursor advanced for it, so it must
  reach the trainer exactly once, never be re-read);
- **epoch advance** — ``reset()`` (the fit loop calls it at each epoch
  end) re-pins the loader via ``set_epoch(epoch + 1)``: an appended
  manifest enters coverage at the next epoch, per the exact-once laws;
- **cursor → checkpoint wiring** — the fit loop stamps
  ``loader.cursor()`` onto the module at every epoch boundary
  (``Module._stream_cursor``) BEFORE the epoch-end callbacks run, so
  a plain ``callback.module_checkpoint(mod, prefix)`` callback writes
  manifests whose ``stream_cursor`` pairs the checkpoint epoch with
  exactly the records consumed when it was cut — the
  world-agnostic resume stamp ``StreamLoader(resume=...)`` replays.

The loader must use ``last_batch="discard"``: ``Module.bind`` compiles
one static batch shape, and a ragged tail batch would retrace it
(coverage is still exact — the discarded tail's records are folded
into the cursor by the loader's attribution markers).
"""
from __future__ import annotations

from ..base import MXNetError
from ..io import DataBatch, DataDesc

__all__ = ["StreamTrainIter"]


class StreamTrainIter:
    """DataIter facade over an epoch-mode StreamLoader.

    ``decode_fn`` samples must batchify into ``(data, label)`` pairs
    (the default batchify does this for tuple samples) or into a bare
    data array (label-less fitting); already-built ``DataBatch``
    objects pass through untouched."""

    def __init__(self, loader, data_name="data",
                 label_name="softmax_label"):
        if getattr(loader, "_mode", None) != "epoch":
            raise MXNetError(
                "Module.fit needs an epoch-mode StreamLoader (follow "
                "mode has no epoch boundary for the fit loop to pace)")
        if getattr(loader, "_last_batch", None) != "discard":
            raise MXNetError(
                "Module.fit over a StreamLoader requires "
                "last_batch='discard': bind compiles ONE static batch "
                "shape, and a ragged tail batch would retrace it "
                "(tail records still reach the cursor — coverage "
                "stays exact-once)")
        self._loader = loader
        self._data_name = data_name
        self._label_name = label_name
        self._peek = None
        self._inner = None
        self.batch_size = loader._batch_size

    # -- shape discovery ---------------------------------------------------
    def _peek_batch(self):
        if self._peek is None:
            if self._inner is None:
                self._inner = iter(self._loader)
            try:
                self._peek = self._to_batch(next(self._inner))
            except StopIteration:
                raise MXNetError(
                    "the stream has no complete batch for this rank — "
                    "cannot derive provide_data (grow the shard set "
                    "or shrink batch_size/world)")
        return self._peek

    @property
    def provide_data(self):
        b = self._peek_batch()
        return [DataDesc(self._data_name, tuple(a.shape),
                         dtype=a.dtype) for a in b.data]

    @property
    def provide_label(self):
        b = self._peek_batch()
        return [DataDesc(self._label_name, tuple(a.shape),
                         dtype=a.dtype) for a in b.label]

    # -- cursor ------------------------------------------------------------
    def stream_cursor(self):
        """The loader's world-agnostic resume stamp — what the fit
        loop hands the checkpoint manifest at each epoch boundary."""
        return self._loader.cursor()

    # -- DataIter protocol -------------------------------------------------
    def _to_batch(self, batch):
        if isinstance(batch, DataBatch):
            return batch
        if isinstance(batch, (tuple, list)):
            if len(batch) == 2:
                return DataBatch(data=[batch[0]], label=[batch[1]],
                                 pad=0)
            return DataBatch(data=list(batch), label=[], pad=0)
        return DataBatch(data=[batch], label=[], pad=0)

    def __iter__(self):
        # one live iteration per loader: adopt the peek's iteration
        # instead of superseding it (the peeked batch advanced the
        # cursor — it must reach the trainer exactly once)
        inner = self._inner if self._inner is not None \
            else iter(self._loader)
        self._inner = None

        def gen():
            if self._peek is not None:
                first, self._peek = self._peek, None
                yield first
            for b in inner:
                yield self._to_batch(b)
        return gen()

    def reset(self):
        """Epoch boundary (the fit loop calls this after each epoch):
        abandon any leftover iteration state and re-pin the next
        epoch's assignment."""
        self._peek = None
        self._inner = None
        self._loader.set_epoch(self._loader._epoch + 1)


def maybe_wrap(train_data):
    """``BaseModule.fit``'s sugar hook: a bare StreamLoader becomes a
    StreamTrainIter; anything else (including an already-wrapped
    adapter) passes through."""
    from .loader import StreamLoader
    if isinstance(train_data, StreamLoader):
        return StreamTrainIter(train_data)
    return train_data
