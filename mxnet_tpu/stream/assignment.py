"""Elastic-aware shard assignment: the exact-once laws on (shard, offset).

``elastic.shard_for_epoch`` (PR 6) states the resume law at sample
granularity: one epoch permutation seeded by ``(seed, epoch)`` ONLY —
never by the world size — cut contiguously by rank, so the union over
ranks is every sample exactly once at ANY world size.  Streaming from
disk needs the same law expressed over **(shard, offset) ranges** so a
rank reads contiguous runs of records (sequential I/O) instead of a
scattered index set:

- **Position space.**  An epoch over shards of sizes ``[n_0..n_k]``
  orders the shards by the epoch permutation (same RNG law as
  ``shard_for_epoch``, applied to shard indices) and concatenates them:
  global position ``p`` ∈ [0, N) maps to one (shard, offset).  Records
  stay sequential *within* a shard — the permutation shuffles at shard
  granularity, which is what keeps reads contiguous.
- **The cut.**  Rank ``r`` of ``world`` owns the contiguous position
  span given by the same base/extra law ``shard_for_epoch`` uses.
  Degenerate case: when every shard holds ONE record, position space
  *is* the PR-6 sample permutation and the ranges reduce to exactly
  ``shard_for_epoch``'s indices (test-pinned).
- **Cursors.**  A rank's progress is "consumed ``k`` records of my span
  concatenation" plus the spans themselves (so cursor-derived
  assignments compose through repeated reshards).  Resuming at ANY new
  world size: every old rank consumed a *prefix* of its spans, so the
  remaining work is a union of position spans; sort them, cut the
  remainder contiguously for the new world — still exactly once.

All functions are pure (no env, no I/O) except for the ``seed`` default
(``MXTPU_DATA_SEED``, matching ``shard_for_epoch``); ``CursorStore`` is
the small persistence layer the continual-training loop stamps next to
its checkpoints (DATA.md "Cursors").
"""
from __future__ import annotations

import json
import os
import re
import time

import numpy as _np

from ..base import MXNetError

__all__ = ["shard_order", "span_for_rank", "spans_to_ranges",
           "ranges_for_epoch", "slice_spans", "resume_spans",
           "follow_spans", "follow_resume", "CursorStore"]

CURSOR_SCHEMA = "mxtpu-stream-cursor-1"


def _default_seed(seed):
    if seed is not None:
        return int(seed)
    try:
        return int(os.environ.get("MXTPU_DATA_SEED", "0") or 0)
    except ValueError:
        return 0


def shard_order(num_shards, epoch, seed=None):
    """The epoch's shard permutation — the exact RNG law of
    ``elastic.shard_for_epoch`` applied to shard indices, so the
    one-record-per-shard degenerate case reproduces PR 6 bit-for-bit."""
    seed = _default_seed(seed)
    return _np.random.RandomState(
        (seed * 1_000_003 + int(epoch)) % (2 ** 32)).permutation(
            int(num_shards))


def span_for_rank(total, rank, world_size):
    """Rank ``rank``'s contiguous position span ``(lo, hi)`` of a
    ``total``-record space under the base/extra remainder law (lowest
    ranks absorb the remainder, uneven by at most one)."""
    world_size = int(world_size)
    rank = int(rank)
    if world_size < 1:
        raise ValueError("world_size must be >= 1, got %d" % world_size)
    if not 0 <= rank < world_size:
        raise ValueError("rank %d outside world of %d"
                         % (rank, world_size))
    base, extra = divmod(int(total), world_size)
    lo = rank * base + min(rank, extra)
    return lo, lo + base + (1 if rank < extra else 0)


def spans_to_ranges(sizes, epoch, spans, seed=None):
    """Map position spans into ``(shard, start, stop)`` read ranges via
    the epoch's shard order.  Ranges come back in position order (the
    deterministic delivery order every rank agrees on)."""
    order = shard_order(len(sizes), epoch, seed)
    bounds = [0]
    for s in order:
        bounds.append(bounds[-1] + int(sizes[int(s)]))
    out = []
    for lo, hi in spans:
        lo, hi = int(lo), int(hi)
        if hi > bounds[-1]:
            raise MXNetError(
                "span (%d, %d) exceeds the epoch's %d records"
                % (lo, hi, bounds[-1]))
        for k, shard in enumerate(order):
            beg, end = bounds[k], bounds[k + 1]
            if end <= lo:
                continue
            if beg >= hi:
                break
            out.append((int(shard), max(lo, beg) - beg,
                        min(hi, end) - beg))
    return out


def ranges_for_epoch(sizes, epoch, rank=None, world_size=None, seed=None):
    """One rank's read ranges for a fresh epoch: the (shard, offset)
    form of ``elastic.shard_for_epoch``.  ``rank``/``world_size``
    default to the current elastic membership."""
    if rank is None or world_size is None:
        from .. import elastic as _elastic
        mem = _elastic.membership()
        rank = mem["rank"] if rank is None else rank
        world_size = mem["world_size"] if world_size is None \
            else world_size
    lo, hi = span_for_rank(sum(int(n) for n in sizes), rank, world_size)
    return spans_to_ranges(sizes, epoch, [(lo, hi)], seed)


def slice_spans(spans, lo, hi):
    """The [lo, hi) slice of a span list's *concatenation*, as spans.
    (Cutting a remainder set for a new rank.)"""
    out = []
    pos = 0
    for a, b in spans:
        n = b - a
        s, e = max(lo, pos), min(hi, pos + n)
        if s < e:
            out.append((a + (s - pos), a + (e - pos)))
        pos += n
    return out


def _remaining(cursor):
    """The un-consumed suffix of one cursor's span concatenation."""
    spans = [(int(a), int(b)) for a, b in cursor["spans"]]
    total = sum(b - a for a, b in spans)
    consumed = int(cursor["consumed"])
    if not 0 <= consumed <= total:
        raise MXNetError(
            "cursor consumed %d outside its %d-record assignment"
            % (consumed, total))
    return slice_spans(spans, consumed, total)


def _check_cursor_set(cursors):
    if not cursors:
        raise MXNetError("empty cursor set")
    worlds = {int(c["world_size"]) for c in cursors}
    if len(worlds) != 1:
        raise MXNetError(
            "cursor set spans multiple world sizes %s — not one "
            "consistent snapshot" % sorted(worlds))
    w = worlds.pop()
    ranks = sorted(int(c["rank"]) for c in cursors)
    if ranks != list(range(w)):
        raise MXNetError(
            "cursor set is incomplete: have ranks %s of world %d"
            % (ranks, w))


def resume_spans(cursors, rank, world_size):
    """Epoch-mode reshard: given ONE consistent cursor per old rank
    (each a prefix-consumed span assignment), the new ``rank``'s spans
    over the remaining records at the new ``world_size``.  The union
    over new ranks is exactly the un-consumed set — exact-once coverage
    survives the world change."""
    _check_cursor_set(cursors)
    rem = []
    for c in sorted(cursors, key=lambda c: int(c["rank"])):
        rem.extend(_remaining(c))
    rem.sort()
    total = sum(b - a for a, b in rem)
    lo, hi = span_for_rank(total, rank, world_size)
    return slice_spans(rem, lo, hi)


# -- follow mode (continual streams) ----------------------------------------
#
# A continual stream has no epoch: shards are consumed once, in
# publication order, each partitioned across the current world by
# span_for_rank over its own records (identity order within the shard —
# there is nothing to shuffle in a stream you see once).  A cursor is
# (shard index, consumed-within-shard) plus an ``assigned`` override map
# for shards whose spans came from an earlier reshard rather than the
# fresh law — which is what makes reshards compose.

def follow_spans(n_records, rank, world_size):
    """Fresh-law spans of one stream shard for ``rank``: the contiguous
    cut, identity order."""
    lo, hi = span_for_rank(n_records, rank, world_size)
    return [(lo, hi)] if hi > lo else []


def _old_spans(cursor, shard_idx, sizes):
    """The spans OLD rank ``cursor`` owned in ``shard_idx``: its
    override when one exists, else the fresh law at its world."""
    assigned = cursor.get("assigned") or {}
    key = str(int(shard_idx))
    if key in assigned:
        return [(int(a), int(b)) for a, b in assigned[key]]
    return follow_spans(int(sizes[shard_idx]), int(cursor["rank"]),
                        int(cursor["world_size"]))


def follow_resume(cursors, sizes, rank, world_size):
    """Follow-mode reshard: from one consistent cursor per old rank,
    compute the new ``rank``'s ``(start_shard, assigned)`` where
    ``assigned`` maps shard index → position spans for every shard any
    old rank had started but not finished (later shards follow the
    fresh law at the new world).  The union over new ranks of
    (assigned ∪ fresh-law tail) is exactly every un-consumed record
    once."""
    _check_cursor_set(cursors)
    n_shards = len(sizes)
    starts = [min(int(c["shard"]), n_shards) for c in cursors]
    lo_shard = min(starts)
    hi_shard = max(starts)  # exclusive of fully-fresh shards beyond
    assigned = {}
    for s in range(lo_shard, min(hi_shard + 1, n_shards)):
        rem = []
        for c in cursors:
            cs = int(c["shard"])
            if cs > s:
                continue  # old rank already finished its slice of s
            if cs == s:
                rem.extend(_remaining(c))
            else:  # cs < s: started nothing of s — its whole slice remains
                rem.extend(_old_spans(c, s, sizes))
        rem.sort()
        total = sum(b - a for a, b in rem)
        lo, hi = span_for_rank(total, rank, world_size)
        assigned[str(s)] = [list(p) for p in slice_spans(rem, lo, hi)]
    return lo_shard, assigned


# -- cursor persistence ------------------------------------------------------

class CursorStore:
    """Per-rank stream cursors, one atomic JSON per (generation, rank),
    published next to the checkpoints they pair with.

    The exact-once resume law needs ONE CONSISTENT SNAPSHOT of every
    rank's position — so cursors are written in *generations* (the
    training loop writes generation ``g`` on the same cadence/barrier
    as checkpoint epoch ``g``), and ``load_latest()`` returns only the
    newest generation for which EVERY rank of that generation's world
    wrote its file.  A rank that died mid-generation leaves it
    incomplete; resume falls back to the previous complete one, and the
    records consumed after it are simply replayed — correct, because
    the parameter state resumes from the paired checkpoint, discarding
    those records' updates too.  World-agnostic on load, like the PR-6
    v2 checkpoint manifests: the files record the world that wrote
    them; any new world re-partitions from them.
    """

    _NAME = re.compile(r"^stream-cursor-g(\d+)-r(\d+)\.json$")

    def __init__(self, directory):
        self.dir = os.fspath(directory)

    def path(self, generation, rank):
        return os.path.join(self.dir, "stream-cursor-g%06d-r%03d.json"
                            % (int(generation), int(rank)))

    def save(self, generation, cursor):
        """Atomically publish one rank's cursor for ``generation``.
        ``cursor`` must carry ``rank``/``world_size`` (the loader's
        ``cursor()`` does) — completeness of a generation is judged
        against the world stamped inside it."""
        from ..checkpoint import _plain_atomic_write
        os.makedirs(self.dir, exist_ok=True)
        doc = dict(cursor)
        doc["schema"] = CURSOR_SCHEMA
        doc["generation"] = int(generation)
        doc["time"] = time.time()
        _plain_atomic_write(
            self.path(generation, cursor["rank"]),
            json.dumps(doc, indent=1).encode("utf-8"))

    def _scan(self):
        out = {}
        try:
            names = os.listdir(self.dir)
        except OSError:
            return out
        for name in names:
            m = self._NAME.match(name)
            if m:
                out.setdefault(int(m.group(1)), {})[int(m.group(2))] = \
                    os.path.join(self.dir, name)
        return out

    def generations(self):
        return sorted(self._scan())

    def load(self, generation):
        """Every cursor of one generation (rank-sorted), or None when
        any file is missing/unreadable — half a snapshot is no
        snapshot."""
        by_rank = self._scan().get(int(generation), {})
        cursors = []
        for rank in sorted(by_rank):
            try:
                with open(by_rank[rank], "rb") as f:
                    cursors.append(json.loads(f.read().decode("utf-8")))
            except (OSError, ValueError):
                return None
        if not cursors:
            return None
        world = {int(c["world_size"]) for c in cursors}
        if len(world) != 1 or sorted(int(c["rank"]) for c in cursors) \
                != list(range(world.pop())):
            return None  # incomplete or mixed-world generation
        return cursors

    def load_latest(self):
        """``(generation, [cursors])`` of the newest COMPLETE
        generation, or ``(None, None)``."""
        for g in reversed(self.generations()):
            cursors = self.load(g)
            if cursors is not None:
                return g, cursors
        return None, None
