"""Parameter-free modules implemented in Python.

Analogue of /root/reference/python/mxnet/module/python_module.py (:28
PythonModule, :240 PythonLossModule): BaseModule subclasses with no
parameters of their own, used to splice host-side computation (custom
losses, metrics bridges) into a SequentialModule chain.  Here the
"python" computation is still jax-backed NDArray math, so a chain with a
PythonLossModule stays on-device.
"""
from __future__ import annotations

import logging

from .. import ndarray as nd
from ..base import MXNetError
from .base_module import BaseModule

__all__ = ["PythonModule", "PythonLossModule"]


class PythonModule(BaseModule):
    """Module with no parameters: subclasses implement forward/backward;
    every parameter-related API is a documented no-op."""

    def __init__(self, data_names, label_names, output_names,
                 logger=logging):
        super().__init__(logger=logger)
        self._data_names = list(data_names or [])
        self._label_names = list(label_names or [])
        self._output_names = list(output_names or [])
        self._data_shapes = None
        self._label_shapes = None
        self._output_shapes = None

    # -- symbol/io info ----------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return self._output_shapes

    # -- parameters: none --------------------------------------------------
    def get_params(self):
        return ({}, {})

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        self.params_initialized = True

    def update(self):
        pass

    def update_metric(self, eval_metric, labels):
        if self._label_shapes:
            eval_metric.update(labels, self.get_outputs())

    # -- binding -----------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already binded, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        assert len(data_shapes) == len(self._data_names)
        self._data_shapes = data_shapes
        self._label_shapes = label_shapes
        self._output_shapes = self._compute_output_shapes()
        self.binded = True

    def _compute_output_shapes(self):
        """Subclasses define outputs from self._data_shapes."""
        raise NotImplementedError()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        self.optimizer_initialized = True

    def install_monitor(self, mon):
        pass


class PythonLossModule(PythonModule):
    """Head module computing a loss in Python: forward passes data
    through (so predictions remain visible), backward emits the gradient
    of the chosen loss w.r.t. its input (reference python_module.py:240).
    """

    def __init__(self, name="pyloss", data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 grad_func=None):
        super().__init__(data_names=data_names, label_names=label_names,
                         output_names=[name + "_output"], logger=logger)
        self._name = name
        self._scores = None
        self._labels = None
        self._scores_grad = None
        self._grad_func = grad_func

    def _compute_output_shapes(self):
        return [(self._name + "_output", self._data_shapes[0][1])]

    def forward(self, data_batch, is_train=None):
        self._scores = data_batch.data[0]
        if is_train is None:
            is_train = self.for_training
        if is_train and data_batch.label:
            self._labels = data_batch.label[0]

    def get_outputs(self, merge_multi_context=True):
        return [self._scores]

    def backward(self, out_grads=None):
        assert out_grads is None, \
            "PythonLossModule is a loss head; it accepts no out_grads"
        assert self.for_training
        if self._grad_func is not None:
            grad = self._grad_func(self._scores, self._labels)
            if not isinstance(grad, nd.NDArray):
                grad = nd.array(grad)
            self._scores_grad = grad
        else:
            # default: cross-entropy over softmax scores, the head the
            # reference shipped
            scores = self._scores
            labels = self._labels.astype("int32")
            prob = scores.asnumpy()
            import numpy as _np
            # (p - onehot), unnormalized: the chained Module's
            # rescale_grad=1/batch applies the normalization once
            g = prob.copy()
            g[_np.arange(g.shape[0]), labels.asnumpy().astype(int)] -= 1.0
            self._scores_grad = nd.array(g)

    def get_input_grads(self, merge_multi_context=True):
        if self._scores_grad is None:
            raise MXNetError("call backward() before get_input_grads()")
        return [self._scores_grad]

    def install_monitor(self, mon):
        pass
