"""Module: the symbolic training Model API.

Port of /root/reference/python/mxnet/module/module.py (246-631).  The
reference bound one executor per GPU and layered gradient reduction over
KVStore (DataParallelExecutorGroup, module/executor_group.py:99).  The
TPU-native design binds ONE executor — XLA SPMD over a device mesh replaces
the per-device executor group, and the fused forward_backward is a single
compiled program.  Multi-context calls (context=[tpu(0), tpu(1), ...]) keep
working: the batch stays whole and the step is sharded across the mesh by
the parallel layer rather than split by Python.
"""
from __future__ import annotations

import logging
import time

from .. import context as ctx_mod
from .. import ndarray as nd
from .. import optimizer as opt
from ..base import MXNetError
from ..initializer import Uniform, InitDesc
from ..model import (_create_kvstore, _initialize_kvstore, _update_params,
                     _update_params_on_kvstore, load_checkpoint,
                     save_checkpoint)
from .base_module import BaseModule, _check_input_names

__all__ = ["Module"]


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, partition_rules=None):
        """``partition_rules``: optional parallel.sharding rule list
        ((pattern, PartitionSpec[, ndim]) tuples or PartitionRule
        objects) resolved over the named param tree at bind — model code
        stays sharding-agnostic while a multi-context bind places every
        param per rule (replicated when no rule matches)."""
        super().__init__(logger=logger)
        self._partition_rules = partition_rules
        if context is None:
            context = ctx_mod.current_context()
        if isinstance(context, ctx_mod.Context):
            context = [context]
        self._context = context
        self._work_load_list = work_load_list

        self._symbol = symbol
        data_names = list(data_names) if data_names is not None else []
        label_names = list(label_names) if label_names is not None else []
        state_names = list(state_names) if state_names is not None else []
        fixed_param_names = list(fixed_param_names) \
            if fixed_param_names is not None else []

        _check_input_names(symbol, data_names, "data", True)
        _check_input_names(symbol, label_names, "label", False)
        _check_input_names(symbol, state_names, "state", True)
        _check_input_names(symbol, fixed_param_names, "fixed_param", True)

        arg_names = symbol.list_arguments()
        input_names = data_names + label_names + state_names
        self._param_names = [x for x in arg_names if x not in input_names]
        self._fixed_param_names = fixed_param_names
        self._aux_names = symbol.list_auxiliary_states()
        self._data_names = data_names
        self._label_names = label_names
        self._state_names = state_names
        self._output_names = symbol.list_outputs()

        self._arg_params = None
        self._aux_params = None
        self._params_dirty = False

        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = None
        self._updater = None
        self._preload_opt_states = None
        self._fused = None  # fused fit_step cache (program + opt state)
        self._consec_guard_skips = 0  # divergence-guard skip streak
        self._precision = None  # PrecisionPolicy (mxnet_tpu.precision)

        self._exec = None
        self._data_shapes = None
        self._label_shapes = None

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        """Create a Module from a saved checkpoint (reference :146)."""
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = "%s-%04d.states" % (prefix, epoch)
        return mod

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False,
                        keep_last=None, mode=None):
        """Save symbol+params(+optimizer states) (reference :173).

        Crash-safe: every artifact is written atomically and the epoch's
        manifest commits last (checkpoint.CheckpointManager), so a crash
        mid-save can never produce a checkpoint that recovery would
        mistake for complete.  ``keep_last`` prunes to the N newest
        complete checkpoints.  ``mode`` ("sync"/"async"/None→env): under
        the async pipeline this call only snapshots to host memory and
        the write overlaps subsequent training; writer failures surface
        on the next fit_step/save/flush (checkpoint.py)."""
        from ..checkpoint import CheckpointManager
        states = None
        if save_optimizer_states:
            states = self._optimizer_states_bytes()
        arg_params, aux_params = self.get_params()
        CheckpointManager(prefix, keep_last=keep_last).save(
            epoch, arg_params, aux_params, symbol=self._symbol,
            optimizer_states=states, mode=mode,
            sharding=self._sharding_stamp(),
            # the streaming-fit sugar: BaseModule.fit stamps the
            # StreamLoader's exact-once cursor here at each epoch
            # boundary, so a plain module_checkpoint callback writes
            # manifests StreamLoader(resume=...) can replay
            stream_cursor=getattr(self, "_stream_cursor", None))

    def _sharding_stamp(self):
        """Manifest stamp for the run's in-memory layout (SCALING.md):
        {"zero_stage", "mesh", "opt_state", "specs"} when the fused step
        runs ZeRO-1 on a mesh, else None.  The state PAYLOAD on disk is
        always full-size — `_optimizer_states_bytes` flushes through the
        Updater, and converting a dp-sharded jax array to host bytes IS
        the all-gather-on-save — so the stamp documents provenance and
        lets an elastic resume at a different world size reshard
        deliberately instead of guessing."""
        fused = self._fused
        if not fused or not fused.get("zero"):
            return None
        mesh = self._exec._mesh
        return {
            "zero_stage": 1,
            "mesh": {k: int(v) for k, v in mesh.shape.items()},
            "opt_state": "gathered",
            "specs": {name: str(s.spec)
                      for name, s in fused["zero"].items()},
        }

    # -- properties --------------------------------------------------------
    @property
    def graph_report(self):
        """The bind's graph rewrite-pipeline pass report (nodes
        before/after, rewrites by pattern, per-pass wall time), or None
        before bind / with the pipeline disabled."""
        return self._exec._graph_report if self._exec is not None else None

    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        assert self.binded
        return self._data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        _, out_shapes, _ = self._symbol.infer_shape(
            **dict([(d[0], d[1]) for d in
                    (self._data_shapes + (self._label_shapes or []))]))
        return list(zip(self._output_names, out_shapes))

    # -- parameters --------------------------------------------------------
    def get_params(self):
        assert self.binded and self.params_initialized
        if self._params_dirty:
            self._sync_params_from_devices()
        return (self._arg_params, self._aux_params)

    def init_params(self, initializer=None, arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        """Initialize parameters (reference module.py:246)."""
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before initializing the parameters"
        if initializer is None:
            initializer = Uniform(0.01)

        if self._arg_params is None:
            self._arg_params = {
                name: nd.zeros(self._exec.arg_dict[name].shape,
                               dtype=self._exec.arg_dict[name].dtype)
                for name in self._param_names}
        if self._aux_params is None:
            self._aux_params = {
                name: nd.zeros(self._exec.aux_dict[name].shape,
                               dtype=self._exec.aux_dict[name].dtype)
                for name in self._aux_names}

        attrs = self._symbol.attr_dict()

        def _impl(name, arr, cache):
            if cache is not None:
                if name in cache:
                    cache_arr = cache[name]
                    if cache_arr is not arr:
                        if cache_arr.shape != arr.shape:
                            raise MXNetError(
                                "Shape mismatch for %s: %s vs %s" %
                                (name, str(cache_arr.shape),
                                 str(arr.shape)))
                        cache_arr.copyto(arr)
                else:
                    if not allow_missing:
                        raise RuntimeError("%s is not presented" % name)
                    if initializer is not None:
                        initializer(name, arr)
            else:
                initializer(name, arr)

        for name, arr in sorted(self._arg_params.items()):
            desc = InitDesc(name, attrs.get(name))
            _impl(desc, arr, arg_params)
        for name, arr in sorted(self._aux_params.items()):
            desc = InitDesc(name, attrs.get(name))
            _impl(desc, arr, aux_params)

        self.params_initialized = True
        self._params_dirty = False
        self._push_params_to_exec()

    def _push_params_to_exec(self):
        for name, arr in self._arg_params.items():
            if name in self._exec.arg_dict:
                self._exec.arg_dict[name]._set_data(arr._data)
        for name, arr in self._aux_params.items():
            if name in self._exec.aux_dict:
                self._exec.aux_dict[name]._set_data(arr._data)

    def _sync_params_from_devices(self):
        for name in self._param_names:
            self._arg_params[name]._set_data(self._exec.arg_dict[name]._data)
        for name in self._aux_names:
            self._aux_params[name]._set_data(self._exec.aux_dict[name]._data)
        self._params_dirty = False

    # -- binding -----------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        """Compile the symbol for the given shapes (reference module.py:351).

        simple_bind → trace → XLA; PlanMemory/bulking are XLA's problem now.
        """
        if force_rebind:
            self._exec = None
            self.binded = False
        if self.binded:
            self.logger.warning("Already binded, ignoring bind()")
            return
        # the fused step program closes over the executor being replaced;
        # optimizer state (plain jnp arrays) survives via _fused_setup
        self._fused_flush_to_updater()
        self._fused = None

        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad

        def _norm(shapes):
            if shapes is None:
                return None
            out = []
            for s in shapes:
                if hasattr(s, "name"):
                    out.append((s.name, tuple(s.shape)))
                else:
                    out.append((s[0], tuple(s[1])))
            return out

        self._data_shapes = _norm(data_shapes)
        self._label_shapes = _norm(label_shapes)

        shape_kwargs = dict(self._data_shapes)
        if self._label_shapes:
            shape_kwargs.update(dict(self._label_shapes))

        req = {}
        for name in self._symbol.list_arguments():
            if name in self._data_names:
                req[name] = "write" if inputs_need_grad else "null"
            elif name in self._label_names or name in self._state_names:
                req[name] = "null"
            elif name in self._fixed_param_names:
                req[name] = "null"
            elif not for_training:
                req[name] = "null"
            else:
                req[name] = grad_req if isinstance(grad_req, str) \
                    else grad_req.get(name, "write")

        ctx = self._context[0]
        mesh = batch_names = None
        if len(self._context) > 1:
            # Module(context=[N devices]) → one SPMD program over a dp mesh.
            # The reference sliced every batch across per-device executors
            # (executor_group.py:296-378) and reduced grads through KVStore;
            # here the whole batch is dp-sharded into ONE compiled step and
            # XLA inserts the gradient all-reduce over ICI.
            from ..parallel.mesh import dp_mesh_from_ctx
            mesh = dp_mesh_from_ctx(self._context)
            batch_names = self._data_names + self._label_names
        self._exec = self._symbol.simple_bind(
            ctx, grad_req=req, mesh=mesh, batch_names=batch_names,
            partition_rules=self._partition_rules, **shape_kwargs)
        self.binded = True
        if shared_module is not None and shared_module.params_initialized:
            self._arg_params = shared_module._arg_params
            self._aux_params = shared_module._aux_params
            self.params_initialized = True
            self._push_params_to_exec()
        elif self.params_initialized:
            self._push_params_to_exec()

    # -- optimizer ---------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        """Set up optimizer + kvstore (reference module.py:460)."""
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring...")
            return

        if self._params_dirty:
            self._sync_params_from_devices()

        (kvstore, update_on_kvstore) = _create_kvstore(
            kvstore, len(self._context), self._arg_params)
        batch_size = self._data_shapes[0][1][0]
        if kvstore and "dist" in kvstore.type and \
                "_sync" in kvstore.type:
            batch_size *= kvstore.num_workers
        rescale_grad = 1.0 / batch_size

        if isinstance(optimizer, str):
            idx2name = {i: n for i, n in enumerate(self._param_names)}
            optimizer_params = dict(optimizer_params)
            if "rescale_grad" not in optimizer_params:
                optimizer_params["rescale_grad"] = rescale_grad
            optimizer = opt.create(optimizer, sym=self.symbol,
                                   param_idx2name=idx2name,
                                   **optimizer_params)
        else:
            assert isinstance(optimizer, opt.Optimizer)

        self._optimizer = optimizer
        self._kvstore = kvstore
        self._update_on_kvstore = update_on_kvstore
        self._updater = None
        optimizer.set_lr_mult({})
        optimizer.set_wd_mult({})

        if kvstore:
            param_arrays = [[self._exec.arg_dict[n]]
                            for n in self._param_names]
            _initialize_kvstore(kvstore=kvstore, param_arrays=param_arrays,
                                arg_params=self._arg_params,
                                param_names=self._param_names,
                                update_on_kvstore=update_on_kvstore)
            if update_on_kvstore:
                kvstore.set_optimizer(self._optimizer)
        if not update_on_kvstore:
            self._updater = opt.get_updater(optimizer)

        self.optimizer_initialized = True
        self._fused = None  # rebuilt lazily against the new optimizer
        if self._preload_opt_states is not None:
            self.load_optimizer_states(self._preload_opt_states)
            self._preload_opt_states = None

    # -- computation -------------------------------------------------------
    def _feed_batch(self, data_batch):
        feeds = {}
        data = data_batch.data
        for name, arr in zip(self._data_names, data):
            feeds[name] = arr
        if self._label_names and data_batch.label:
            for name, arr in zip(self._label_names, data_batch.label):
                feeds[name] = arr
        return feeds

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        if is_train is None:
            is_train = self.for_training
        feeds = self._feed_batch(data_batch)
        self._exec.forward(is_train=is_train, **feeds)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._exec.backward(out_grads=out_grads)

    def forward_backward(self, data_batch):
        """One fused jitted program for fwd+bwd (the per-batch hot path)."""
        assert self.binded and self.params_initialized
        feeds = self._feed_batch(data_batch)
        self._exec.forward_backward(**feeds)

    def set_precision(self, policy):
        """Install a :class:`mxnet_tpu.precision.PrecisionPolicy` (or
        None to clear).  The policy's fingerprint keys the fused-step
        program — changing it rebuilds instead of replaying a stale
        executable — and its loss scaler (if any) threads through the
        step's dynamic ``rescale_grad`` and consumes the divergence-
        guard verdict (skip accounting unchanged)."""
        self._precision = policy
        self._fused = None

    # -- fused fit step ----------------------------------------------------
    def _fused_eligible(self):
        """Can this configuration run fwd+bwd+update as ONE donated XLA
        program?  kvstore aggregation, grad_req='add' accumulation,
        inputs_need_grad, installed monitors, staged (multi-ctx-group)
        binds, and non-fusable optimizers all keep the split path."""
        if self._kvstore is not None or self._update_on_kvstore:
            return False
        if self._optimizer is None or self._optimizer.fused_kind() is None:
            return False
        if self._exec is None or self._exec._staged:
            return False
        if self._exec._monitor_callback is not None:
            return False
        if self.inputs_need_grad:
            return False
        for name in self._param_names:
            if self._exec._grad_req.get(name, "null") not in ("write",
                                                              "null"):
                return False
        return True

    def _fused_update_names(self):
        return [n for n in self._param_names
                if self._exec._grad_req.get(n) == "write"]

    def _fused_setup(self):
        """(Re)build the fused step program + optimizer state.  The cache
        key covers everything baked statically into the program
        (optimizer identity/kind and the per-param mult aux tree);
        lr / wd / rescale_grad / t stay dynamic so schedulers never force
        a rebuild."""
        from ..ops.optimizer_ops import zero_stage
        opt = self._optimizer
        kind = opt.fused_kind()
        update_names = self._fused_update_names()
        idx2name = {i: n for i, n in enumerate(self._param_names)
                    if n in set(update_names)}
        mults = opt.fused_mults(idx2name)
        # ZeRO-1 (MXTPU_ZERO=1, SCALING.md): optimizer state sharded 1/N
        # over the dp mesh axis.  The env value is part of the cache key
        # — toggling it across a re-setup must rebuild the program AND
        # re-place the state — but the sharding resolution itself runs
        # only on rebuild (this method is on the per-step path)
        # the SAME gate zero_shardings applies (mesh with a >1 dp axis),
        # so the key flag always equals the resolved (zero is not None)
        # and the state-carry fast path stays live on dp-less meshes
        mesh = self._exec._mesh
        want_zero = zero_stage() >= 1 and mesh is not None and \
            self._exec._dp_axis in mesh.shape and \
            mesh.shape[self._exec._dp_axis] > 1
        from ..precision import policy_fingerprint
        precision_fp = policy_fingerprint(self._precision)
        key = (id(opt), kind, tuple(update_names),
               tuple(sorted(mults.items())),
               tuple(sorted(opt.fused_hyper().items())),
               want_zero, precision_fp)
        if self._fused is not None and self._fused["key"] == key:
            return self._fused
        zero = self._exec.zero_shardings(update_names) \
            if want_zero else None
        init_state, apply_fn = opt.make_fused_apply(idx2name,
                                                    zero_shardings=zero)
        params = {n: self._exec.arg_dict[n] for n in update_names}
        if self._fused is not None and self._fused["kind"] == kind and \
                self._fused["key"][-2] == (zero is not None) and \
                set(self._fused["state"]) == set(update_names):
            state = self._fused["state"]  # mults changed; state carries
        else:
            # park accumulated momentum/Adam moments in the Updater
            # FIRST (same discipline as Trainer._fused_step): a rebuild
            # that can't carry state directly (kind change, MXTPU_ZERO
            # toggled between steps) re-seeds from the Updater, and
            # without this flush the re-seed would silently rewind to
            # whatever the Updater last saw
            self._fused_flush_to_updater()
            state = self._fused_state_from_updater(kind, init_state, params,
                                                   zero_shardings=zero)
        # everything baked statically into the traced program feeds the
        # AOT warm-start cache key (aot_cache.cache_key adds the backend
        # fingerprint and the full input tree shapes/dtypes itself).
        # The GRAPH must be in the key too: two networks with identical
        # param names/shapes but different ops (relu vs tanh, a changed
        # loss) would otherwise collide and a restart would silently
        # train the wrong program
        import hashlib as _hashlib
        graph = _hashlib.sha256(
            self._symbol.tojson().encode("utf-8")).hexdigest()
        cache_extra = repr((graph, type(opt).__name__, kind,
                            tuple(update_names),
                            tuple(sorted(mults.items())),
                            tuple(sorted(opt.fused_hyper().items())),
                            precision_fp))
        self._fused = {
            "key": key, "kind": kind, "update_names": update_names,
            "state": state, "zero": zero,
            "step": self._exec.make_fit_step(update_names, apply_fn,
                                             opt_state=state,
                                             cache_extra=cache_extra,
                                             zero_shardings=zero),
        }
        return self._fused

    def _fused_state_from_updater(self, kind, init_state, params,
                                  zero_shardings=None):
        """Seed fused optimizer state, adopting any state the Updater
        already holds (e.g. from load_optimizer_states).  Under ZeRO-1
        every leaf — freshly-initialized AND Updater-loaded (checkpoint
        states are saved gathered) — is placed onto its 1/N dp sharding:
        this is the reshard-on-load half of the elastic contract (a
        checkpoint written at world N loads at world M because the state
        payload is always full-size on disk)."""
        # _raw commits params to their mesh placement first, so
        # zeros_like state inherits it (mixed committed devices would
        # fail the jitted fused step)
        raw = self._exec._raw(params)
        state = init_state(raw)
        if self._updater is not None and self._updater.states:
            from ..optimizer import fused_state_from_updater
            for i, name in enumerate(self._param_names):
                if name in state and i in self._updater.states:
                    state[name] = fused_state_from_updater(
                        kind, self._updater.states[i], params[name])
        if self._exec._mesh is not None:
            # align every state leaf (incl. Updater-loaded ones) with its
            # param's sharding — or its ZeRO-1 shard placement.  Fresh
            # buffers (not device_put): this tree is DONATED on the next
            # fit_step while the Updater keeps referencing the loaded
            # arrays (sharding.fresh_device_put docs — the resume-crash
            # root cause)
            import jax
            from ..parallel.sharding import fresh_device_put
            placed = {}
            for name, st in state.items():
                target = (zero_shardings or {}).get(name,
                                                    raw[name].sharding)
                placed[name] = jax.tree_util.tree_map(
                    lambda s, _t=target: fresh_device_put(s, _t), st)
            state = placed
        return state

    def _fused_flush_to_updater(self):
        """Mirror fused optimizer state back into the Updater's per-index
        dict so save_optimizer_states round-trips across paths."""
        if self._fused is None or self._updater is None:
            return
        from ..optimizer import fused_state_to_updater
        kind = self._fused["kind"]
        for i, name in enumerate(self._param_names):
            if name in self._fused["state"]:
                self._updater.states[i] = fused_state_to_updater(
                    kind, self._fused["state"][name])

    def fit_step(self, data_batch):
        """One donated XLA program per batch: fwd + bwd + optimizer.

        The BaseModule.fit hot loop calls this instead of the
        forward_backward()/update() pair; ineligible configurations fall
        back to exactly that pair.  Steady state: ONE dispatch, zero
        compiles (profiler.step_stats proves it)."""
        assert self.binded and self.params_initialized and \
            self.optimizer_initialized
        from ..checkpoint import check_async_error
        # a background checkpoint write that failed must stop the run at
        # the NEXT step, not rot silently (one global None-check — no
        # dispatches, steptrace's 1.0/step contract holds)
        check_async_error()
        if not self._fused_eligible():
            return super().fit_step(data_batch)
        from .. import fault as _fault
        from .. import profiler as _profiler
        from .. import random as _random
        from .. import telemetry as _telemetry
        from .. import watchdog as _watchdog
        from ..ndarray.ndarray import NDArray
        from ..ops.optimizer_ops import handle_guard_verdict

        # hang-defense probe: a wedged step stops renewing the lease
        # below; the watchdog (armed when MXTPU_STALL_TIMEOUT is set)
        # diagnoses and exits 75 — retryable by the launcher
        _fault.stall_if("worker.stall")
        fused = self._fused_setup()
        exe = self._exec
        feeds = self._feed_batch(data_batch)
        for k, v in feeds.items():
            exe.arg_dict[k]._set_data(
                v._data if isinstance(v, nd.NDArray) else
                nd.array(v)._data)

        update_names = fused["update_names"]
        in_update = set(update_names)
        param_vals = exe._raw({n: exe.arg_dict[n] for n in update_names})
        other_vals = exe._raw({n: a for n, a in exe.arg_dict.items()
                               if n not in in_update})
        aux_vals = exe._raw_aux()

        opt = self._optimizer
        first_idx = None
        update_idxs = []
        pre_num_update = opt.num_update
        for i, name in enumerate(self._param_names):
            if name in in_update:
                opt._update_count(i)
                update_idxs.append(i)
                if first_idx is None:
                    first_idx = i
        t = float(opt._index_update_count[first_idx]) \
            if first_idx is not None else 1.0
        lr = opt.fused_base_lr()
        wd = float(opt.wd)
        rescale = float(opt.rescale_grad)
        scaler = getattr(self._precision, "loss_scaler", None)
        if scaler is not None:
            # loss scaling (precision.py): the graph's loss head is
            # pre-scaled by scaler.scale; undo it on the grads through
            # the DYNAMIC rescale scalar — scale moves never recompile
            rescale *= scaler.unscale
        poison = float("nan") if _fault.trigger("grad.nan") else 0.0

        rng = _random.next_key()
        t0 = time.perf_counter_ns()
        # straggler stand-in: a bounded delay INSIDE the timed dispatch
        # window, so the injected slowness shows exactly where a slow
        # host's would — in this rank's fit_step.dispatch percentiles
        # (job_report.py's straggler blame keys off them)
        _fault.delay_if("step.slow")
        outs, new_params, new_state, new_aux, ok = fused["step"](
            param_vals, fused["state"], other_vals, aux_vals, rng,
            lr, wd, rescale, t, poison)
        t1 = time.perf_counter_ns()
        fused["state"] = new_state
        # donated inputs are dead now — re-point every wrapper at the
        # step's outputs before anything else can touch them
        for name, v in new_params.items():
            exe.arg_dict[name]._set_data(v)
        for name, v in new_aux.items():
            exe.aux_dict[name]._set_data(v)
        exe.outputs = [NDArray(o, exe._ctx) for o in outs]
        self._params_dirty = True
        _profiler.note_step()
        # divergence guard verdict: reading the scalar costs one small
        # host readback that the fit loop's metric update would force
        # anyway (PERF.md "Divergence guard"); a skipped step rewinds the
        # optimizer clocks so it is as if the batch never arrived.  The
        # readback is also the step's device-sync point, so [t1, t2] is
        # telemetry's "fit_step.sync" phase (~the device compute time).
        ok_host = bool(ok)
        t2 = time.perf_counter_ns()
        # loss for the flight recorder, free of extra syncs: only a
        # scalar head (loss-output nets) is worth a host read, and only
        # while recording actually consumes it
        loss = float(outs[0]) if outs and not outs[0].shape \
            and _telemetry.enabled() else None
        _telemetry.note_train_step(t0, t1, t2, not ok_host, loss)
        # progress lease: one monotonic store per completed step (no
        # dispatches — steptrace's 1.0 dispatch/step still holds)
        _watchdog.renew("fit_step", phase="train")
        self._consec_guard_skips = handle_guard_verdict(
            ok_host, opt, update_idxs, self._consec_guard_skips,
            pre_num_update)
        if scaler is not None:
            # the scaler consumes the SAME verdict the guard already
            # acted on: backoff on a skipped step, growth on a clean
            # streak — skipped_steps accounting is untouched
            scaler.update(ok_host)

    def update(self):
        """Apply optimizer using accumulated grads (reference module.py:615)."""
        assert self.binded and self.params_initialized and \
            self.optimizer_initialized
        if self._fused is not None:
            # momentum/mean/var accumulated by fused steps must seed the
            # per-param Updater, and vice versa on the next fit_step
            self._fused_flush_to_updater()
            self._fused = None
        self._params_dirty = True
        param_arrays = [[self._exec.arg_dict[n]] for n in self._param_names]
        grad_arrays = [[self._exec.grad_dict.get(n)]
                       for n in self._param_names]
        if self._update_on_kvstore:
            _update_params_on_kvstore(param_arrays, grad_arrays,
                                      self._kvstore, self._param_names)
        else:
            _update_params(param_arrays, grad_arrays,
                           updater=self._updater,
                           num_device=len(self._context),
                           kvstore=self._kvstore,
                           param_names=self._param_names)

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._exec.outputs

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized and \
            self.inputs_need_grad
        return [self._exec.grad_dict[n] for n in self._data_names]

    def update_metric(self, eval_metric, labels):
        eval_metric.update(labels, self._exec.outputs)

    def install_monitor(self, mon):
        assert self.binded
        mon.install(self._exec)

    def get_states(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return [self._exec.arg_dict[n] for n in self._state_names]

    def set_states(self, states=None, value=None):
        assert self.binded and self.params_initialized
        if states is not None:
            for name, arr in zip(self._state_names, states):
                self._exec.arg_dict[name]._set_data(
                    arr._data if isinstance(arr, nd.NDArray) else arr)
        else:
            for name in self._state_names:
                self._exec.arg_dict[name][:] = value

    # -- optimizer state io -------------------------------------------------
    def _optimizer_states_bytes(self):
        """Current optimizer state as the payload save_optimizer_states
        persists (fused state flushed into the Updater first)."""
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            return self._kvstore._optimizer_states_bytes()
        self._fused_flush_to_updater()
        return self._updater.get_states()

    def save_optimizer_states(self, fname):
        """Atomic, checksummed write (checkpoint.write_state_file)."""
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname)
        else:
            from ..checkpoint import write_state_file
            self._fused_flush_to_updater()
            write_state_file(fname, self._updater.get_states())

    def load_optimizer_states(self, fname):
        """Validated read: a torn/corrupt state file raises MXNetError
        naming the path instead of a cryptic unpickling error."""
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
        else:
            from ..checkpoint import load_state_file
            load_state_file(fname, self._updater.set_states)
            self._fused = None  # re-seed fused state from the Updater
        self._consec_guard_skips = 0  # fresh state, fresh streak

    def reshape(self, data_shapes, label_shapes=None):
        """Re-bind for new shapes (XLA re-jits; params carry over)."""
        assert self.binded
        self._sync_params_from_devices() if self._params_dirty else None
        self.binded = False
        self._exec = None
        self.bind(data_shapes, label_shapes,
                  for_training=self.for_training,
                  inputs_need_grad=self.inputs_need_grad,
                  force_rebind=True)
