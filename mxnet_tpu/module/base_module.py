"""BaseModule: the training-loop contract.

Port of /root/reference/python/mxnet/module/base_module.py — same
intermediate-level API (bind → init_params → init_optimizer →
forward/backward/update/metric) and the same high-level ``fit``/``score``/
``predict`` loops (:376-487).  The hot path per batch is
``forward_backward`` which subclasses implement as ONE fused jitted
XLA program (the reference pushed per-node engine ops instead,
graph_executor.cc:1421).
"""
from __future__ import annotations

import logging
import time

import numpy as _np

from .. import fault as _fault
from .. import metric as metric_mod
from .. import ndarray as nd
from ..base import MXNetError
from ..model import BatchEndParam

__all__ = ["BaseModule"]


def _as_list(obj):
    if isinstance(obj, list):
        return obj
    return [obj]


def _check_input_names(symbol, names, typename, throw):
    """Verify declared names exist in the symbol (reference :33)."""
    args = symbol.list_arguments()
    for name in names:
        if name in args:
            continue
        candidates = [arg for arg in args if not arg.endswith("_weight")
                      and not arg.endswith("_bias")
                      and not arg.endswith("_gamma")
                      and not arg.endswith("_beta")]
        msg = "\033[91mYou created Module with Module(..., %s_names=%s) " \
              "but input with name '%s' is not found in symbol.list_" \
              "arguments(). Did you mean one of:\n\t%s\033[0m" % (
                  typename, str(names), name, "\n\t".join(candidates))
        if throw:
            raise ValueError(msg)
        logging.warning(msg)


class BaseModule:
    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.inputs_need_grad = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None
        self._total_exec_bytes = 0

    # -- high-level API ----------------------------------------------------
    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def fit_step(self, data_batch):
        """One full train step (forward + backward + optimizer update) —
        the per-batch hot path of ``fit``.  Subclasses fuse this into a
        single donated XLA program when the configuration allows
        (Module.fit_step); the default is the classic split pair."""
        self.forward_backward(data_batch)
        self.update()

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0):
        """Run prediction + metric over eval_data (reference :176)."""
        assert self.binded and self.params_initialized
        from .. import watchdog as _watchdog
        if reset:
            eval_data.reset()
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        eval_metric.reset()
        actual_num_batch = 0
        # scoped like fit: a standalone score() must not leave the
        # watchdog armed with a live lease after it returns (an eval-only
        # process would be killed during its post-scoring work)
        _armed_here = _watchdog.maybe_arm()
        try:
            for nbatch, eval_batch in enumerate(eval_data):
                if num_batch is not None and nbatch == num_batch:
                    break
                self.forward(eval_batch, is_train=False)
                # evaluation is progress too: without this a validation
                # pass longer than the stall timeout would expire the
                # training leases and kill a healthy job mid-eval
                _watchdog.renew("fit_step", phase="eval")
                self.update_metric(eval_metric, eval_batch.label)
                if batch_end_callback is not None:
                    params = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                           eval_metric=eval_metric,
                                           locals=locals())
                    for callback in _as_list(batch_end_callback):
                        callback(params)
                actual_num_batch += 1
            if score_end_callback:
                params = BatchEndParam(epoch=epoch,
                                       nbatch=actual_num_batch,
                                       eval_metric=eval_metric,
                                       locals=locals())
                for callback in _as_list(score_end_callback):
                    callback(params)
            return eval_metric.get_name_value()
        finally:
            if _armed_here:
                _watchdog.disarm()

    def iter_predict(self, eval_data, num_batch=None, reset=True):
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0:out.shape[0] - pad]
                       for out in self.get_outputs()]
            yield (outputs, nbatch, eval_batch)

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False):
        """Collect outputs over the iterator (reference :268)."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0:out.shape[0] - pad].copy()
                       for out in self.get_outputs()]
            output_list.append(outputs)
        if len(output_list) == 0:
            return output_list
        if merge_batches:
            num_outputs = len(output_list[0])
            for out in output_list:
                assert len(out) == num_outputs, \
                    "Cannot merge batches, as num of outputs is not the " \
                    "same in mini-batches. Maybe bucketing is used?"
            output_list2 = [
                nd.concatenate([out[i] for out in output_list])
                for i in range(num_outputs)]
            if num_outputs == 1 and not always_output_list:
                return output_list2[0]
            return output_list2
        return output_list

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None):
        """The training loop (reference base_module.py:376-487).

        ``train_data`` may be a DataIter OR an epoch-mode
        :class:`~mxnet_tpu.stream.loader.StreamLoader` (the streaming
        data plane, DATA.md): a bare loader is wrapped in
        :class:`~mxnet_tpu.stream.fit.StreamTrainIter` — shapes peeked
        from its first batch, ``reset()`` advancing ``set_epoch``, and
        the loader's exact-once CURSOR stamped onto this module at
        every epoch boundary so a checkpoint epoch callback
        (``callback.module_checkpoint``) pairs each checkpoint with
        the records consumed when it was cut."""
        assert num_epoch is not None, "please specify number of epochs"
        from .. import watchdog as _watchdog
        from ..initializer import Uniform
        from ..stream.fit import maybe_wrap as _maybe_wrap_stream
        train_data = _maybe_wrap_stream(train_data)
        if initializer is None:
            initializer = Uniform(0.01)
        # hang defense is scoped to the run: armed here (no-op unless
        # MXTPU_STALL_TIMEOUT is set), disarmed in the finally below so
        # post-training work can't trip over a stale training lease.
        # The try covers bind/init too: a raise there must not leak an
        # armed watchdog into a caller that handled the error.
        _armed_here = _watchdog.maybe_arm()
        try:
            self.bind(data_shapes=train_data.provide_data,
                      label_shapes=train_data.provide_label,
                      for_training=True, force_rebind=force_rebind)
            if monitor is not None:
                self.install_monitor(monitor)
            self.init_params(initializer=initializer,
                             arg_params=arg_params,
                             aux_params=aux_params,
                             allow_missing=allow_missing,
                             force_init=force_init)
            self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                                optimizer_params=optimizer_params)

            if validation_metric is None:
                validation_metric = eval_metric
            if not isinstance(eval_metric, metric_mod.EvalMetric):
                eval_metric = metric_mod.create(eval_metric)

            self._fit_epochs(train_data, eval_data, eval_metric,
                             validation_metric, epoch_end_callback,
                             batch_end_callback, eval_end_callback,
                             eval_batch_end_callback, monitor,
                             begin_epoch, num_epoch)
            # fit exit: every checkpoint enqueued by epoch callbacks must
            # be durably on disk before fit() returns success — and a
            # background write failure must fail the fit, not the exit
            # status of some later unrelated save
            from .. import checkpoint as _checkpoint
            _checkpoint.flush_async()
        finally:
            if _armed_here:
                _watchdog.disarm()

    def _fit_epochs(self, train_data, eval_data, eval_metric,
                    validation_metric, epoch_end_callback,
                    batch_end_callback, eval_end_callback,
                    eval_batch_end_callback, monitor, begin_epoch,
                    num_epoch):
        from .. import watchdog as _watchdog
        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            nbatch = 0
            data_iter = iter(train_data)
            end_of_batch = False
            next_data_batch = next(data_iter)
            while not end_of_batch:
                data_batch = next_data_batch
                if monitor is not None:
                    monitor.tic()
                # deterministic permanent-rank-death injection: a hard
                # os._exit(77) between steps (the elastic runbook's
                # "kill a rank mid-run", ROBUSTNESS.md §9)
                _fault.exit_if("worker.lost")
                self.fit_step(data_batch)
                # progress lease for the split fallback path too
                # (Module.fit_step renews on the fused path; renewal is
                # one monotonic store, so doubling up is free)
                _watchdog.renew("fit_step", phase="train")
                try:
                    next_data_batch = next(data_iter)
                    self.prepare(next_data_batch)
                except StopIteration:
                    end_of_batch = True
                self.update_metric(eval_metric, data_batch.label)
                if monitor is not None:
                    monitor.toc_print()
                if batch_end_callback is not None:
                    batch_end_params = BatchEndParam(
                        epoch=epoch, nbatch=nbatch, eval_metric=eval_metric,
                        locals=locals())
                    for callback in _as_list(batch_end_callback):
                        callback(batch_end_params)
                nbatch += 1

            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            toc = time.time()
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch, (toc - tic))

            arg_params_, aux_params_ = self.get_params()
            self.set_params(arg_params_, aux_params_)
            # streaming sugar: stamp the loader's exact-once cursor on
            # the module BEFORE the epoch-end callbacks run, so a
            # checkpoint callback saving now pairs this epoch with
            # exactly the records consumed when it was cut.  Always
            # assigned: a later fit() over a PLAIN iter on the same
            # module must clear the stamp, or its checkpoints would
            # carry a stale cursor from an unrelated stream run
            cursor_fn = getattr(train_data, "stream_cursor", None)
            self._stream_cursor = None if cursor_fn is None \
                else cursor_fn()
            if epoch_end_callback is not None:
                for callback in _as_list(epoch_end_callback):
                    callback(epoch, self.symbol, arg_params_, aux_params_)
            # surface any async checkpoint-writer failure at the epoch
            # boundary WITHOUT draining the queue — draining here would
            # serialize the write against the next epoch's compute and
            # forfeit the overlap the async pipeline exists for
            from .. import checkpoint as _checkpoint
            _checkpoint.check_async_error()

            if eval_data:
                res = self.score(eval_data, validation_metric,
                                 score_end_callback=eval_end_callback,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch,
                                     name, val)

            train_data.reset()

    # -- symbol/params introspection ---------------------------------------
    @property
    def symbol(self):
        return self._symbol

    @property
    def data_names(self):
        raise NotImplementedError()

    @property
    def output_names(self):
        raise NotImplementedError()

    @property
    def data_shapes(self):
        raise NotImplementedError()

    @property
    def label_shapes(self):
        raise NotImplementedError()

    @property
    def output_shapes(self):
        raise NotImplementedError()

    def get_params(self):
        raise NotImplementedError()

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False,
                    allow_extra=False):
        raise NotImplementedError()

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    def save_params(self, fname):
        arg_params, aux_params = self.get_params()
        save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
        save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
        nd.save(fname, save_dict)

    def load_params(self, fname):
        save_dict = nd.load(fname)
        arg_params = {}
        aux_params = {}
        for k, value in save_dict.items():
            arg_type, _, name = k.partition(":")
            if arg_type == "arg":
                arg_params[name] = value
            elif arg_type == "aux":
                aux_params[name] = value
            else:
                raise ValueError("Invalid param file " + fname)
        self.set_params(arg_params, aux_params)

    def get_states(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return []

    def set_states(self, states=None, value=None):
        assert self.binded and self.params_initialized

    def install_monitor(self, mon):
        raise NotImplementedError()

    def prepare(self, data_batch):
        """Hook before processing a batch (default no-op)."""

    # -- computation -------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        raise NotImplementedError()

    def backward(self, out_grads=None):
        raise NotImplementedError()

    def outputs(self):
        raise NotImplementedError()

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError()

    def get_input_grads(self, merge_multi_context=True):
        raise NotImplementedError()

    def update(self):
        raise NotImplementedError()

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError()

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        raise NotImplementedError()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        raise NotImplementedError()
