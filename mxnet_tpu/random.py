"""Global PRNG state.

The reference seeds per-device random resources via ``mx.random.seed``
(/root/reference/python/mxnet/random.py, src/resource.cc).  Here a single
functional JAX key chain is the source of randomness; every random op pulls
``next_key()``, so runs are reproducible after ``seed(n)`` regardless of
device layout.
"""
from __future__ import annotations

import threading

import jax

__all__ = ["seed", "next_key"]

_LOCK = threading.Lock()
# lazy: creating a key touches the device backend, which must not happen at
# import time (it would initialize/occupy the TPU for every importer)
_KEY = None


def seed(seed_state):
    """Seed the global generator (reference: mx.random.seed)."""
    global _KEY
    with _LOCK:
        _KEY = jax.random.PRNGKey(int(seed_state))


def next_key():
    """Split one key off the global chain."""
    global _KEY
    with _LOCK:
        if _KEY is None:
            _KEY = jax.random.PRNGKey(0)
        _KEY, sub = jax.random.split(_KEY)
    return sub


def uniform(low=0.0, high=1.0, shape=(), dtype="float32", ctx=None, out=None):
    from .ndarray.ndarray import imperative_invoke
    return imperative_invoke("_random_uniform", (), {
        "low": low, "high": high, "shape": shape, "dtype": dtype}, out=out)


def normal(loc=0.0, scale=1.0, shape=(), dtype="float32", ctx=None, out=None):
    from .ndarray.ndarray import imperative_invoke
    return imperative_invoke("_random_normal", (), {
        "loc": loc, "scale": scale, "shape": shape, "dtype": dtype}, out=out)


def randint(low, high, shape=(), dtype="int32", ctx=None, out=None):
    import jax.numpy as jnp
    from .ndarray.ndarray import NDArray
    key = next_key()
    data = jax.random.randint(key, tuple(shape) if shape else (),
                              low, high).astype(jnp.dtype(dtype))
    return NDArray(data)
