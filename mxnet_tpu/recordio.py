"""RecordIO: the packed-record dataset container.

Port of /root/reference/python/mxnet/recordio.py (456 L) — same on-disk
format as dmlc recordio so `.rec` files interoperate: each record is
``uint32 magic (0xced7230a) | uint32 lrec | payload | pad to 4 bytes``
where lrec's top 3 bits are the continuation flag and the low 29 bits the
length (flag 0 = whole record — the only kind this writer emits).
``IRHeader`` carries ``(flag, label, id, id2)`` ahead of image payloads,
with flag>1 meaning a float-array label of that many entries.

The reference's C++ reader ran OpenMP decode threads
(src/io/iter_image_recordio_2.cc); the native decode path here lives in
native/ (C++ via ctypes) with a PIL fallback in image.py.
"""
from __future__ import annotations

import ctypes
import os
import struct
from collections import namedtuple

import numpy as np

from .base import MXNetError

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_MAGIC = 0xced7230a
_LEN_MASK = (1 << 29) - 1


class MXRecordIO:
    """Sequential .rec reader/writer (reference recordio.py:MXRecordIO).

    Reads validate the frame on every record: a bad magic or a record
    that ends mid-header/mid-payload — the torn tail a crashed writer
    leaves — raises :class:`MXNetError` naming the path and byte offset
    instead of returning garbage (the stream layer's skip-and-count
    policy sits on top of exactly this error,
    mxnet_tpu/stream/loader.py).  Only a clean EOF at a record boundary
    returns ``None``.

    Teardown is defensive: ``close`` is idempotent and safe on a
    half-constructed instance (``open`` raised) and at interpreter
    shutdown.  Readers pickle (decode worker processes ship them; the
    reopened copy seeks back to the pickled position); pickling an OPEN
    WRITER refuses loudly — ``__setstate__``'s reopen would truncate
    the file it is mid-writing.
    """

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.handle = None
        self.is_open = False
        self.open()

    def open(self):
        if self.flag == "w":
            self.handle = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.handle = open(self.uri, "rb")
            self.writable = False
        else:
            raise ValueError("Invalid flag %s" % self.flag)
        self.is_open = True

    def close(self):
        """Idempotent; never assumes construction finished (``__del__``
        runs even when ``open()`` raised, and interpreter shutdown may
        have torn half the module away)."""
        if getattr(self, "is_open", False):
            self.is_open = False
            handle = getattr(self, "handle", None)
            if handle is not None:
                handle.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __getstate__(self):
        if getattr(self, "writable", False):
            # open OR closed: __setstate__ reopens with the original
            # flag, and mode "w" TRUNCATES — unpickling a closed
            # writer would zero the completed shard it just wrote
            raise MXNetError(
                "refusing to pickle the WRITER MXRecordIO(%s): "
                "__setstate__ reopens with mode 'w', truncating the "
                "file — ship the path and reopen for read instead"
                % self.uri)
        d = dict(self.__dict__)
        d["handle"] = None
        d["_pos"] = self.handle.tell() if self.is_open else 0
        d["is_open"] = False
        return d

    def __setstate__(self, d):
        if d.get("writable"):
            raise MXNetError(
                "refusing to unpickle a WRITER MXRecordIO(%s): "
                "reopening with mode 'w' would truncate the file"
                % d.get("uri"))
        pos = d.pop("_pos", 0)
        self.__dict__.update(d)
        self.open()
        self.handle.seek(pos)

    def reset(self):
        self.close()
        self.open()

    def write(self, buf):
        assert self.writable
        lrec = len(buf) & _LEN_MASK
        self.handle.write(struct.pack("<II", _MAGIC, lrec))
        self.handle.write(buf)
        pad = (-len(buf)) % 4
        if pad:
            self.handle.write(b"\x00" * pad)

    def read(self):
        assert not self.writable
        offset = self.handle.tell()
        head = self.handle.read(8)
        if not head:
            return None  # clean EOF at a record boundary
        if len(head) < 8:
            raise MXNetError(
                "truncated record header in %s at offset %d (%d of 8 "
                "bytes) — torn tail from a crashed writer?"
                % (self.uri, offset, len(head)))
        magic, lrec = struct.unpack("<II", head)
        if magic != _MAGIC:
            raise MXNetError(
                "invalid record magic 0x%08x in %s at offset %d "
                "(corrupt file or mid-record seek)"
                % (magic, self.uri, offset))
        length = lrec & _LEN_MASK
        buf = self.handle.read(length)
        if len(buf) < length:
            raise MXNetError(
                "truncated record payload in %s at offset %d (%d of %d "
                "bytes) — torn tail from a crashed writer?"
                % (self.uri, offset, len(buf), length))
        pad = (-length) % 4
        if pad:
            # a missing pad means the writer died AFTER the payload:
            # the record itself is whole, so return it — the next read
            # hits the truncated frame and raises there
            self.handle.read(pad)
        return buf

    def tell(self):
        return self.handle.tell()


class MXIndexedRecordIO(MXRecordIO):
    """Indexed .rec with a .idx sidecar (reference MXIndexedRecordIO)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        self.fidx = None
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if not self.writable and os.path.isfile(self.idx_path):
            with open(self.idx_path) as fin:
                for line in fin:
                    parts = line.strip().split("\t")
                    if len(parts) < 2:
                        continue
                    key = self.key_type(parts[0])
                    self.idx[key] = int(parts[1])
                    self.keys.append(key)
        self.fidx = open(self.idx_path, "w") if self.writable else None

    def close(self):
        # getattr-guarded like the base close: __del__ may run on a
        # half-constructed instance, double-close must be a no-op
        fidx = getattr(self, "fidx", None)
        if getattr(self, "is_open", False) and fidx is not None:
            fidx.close()
            self.fidx = None
        super().close()

    def __getstate__(self):
        # the .idx sidecar handle never pickles: readers reload the idx
        # in __setstate__→open(); writers already refuse in the base
        d = super().__getstate__()
        d["fidx"] = None
        return d

    def seek(self, idx):
        assert not self.writable
        self.handle.seek(self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        assert self.writable
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.fidx.write("%s\t%d\n" % (str(key), pos))
        self.idx[key] = pos
        self.keys.append(key)


IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Pack (IRHeader, bytes) into a record payload (reference :pack)."""
    header = IRHeader(*header)
    if isinstance(header.label, (int, float)):
        hdr = struct.pack(_IR_FORMAT, 0, float(header.label), header.id,
                          header.id2)
    else:
        label = np.asarray(header.label, dtype=np.float32)
        hdr = struct.pack(_IR_FORMAT, label.size, 0.0, header.id,
                          header.id2) + label.tobytes()
    return hdr + s


def unpack(s):
    """Unpack a record payload into (IRHeader, bytes)."""
    flag, label, id_, id2 = struct.unpack(_IR_FORMAT, s[:_IR_SIZE])
    s = s[_IR_SIZE:]
    if flag > 0:
        arr = np.frombuffer(s[:flag * 4], dtype=np.float32)
        s = s[flag * 4:]
        header = IRHeader(flag, arr, id_, id2)
    else:
        header = IRHeader(flag, label, id_, id2)
    return header, s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Pack an image array (reference :pack_img). Requires PIL."""
    import io as _io
    from PIL import Image
    arr = np.asarray(img)
    if arr.ndim == 3 and arr.shape[2] == 3:
        pil = Image.fromarray(arr[:, :, ::-1])  # BGR→RGB like cv2 write
    else:
        pil = Image.fromarray(arr)
    buf = _io.BytesIO()
    fmt = "JPEG" if img_fmt.lower() in (".jpg", ".jpeg") else "PNG"
    pil.save(buf, format=fmt, quality=quality)
    return pack(header, buf.getvalue())


def unpack_img(s, iscolor=-1):
    """Unpack a packed image record into (IRHeader, ndarray BGR)."""
    import io as _io
    from PIL import Image
    header, img_bytes = unpack(s)
    pil = Image.open(_io.BytesIO(img_bytes))
    arr = np.asarray(pil)
    if arr.ndim == 3 and arr.shape[2] == 3:
        arr = arr[:, :, ::-1]  # RGB→BGR, matching the reference's cv2
    return header, arr
