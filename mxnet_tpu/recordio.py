"""RecordIO: the packed-record dataset container.

Port of /root/reference/python/mxnet/recordio.py (456 L) — same on-disk
format as dmlc recordio so `.rec` files interoperate: each record is
``uint32 magic (0xced7230a) | uint32 lrec | payload | pad to 4 bytes``
where lrec's top 3 bits are the continuation flag and the low 29 bits the
length (flag 0 = whole record — the only kind this writer emits).
``IRHeader`` carries ``(flag, label, id, id2)`` ahead of image payloads,
with flag>1 meaning a float-array label of that many entries.

The reference's C++ reader ran OpenMP decode threads
(src/io/iter_image_recordio_2.cc); the native decode path here lives in
native/ (C++ via ctypes) with a PIL fallback in image.py.
"""
from __future__ import annotations

import ctypes
import os
import struct
from collections import namedtuple

import numpy as np

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_MAGIC = 0xced7230a
_LEN_MASK = (1 << 29) - 1


class MXRecordIO:
    """Sequential .rec reader/writer (reference recordio.py:MXRecordIO)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.handle = None
        self.open()

    def open(self):
        if self.flag == "w":
            self.handle = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.handle = open(self.uri, "rb")
            self.writable = False
        else:
            raise ValueError("Invalid flag %s" % self.flag)
        self.is_open = True

    def close(self):
        if self.is_open:
            self.handle.close()
            self.is_open = False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __getstate__(self):
        d = dict(self.__dict__)
        d["handle"] = None
        d["_pos"] = self.handle.tell() if self.is_open else 0
        d["is_open"] = False
        return d

    def __setstate__(self, d):
        pos = d.pop("_pos", 0)
        self.__dict__.update(d)
        self.open()
        self.handle.seek(pos)

    def reset(self):
        self.close()
        self.open()

    def write(self, buf):
        assert self.writable
        lrec = len(buf) & _LEN_MASK
        self.handle.write(struct.pack("<II", _MAGIC, lrec))
        self.handle.write(buf)
        pad = (-len(buf)) % 4
        if pad:
            self.handle.write(b"\x00" * pad)

    def read(self):
        assert not self.writable
        head = self.handle.read(8)
        if len(head) < 8:
            return None
        magic, lrec = struct.unpack("<II", head)
        if magic != _MAGIC:
            raise IOError("Invalid magic number in record file %s"
                          % self.uri)
        length = lrec & _LEN_MASK
        buf = self.handle.read(length)
        pad = (-length) % 4
        if pad:
            self.handle.read(pad)
        return buf

    def tell(self):
        return self.handle.tell()


class MXIndexedRecordIO(MXRecordIO):
    """Indexed .rec with a .idx sidecar (reference MXIndexedRecordIO)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        self.fidx = None
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if not self.writable and os.path.isfile(self.idx_path):
            with open(self.idx_path) as fin:
                for line in fin:
                    parts = line.strip().split("\t")
                    if len(parts) < 2:
                        continue
                    key = self.key_type(parts[0])
                    self.idx[key] = int(parts[1])
                    self.keys.append(key)
        self.fidx = open(self.idx_path, "w") if self.writable else None

    def close(self):
        if self.is_open and self.fidx is not None:
            self.fidx.close()
            self.fidx = None
        super().close()

    def seek(self, idx):
        assert not self.writable
        self.handle.seek(self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        assert self.writable
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.fidx.write("%s\t%d\n" % (str(key), pos))
        self.idx[key] = pos
        self.keys.append(key)


IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Pack (IRHeader, bytes) into a record payload (reference :pack)."""
    header = IRHeader(*header)
    if isinstance(header.label, (int, float)):
        hdr = struct.pack(_IR_FORMAT, 0, float(header.label), header.id,
                          header.id2)
    else:
        label = np.asarray(header.label, dtype=np.float32)
        hdr = struct.pack(_IR_FORMAT, label.size, 0.0, header.id,
                          header.id2) + label.tobytes()
    return hdr + s


def unpack(s):
    """Unpack a record payload into (IRHeader, bytes)."""
    flag, label, id_, id2 = struct.unpack(_IR_FORMAT, s[:_IR_SIZE])
    s = s[_IR_SIZE:]
    if flag > 0:
        arr = np.frombuffer(s[:flag * 4], dtype=np.float32)
        s = s[flag * 4:]
        header = IRHeader(flag, arr, id_, id2)
    else:
        header = IRHeader(flag, label, id_, id2)
    return header, s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Pack an image array (reference :pack_img). Requires PIL."""
    import io as _io
    from PIL import Image
    arr = np.asarray(img)
    if arr.ndim == 3 and arr.shape[2] == 3:
        pil = Image.fromarray(arr[:, :, ::-1])  # BGR→RGB like cv2 write
    else:
        pil = Image.fromarray(arr)
    buf = _io.BytesIO()
    fmt = "JPEG" if img_fmt.lower() in (".jpg", ".jpeg") else "PNG"
    pil.save(buf, format=fmt, quality=quality)
    return pack(header, buf.getvalue())


def unpack_img(s, iscolor=-1):
    """Unpack a packed image record into (IRHeader, ndarray BGR)."""
    import io as _io
    from PIL import Image
    header, img_bytes = unpack(s)
    pil = Image.open(_io.BytesIO(img_bytes))
    arr = np.asarray(pil)
    if arr.ndim == 3 and arr.shape[2] == 3:
        arr = arr[:, :, ::-1]  # RGB→BGR, matching the reference's cv2
    return header, arr
