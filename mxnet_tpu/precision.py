"""First-class precision policy (ISSUE 20).

One object answers every "what dtype does X run in?" question instead
of per-subsystem flags: a :class:`PrecisionPolicy` resolves per-layer
**param / compute / output** dtypes, optionally carries a serving
``kv_dtype`` (so the quantized KV pages of serving/kv_cache.py are one
instance of the general policy, not a one-off flag), and owns the
:class:`LossScaler` hook the fused training steps consult.

Resolution laws (pinned by tests/test_precision.py):

1. ``compute`` defaults to ``param``; ``output`` defaults to
   ``compute`` — an unqualified policy never mixes dtypes.
2. Per-layer ``overrides`` are fnmatch patterns checked in declaration
   order; the LAST matching pattern wins **field-wise** (a later
   ``{"compute": ...}`` override keeps an earlier match's ``param``),
   and unset fields fall through to the policy-wide defaults, then
   law 1.
3. Dtype names are canonicalised (``fp32``/``float32``/``np.float32``
   are one name) so two spellings of the same policy hash identically.

The policy's :meth:`~PrecisionPolicy.fingerprint` is folded into the
fused-step AOT cache keys (module.Module._fused_setup and
gluon.Trainer._fused_step): a policy change can never replay a stale
executable, while the loss scaler's *dynamic* scale — a runtime scalar,
not program structure — stays out of the hash so scale updates never
recompile.

Loss scaling rides the PR-2 divergence guard instead of duplicating
it: the fused step already computes an all-finite verdict and
``handle_guard_verdict`` already rewinds the optimizer clock on a
skipped step.  :meth:`LossScaler.update` takes that SAME verdict —
backoff on a skipped step, growth after a clean streak — so the
``skipped_steps`` accounting is byte-for-byte what it was without a
scaler.  The scale itself threads through the fused step's *dynamic*
``rescale_grad`` scalar (grads are unscaled by ``1/scale`` inside the
one donated program); callers scale the loss head with
:meth:`LossScaler.scale_loss` when building the graph.
"""
from __future__ import annotations

import fnmatch
import hashlib
from collections import namedtuple

__all__ = ["PrecisionPolicy", "LossScaler", "Resolved",
           "policy_fingerprint"]

#: canonical dtype names the policy speaks, and every accepted spelling
_CANON = {
    "fp32": "fp32", "float32": "fp32", "f32": "fp32",
    "bf16": "bf16", "bfloat16": "bf16",
    "fp16": "fp16", "float16": "fp16", "f16": "fp16",
}

_JAX_NAMES = {"fp32": "float32", "bf16": "bfloat16", "fp16": "float16"}

Resolved = namedtuple("Resolved", ["param", "compute", "output"])


def _canon_dtype(dt, field):
    """Canonical short name for a dtype spelling (law 3)."""
    if dt is None:
        return None
    name = getattr(dt, "__name__", None) or getattr(dt, "name", None) \
        or str(dt)
    key = name.strip().lower()
    if key not in _CANON:
        raise ValueError(
            "unsupported %s dtype %r (want one of %s)"
            % (field, dt, "/".join(sorted(set(_CANON.values())))))
    return _CANON[key]


def jax_dtype(name):
    """jnp dtype object for a canonical policy dtype name."""
    import jax.numpy as jnp
    return jnp.dtype(_JAX_NAMES[_canon_dtype(name, "jax")])


class LossScaler:
    """Dynamic (or static) loss scaling, driven by the divergence-guard
    verdict.  ``update(step_ok)`` is called once per fused step with
    the guard's all-finite verdict: a skipped step backs the scale off,
    ``growth_interval`` consecutive good steps grow it.  The scaler
    never decides whether a step is skipped — that stays the guard's
    job, so skip accounting is unchanged by its presence."""

    def __init__(self, init_scale=2.0 ** 15, growth_factor=2.0,
                 backoff_factor=0.5, growth_interval=200, dynamic=True,
                 max_scale=2.0 ** 24):
        if init_scale <= 0:
            raise ValueError("init_scale must be positive")
        if not (0.0 < backoff_factor < 1.0):
            raise ValueError("backoff_factor must be in (0, 1)")
        if growth_factor <= 1.0:
            raise ValueError("growth_factor must be > 1")
        self.scale = float(init_scale)
        self.growth_factor = float(growth_factor)
        self.backoff_factor = float(backoff_factor)
        self.growth_interval = int(growth_interval)
        self.dynamic = bool(dynamic)
        self.max_scale = float(max_scale)
        self.good_steps = 0
        self.overflows = 0

    @property
    def unscale(self):
        """Multiplier that removes the loss scale from gradients —
        folded into the fused step's dynamic ``rescale_grad`` scalar
        (no recompile when the scale moves)."""
        return 1.0 / self.scale

    def scale_loss(self, loss):
        """Scale a loss value/symbol/array by the current scale."""
        return loss * self.scale

    def update(self, step_ok):
        """Consume one divergence-guard verdict.  Returns the (possibly
        updated) scale."""
        if not self.dynamic:
            return self.scale
        if step_ok:
            self.good_steps += 1
            if self.good_steps >= self.growth_interval:
                self.scale = min(self.scale * self.growth_factor,
                                 self.max_scale)
                self.good_steps = 0
        else:
            self.overflows += 1
            self.good_steps = 0
            self.scale = max(self.scale * self.backoff_factor, 1.0)
        return self.scale

    def config_key(self):
        """Static configuration only — the dynamic scale stays OUT so
        scale updates never re-key a compiled program."""
        return ("loss_scaler", self.dynamic, self.growth_factor,
                self.backoff_factor, self.growth_interval)


class PrecisionPolicy:
    """Per-layer param/compute/output dtype resolution + optional
    serving ``kv_dtype`` + optional :class:`LossScaler`.

    ``overrides``: ``{fnmatch_pattern: {"param"/"compute"/"output":
    dtype}}`` applied to layer names in declaration order, last match
    winning field-wise (law 2)."""

    def __init__(self, param_dtype="fp32", compute_dtype=None,
                 output_dtype=None, overrides=None, kv_dtype=None,
                 loss_scaler=None):
        self.param_dtype = _canon_dtype(param_dtype, "param")
        self.compute_dtype = _canon_dtype(compute_dtype, "compute")
        self.output_dtype = _canon_dtype(output_dtype, "output")
        self.overrides = []
        for pat, ov in (overrides or {}).items():
            bad = set(ov) - {"param", "compute", "output"}
            if bad:
                raise ValueError("unknown override fields %r for %r"
                                 % (sorted(bad), pat))
            self.overrides.append((str(pat), {
                f: _canon_dtype(v, f) for f, v in ov.items()}))
        # serving KV-page storage mode: validated by the same authority
        # the allocator uses, so a policy can't name a mode the pools
        # can't store
        if kv_dtype is None:
            self.kv_dtype = None
        else:
            from .serving.kv_cache import normalize_kv_dtype
            self.kv_dtype = normalize_kv_dtype(kv_dtype)
        self.loss_scaler = loss_scaler

    def resolve(self, name):
        """Resolved (param, compute, output) canonical dtype names for
        layer ``name`` under laws 1–3."""
        got = {"param": None, "compute": None, "output": None}
        for pat, ov in self.overrides:
            if fnmatch.fnmatchcase(str(name), pat):
                got.update(ov)          # later match wins, field-wise
        param = got["param"] or self.param_dtype
        compute = got["compute"] or self.compute_dtype or param
        output = got["output"] or self.output_dtype or compute
        return Resolved(param, compute, output)

    def cast_params(self, tree, name="*"):
        """Cast every array leaf of a (nested) param tree to the
        resolved ``param`` dtype for ``name`` — how decode_params
        applies the policy to a serving parameter snapshot."""
        import jax
        dt = jax_dtype(self.resolve(name).param)
        return jax.tree_util.tree_map(lambda a: a.astype(dt), tree)

    def fingerprint(self):
        """Stable hash of everything that alters compiled programs:
        dtype layout, overrides, kv_dtype, scaler *configuration*
        (never its dynamic scale).  Folded into the fused-step AOT
        cache keys."""
        scaler = self.loss_scaler.config_key() \
            if self.loss_scaler is not None else None
        spec = (self.param_dtype, self.compute_dtype, self.output_dtype,
                tuple((p, tuple(sorted(ov.items())))
                      for p, ov in self.overrides),
                self.kv_dtype, scaler)
        return hashlib.sha256(repr(spec).encode("utf-8")).hexdigest()


def policy_fingerprint(policy):
    """Fingerprint of an optional policy ('' for None) — what the fused
    steps fold into their cache keys unconditionally."""
    return "" if policy is None else policy.fingerprint()
