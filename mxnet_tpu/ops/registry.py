"""Operator registry.

TPU-native analogue of the reference's NNVM op registry
(``NNVM_REGISTER_OP`` / ``MXNET_REGISTER_OP_PROPERTY``, see
/root/reference/include/mxnet/op_attr_types.h:171-240).  Each operator is a
pure JAX function ``fn(*arrays, **params) -> array | tuple`` plus metadata:

- ``arg_names`` — named inputs (data + learnable params), possibly a function
  of the op's kwargs (e.g. Concat's ``num_args``);
- ``aux_names`` — auxiliary states excluded from gradient (BatchNorm moving
  stats), mirroring ``ListAuxiliaryStates`` in the reference;
- ``num_outputs`` — static or a function of kwargs;
- ``flatten_outputs`` — whether a single-element tuple unwraps.

There is no FCompute<cpu>/FCompute<gpu> split: one jnp/lax lowering serves all
backends, and XLA performs the kernel fusion the reference's graph executor
did by hand (PlanMemory / inplace / op bulking,
/root/reference/src/executor/graph_executor.cc:869-875,1328-1396).

Shape/dtype inference — the reference's per-op ``FInferShape``/``FInferType``
(/root/reference/src/executor/infer_graph_attr_pass.cc) — is derived
automatically from the lowering via ``jax.eval_shape``: no per-op inference
code can disagree with the kernel.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["OpDef", "register_op", "get_op", "list_ops", "alias"]

_OP_REGISTRY: dict = {}


def _hashable(value):
    """Canonicalize a param value into something hashable for the jit cache."""
    if isinstance(value, (list, tuple)):
        return tuple(_hashable(v) for v in value)
    if isinstance(value, dict):
        return tuple(sorted((k, _hashable(v)) for k, v in value.items()))
    return value


class OpDef:
    """A registered operator."""

    def __init__(self, name, fn, arg_names=("data",), aux_names=(),
                 num_outputs=1, param_defaults=None, mutate_aux=False,
                 backward_ignore=(), needs_rng=False, takes_train=False,
                 dynamic_params=()):
        self.name = name
        self.fn = fn
        self._arg_names = arg_names
        self._aux_names = aux_names
        self._num_outputs = num_outputs
        self.param_defaults = dict(param_defaults or {})
        # aux inputs the op updates in place during training (BatchNorm)
        self.mutate_aux = mutate_aux
        # arg names that never receive gradient (labels of loss heads)
        self.backward_ignore = tuple(backward_ignore)
        # op draws randomness: fn takes a PRNG key as its LAST positional arg
        # (the analogue of ResourceRequest::kRandom,
        # /root/reference/include/mxnet/resource.h:36-57)
        self.needs_rng = needs_rng
        # op behaves differently in training: fn takes kwarg ``_train``
        # (the analogue of OpContext::is_train)
        self.takes_train = takes_train
        # scalar params traced as jit ARGUMENTS instead of baked into the
        # compiled program: values that vary per call (a scheduler's lr,
        # Adam's bias-corrected lr, Nadam's momentum schedule) must not
        # key the jit cache, else every step compiles a fresh executable
        # and the cache grows one entry per distinct value.  Only params
        # used purely arithmetically qualify — anything consulted by
        # Python control flow (clip_gradient's sign test, lazy_update)
        # must stay static.
        self.dynamic_params = tuple(dynamic_params)
        self._jit_cache = {}

    # -- metadata ---------------------------------------------------------
    def arg_names(self, params=None):
        if callable(self._arg_names):
            return list(self._arg_names(params or {}))
        return list(self._arg_names)

    def aux_names(self, params=None):
        if callable(self._aux_names):
            return list(self._aux_names(params or {}))
        return list(self._aux_names)

    def num_outputs(self, params=None):
        if callable(self._num_outputs):
            return self._num_outputs(params or {})
        return self._num_outputs

    def canon_params(self, params):
        """Merge with defaults, drop Nones not in defaults, make hashable key."""
        merged = dict(self.param_defaults)
        merged.update({k: v for k, v in params.items() if v is not None or k in merged})
        return merged

    # -- execution --------------------------------------------------------
    def jitted(self, **params):
        """A jitted closure of fn over params, cached per STATIC param
        set.  ``dynamic_params`` present in ``params`` ride as traced
        scalar arguments: the returned callable still takes arrays only
        (their current values are bound in a partial), so callers — and
        the autograd tape replaying it — are none the wiser, but every
        value of a dynamic param reuses one compiled executable."""
        dyn_names = tuple(k for k in self.dynamic_params if k in params)
        if dyn_names:
            dyn_vals = tuple(float(params[k]) for k in dyn_names)
            static = {k: v for k, v in params.items()
                      if k not in dyn_names}
            key = (dyn_names, _hashable(static))
            fun = self._jit_cache.get(key)
            if fun is None:
                fn = functools.partial(self.fn, **static)

                def _call(_dyn, *arrays):
                    return fn(*arrays, **dict(zip(dyn_names, _dyn)))

                fun = jax.jit(_call)
                self._jit_cache[key] = fun
            return functools.partial(fun, dyn_vals)
        key = _hashable(params)
        fun = self._jit_cache.get(key)
        if fun is None:
            fun = jax.jit(functools.partial(self.fn, **params))
            self._jit_cache[key] = fun
        return fun

    def __call__(self, *arrays, **params):
        return self.jitted(**self.canon_params(params))(*arrays)

    def abstract_eval(self, *avals, **params):
        """Shape/dtype inference via jax.eval_shape (replaces FInferShape)."""
        return jax.eval_shape(functools.partial(self.fn, **self.canon_params(params)),
                              *avals)

    def __repr__(self):
        return "OpDef(%s)" % self.name


def register_op(name, arg_names=("data",), aux_names=(), num_outputs=1,
                param_defaults=None, mutate_aux=False, backward_ignore=(),
                needs_rng=False, takes_train=False, dynamic_params=()):
    """Decorator registering ``fn`` as operator ``name``."""
    def _reg(fn):
        op = OpDef(name, fn, arg_names=arg_names, aux_names=aux_names,
                   num_outputs=num_outputs, param_defaults=param_defaults,
                   mutate_aux=mutate_aux, backward_ignore=backward_ignore,
                   needs_rng=needs_rng, takes_train=takes_train,
                   dynamic_params=dynamic_params)
        _OP_REGISTRY[name] = op
        return fn
    return _reg


def alias(name, *aliases):
    """Register additional names for an existing op."""
    op = _OP_REGISTRY[name]
    for a in aliases:
        _OP_REGISTRY[a] = op


def get_op(name):
    op = _OP_REGISTRY.get(name)
    if op is None:
        raise KeyError("Operator %s is not registered" % name)
    return op


def list_ops():
    return sorted(_OP_REGISTRY)
