"""Operator catalog: registry + all op families.

Importing this package registers every operator, mirroring how the
reference's static registration (NNVM_REGISTER_OP at library load) populates
the op registry before any frontend call.
"""
from .registry import OpDef, register_op, get_op, list_ops, alias
from . import tensor  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import random_ops  # noqa: F401
from . import rnn  # noqa: F401
from . import contrib  # noqa: F401
from . import rcnn  # noqa: F401
from . import tail  # noqa: F401
from . import fused  # noqa: F401  (graph-pass fused regions)

__all__ = ["OpDef", "register_op", "get_op", "list_ops", "alias"]
