"""R-CNN detection ops (contrib): Proposal, MultiProposal, PSROIPooling,
DeformableConvolution, DeformablePSROIPooling.

TPU-native lowerings of /root/reference/src/operator/contrib/
{proposal,multi_proposal,psroi_pooling,deformable_convolution,
deformable_psroi_pooling}*.  The reference ships hand-written CUDA kernels;
here each op is a vectorized jnp program: anchor/bbox math is dense
elementwise work, greedy NMS is a fixed-trip lax.fori_loop (static shapes
keep it jittable), and the deformable ops build bilinear-sampled patch
tensors with gathers, reducing to MXU matmuls.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register_op, alias

# ---------------------------------------------------------------------------
# anchors + box utils (proposal-inl.h helpers)
# ---------------------------------------------------------------------------


def _generate_anchors(base_size, scales, ratios):
    """(A, 4) anchors centered on a base_size box at the origin
    (reference rcnn generate_anchors)."""
    import numpy as np
    base = np.array([0, 0, base_size - 1, base_size - 1], np.float32)
    w = base[2] - base[0] + 1
    h = base[3] - base[1] + 1
    cx = base[0] + 0.5 * (w - 1)
    cy = base[1] + 0.5 * (h - 1)
    out = []
    for r in ratios:
        size = w * h
        ws = np.round(np.sqrt(size / r))
        hs = np.round(ws * r)
        for s in scales:
            wss, hss = ws * s, hs * s
            out.append([cx - 0.5 * (wss - 1), cy - 0.5 * (hss - 1),
                        cx + 0.5 * (wss - 1), cy + 0.5 * (hss - 1)])
    return np.array(out, np.float32)


def _bbox_transform_inv(anchors, deltas):
    """Apply (dx, dy, dw, dh) regression deltas to anchors."""
    w = anchors[:, 2] - anchors[:, 0] + 1.0
    h = anchors[:, 3] - anchors[:, 1] + 1.0
    cx = anchors[:, 0] + 0.5 * (w - 1.0)
    cy = anchors[:, 1] + 0.5 * (h - 1.0)
    dx, dy, dw, dh = deltas[:, 0], deltas[:, 1], deltas[:, 2], deltas[:, 3]
    pcx = dx * w + cx
    pcy = dy * h + cy
    pw = jnp.exp(dw) * w
    ph = jnp.exp(dh) * h
    return jnp.stack([pcx - 0.5 * (pw - 1.0), pcy - 0.5 * (ph - 1.0),
                      pcx + 0.5 * (pw - 1.0), pcy + 0.5 * (ph - 1.0)],
                     axis=1)


def _iou_one_vs_all(box, boxes):
    ix0 = jnp.maximum(box[0], boxes[:, 0])
    iy0 = jnp.maximum(box[1], boxes[:, 1])
    ix1 = jnp.minimum(box[2], boxes[:, 2])
    iy1 = jnp.minimum(box[3], boxes[:, 3])
    iw = jnp.maximum(0.0, ix1 - ix0 + 1.0)
    ih = jnp.maximum(0.0, iy1 - iy0 + 1.0)
    inter = iw * ih
    a1 = (box[2] - box[0] + 1.0) * (box[3] - box[1] + 1.0)
    a2 = (boxes[:, 2] - boxes[:, 0] + 1.0) * (boxes[:, 3] - boxes[:, 1] + 1.0)
    return inter / jnp.maximum(a1 + a2 - inter, 1e-12)


def _greedy_nms_mask(boxes, scores, thresh):
    """Boolean keep-mask of greedy NMS over score-sorted boxes."""
    n = boxes.shape[0]
    order = jnp.argsort(-scores)
    sboxes = boxes[order]

    def body(i, keep):
        iou = _iou_one_vs_all(sboxes[i], sboxes)
        suppress = (iou > thresh) & (jnp.arange(n) > i)
        return jnp.where(keep[i], keep & ~suppress, keep)

    keep_sorted = lax.fori_loop(0, n, body, jnp.ones((n,), bool))
    keep = jnp.zeros((n,), bool).at[order].set(keep_sorted)
    return keep


def _proposal_one(scores_fg, bbox_deltas, im_info, anchors_np,
                  feature_stride, rpn_pre_nms_top_n, rpn_post_nms_top_n,
                  threshold, rpn_min_size, iou_loss=False):
    """Proposals for ONE image.

    scores_fg: (A, H, W) foreground scores; bbox_deltas: (4A, H, W).
    Returns (rois (post, 4), roi_scores (post,)).
    """
    A = scores_fg.shape[0]
    H, W = scores_fg.shape[1], scores_fg.shape[2]
    # full anchor field (H*W*A, 4), matching the reference's enumeration
    shift_x = jnp.arange(W, dtype=jnp.float32) * feature_stride
    shift_y = jnp.arange(H, dtype=jnp.float32) * feature_stride
    sx, sy = jnp.meshgrid(shift_x, shift_y)  # (H, W)
    shifts = jnp.stack([sx, sy, sx, sy], axis=-1)  # (H, W, 4)
    anchors = (jnp.asarray(anchors_np)[None, None, :, :] +
               shifts[:, :, None, :]).reshape(-1, 4)  # (H*W*A, 4)
    # deltas (4A, H, W) -> (H, W, A, 4) -> (H*W*A, 4)
    deltas = bbox_deltas.reshape(A, 4, H, W).transpose(2, 3, 0, 1)
    deltas = deltas.reshape(-1, 4)
    scores = scores_fg.transpose(1, 2, 0).reshape(-1)  # (H*W*A,)

    if iou_loss:
        # IoU-loss models regress direct corner offsets
        # (reference proposal-inl.h IoUTransformInv)
        proposals = anchors + deltas
    else:
        proposals = _bbox_transform_inv(anchors, deltas)
    # clip to image
    im_h, im_w = im_info[0], im_info[1]
    proposals = jnp.stack([
        jnp.clip(proposals[:, 0], 0, im_w - 1.0),
        jnp.clip(proposals[:, 1], 0, im_h - 1.0),
        jnp.clip(proposals[:, 2], 0, im_w - 1.0),
        jnp.clip(proposals[:, 3], 0, im_h - 1.0)], axis=1)
    # filter boxes below min_size (scaled by im scale)
    min_size = rpn_min_size * im_info[2]
    ws = proposals[:, 2] - proposals[:, 0] + 1.0
    hs = proposals[:, 3] - proposals[:, 1] + 1.0
    valid = (ws >= min_size) & (hs >= min_size)
    scores = jnp.where(valid, scores, -jnp.inf)

    pre = min(rpn_pre_nms_top_n, scores.shape[0])
    top_scores, top_idx = lax.top_k(scores, pre)
    top_boxes = proposals[top_idx]
    keep = _greedy_nms_mask(top_boxes, top_scores, threshold)
    keep &= jnp.isfinite(top_scores)
    # stable-select kept boxes in score order; when NMS keeps fewer than
    # post_nms_top_n, pad by CYCLING the kept proposals (reference
    # proposal.cc:412 keep[i % out_size]) — downstream ROI sampling must
    # see valid duplicates, not degenerate zero boxes
    rank = jnp.where(keep, jnp.arange(pre), pre + jnp.arange(pre))
    order_all = jnp.argsort(rank)
    num_kept = jnp.maximum(keep.sum(), 1)
    pick = order_all[jnp.arange(rpn_post_nms_top_n) % num_kept]
    return top_boxes[pick], top_scores[pick]


def _proposal_params():
    return {"rpn_pre_nms_top_n": 6000, "rpn_post_nms_top_n": 300,
            "threshold": 0.7, "rpn_min_size": 16,
            "scales": (4.0, 8.0, 16.0, 32.0), "ratios": (0.5, 1.0, 2.0),
            "feature_stride": 16, "output_score": False, "iou_loss": False}


@register_op("_contrib_Proposal",
             arg_names=("cls_prob", "bbox_pred", "im_info"),
             num_outputs=lambda p: 2 if p.get("output_score") else 1,
             param_defaults=_proposal_params())
def _proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
              rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
              scales=(4.0, 8.0, 16.0, 32.0), ratios=(0.5, 1.0, 2.0),
              feature_stride=16, output_score=False, iou_loss=False):
    """RPN proposal layer (reference contrib/proposal.cc; batch size 1).

    cls_prob: (1, 2A, H, W) softmax over {bg, fg} per anchor;
    bbox_pred: (1, 4A, H, W); im_info: (1, 3) = (h, w, scale).
    Output rois: (post_nms_top_n, 5) with batch-index column 0.
    """
    anchors_np = _generate_anchors(feature_stride, scales, ratios)
    A = anchors_np.shape[0]
    boxes, scores = _proposal_one(
        cls_prob[0, A:], bbox_pred[0], im_info[0], anchors_np,
        feature_stride, rpn_pre_nms_top_n, rpn_post_nms_top_n, threshold,
        rpn_min_size, iou_loss)
    rois = jnp.concatenate(
        [jnp.zeros((boxes.shape[0], 1), boxes.dtype), boxes], axis=1)
    if output_score:
        return rois, scores[:, None]
    return rois


alias("_contrib_Proposal", "Proposal")


@register_op("_contrib_MultiProposal",
             arg_names=("cls_prob", "bbox_pred", "im_info"),
             num_outputs=lambda p: 2 if p.get("output_score") else 1,
             param_defaults=_proposal_params())
def _multi_proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
                    rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
                    scales=(4.0, 8.0, 16.0, 32.0), ratios=(0.5, 1.0, 2.0),
                    feature_stride=16, output_score=False, iou_loss=False):
    """Batched Proposal (reference contrib/multi_proposal.cc): rois
    (N*post, 5), column 0 = batch index."""
    import jax
    anchors_np = _generate_anchors(feature_stride, scales, ratios)
    A = anchors_np.shape[0]

    def per_image(args):
        cp, bp, info = args
        return _proposal_one(cp[A:], bp, info, anchors_np, feature_stride,
                             rpn_pre_nms_top_n, rpn_post_nms_top_n,
                             threshold, rpn_min_size, iou_loss)

    boxes, scores = jax.vmap(per_image)((cls_prob, bbox_pred, im_info))
    N, P = boxes.shape[0], boxes.shape[1]
    batch_idx = jnp.repeat(jnp.arange(N, dtype=boxes.dtype), P)
    rois = jnp.concatenate([batch_idx[:, None], boxes.reshape(-1, 4)],
                           axis=1)
    if output_score:
        return rois, scores.reshape(-1, 1)
    return rois


alias("_contrib_MultiProposal", "MultiProposal")


# ---------------------------------------------------------------------------
# PSROIPooling (reference contrib/psroi_pooling.cc)
# ---------------------------------------------------------------------------

@register_op("_contrib_PSROIPooling", arg_names=("data", "rois"),
             param_defaults={"spatial_scale": 1.0, "output_dim": 0,
                             "pooled_size": 0, "group_size": 0})
def _psroi_pooling(data, rois, spatial_scale=1.0, output_dim=0,
                   pooled_size=0, group_size=0):
    """Position-sensitive ROI average pooling.

    data: (N, group²·output_dim, H, W); rois: (R, 5).
    Output: (R, output_dim, pooled, pooled); bin (i, j) of channel c pools
    data channel (c·group + gi)·group + gj over the bin's rectangle.
    """
    if group_size == 0:
        group_size = pooled_size
    P = pooled_size
    G = group_size
    N, C, H, W = data.shape

    yy = jnp.arange(H, dtype=jnp.float32)
    xx = jnp.arange(W, dtype=jnp.float32)

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        # round-half-up = C round() for non-negative coords (the
        # reference psroi_pooling.cu uses C round, not half-to-even)
        x1 = jnp.floor(roi[1] + 0.5) * spatial_scale
        y1 = jnp.floor(roi[2] + 0.5) * spatial_scale
        x2 = (jnp.floor(roi[3] + 0.5) + 1.0) * spatial_scale
        y2 = (jnp.floor(roi[4] + 0.5) + 1.0) * spatial_scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bin_w = rw / P
        bin_h = rh / P
        img = data[b]  # (C, H, W)

        # bin edges per pooled cell
        ph = jnp.arange(P, dtype=jnp.float32)
        hstart = jnp.clip(jnp.floor(y1 + ph * bin_h), 0, H)      # (P,)
        hend = jnp.clip(jnp.ceil(y1 + (ph + 1) * bin_h), 0, H)
        wstart = jnp.clip(jnp.floor(x1 + ph * bin_w), 0, W)
        wend = jnp.clip(jnp.ceil(x1 + (ph + 1) * bin_w), 0, W)

        # mask-based average per bin: (P, H) row masks, (P, W) col masks
        row_m = ((yy[None, :] >= hstart[:, None]) &
                 (yy[None, :] < hend[:, None])).astype(jnp.float32)
        col_m = ((xx[None, :] >= wstart[:, None]) &
                 (xx[None, :] < wend[:, None])).astype(jnp.float32)
        # sums over bins: (C, P, P)
        tmp = jnp.einsum("ih,chw->ciw", row_m, img)
        sums = jnp.einsum("jw,ciw->cij", col_m, tmp)
        counts = row_m.sum(1)[None, :, None] * col_m.sum(1)[None, None, :]
        means = jnp.where(counts > 0, sums / jnp.maximum(counts, 1.0), 0.0)
        # position-sensitive channel select: bin (i,j) takes channel
        # (c*G + gi)*G + gj with gi = i*G//P, gj = j*G//P
        gi = (jnp.arange(P) * G // P).astype(jnp.int32)
        gj = (jnp.arange(P) * G // P).astype(jnp.int32)
        c_idx = (jnp.arange(output_dim)[:, None, None] * G +
                 gi[None, :, None]) * G + gj[None, None, :]
        return means[c_idx, jnp.arange(P)[None, :, None],
                     jnp.arange(P)[None, None, :]]

    import jax
    return jax.vmap(one_roi)(rois)


alias("_contrib_PSROIPooling", "PSROIPooling")


# ---------------------------------------------------------------------------
# Deformable ops (reference contrib/deformable_convolution.cc,
# deformable_psroi_pooling.cc — Dai et al. 2017)
# ---------------------------------------------------------------------------

def _bilinear_at(img, y, x):
    """Bilinear sample img (C, H, W) at float coords y, x (...); zero
    outside [0, H/W-1] as the reference's im2col does."""
    C, H, W = img.shape
    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    wy1 = y - y0
    wx1 = x - x0
    wy0 = 1.0 - wy1
    wx0 = 1.0 - wx1

    def at(yi, xi):
        inb = (yi >= 0) & (yi <= H - 1) & (xi >= 0) & (xi <= W - 1)
        yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
        xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
        v = img[:, yc, xc]  # (C, ...)
        return jnp.where(inb, v, 0.0)

    out = (at(y0, x0) * (wy0 * wx0) + at(y0, x0 + 1) * (wy0 * wx1) +
           at(y0 + 1, x0) * (wy1 * wx0) + at(y0 + 1, x0 + 1) * (wy1 * wx1))
    valid = (y > -1) & (y < H) & (x > -1) & (x < W)
    return jnp.where(valid, out, 0.0)


def _bilinear_clamped(img, y, x):
    """Bilinear sample img (C, H, W) at in-range float coords y, x using
    floor/ceil corner pairs, matching the reference's bilinear_interp
    (deformable_psroi_pooling.cu:49-68).  Coords must already be clamped
    to [0, H-1]/[0, W-1]."""
    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    y1 = jnp.ceil(y)
    x1 = jnp.ceil(x)
    dy = y - y0
    dx = x - x0

    def at(yi, xi):
        return img[:, yi.astype(jnp.int32), xi.astype(jnp.int32)]

    return ((1 - dx) * (1 - dy) * at(y0, x0) + (1 - dx) * dy * at(y1, x0) +
            dx * (1 - dy) * at(y0, x1) + dx * dy * at(y1, x1))


@register_op("_contrib_DeformableConvolution",
             arg_names=("data", "offset", "weight", "bias"),
             param_defaults={"kernel": (3, 3), "stride": (1, 1),
                             "dilate": (1, 1), "pad": (0, 0),
                             "num_filter": 0, "num_group": 1,
                             "num_deformable_group": 1, "workspace": 1024,
                             "no_bias": False})
def _deformable_convolution(data, offset, weight, bias=None, kernel=(3, 3),
                            stride=(1, 1), dilate=(1, 1), pad=(0, 0),
                            num_filter=0, num_group=1,
                            num_deformable_group=1, workspace=1024,
                            no_bias=False):
    """Deformable conv v1: kernel taps sample at learned offsets.

    data (N, C, H, W); offset (N, 2·dg·KH·KW, OH, OW) ordered
    (dg, kh, kw, {y, x}); weight (F, C/g, KH, KW).
    Lowering: bilinear-gather a deformable im2col tensor
    (N, C·KH·KW, OH, OW), then one MXU matmul per group.
    """
    import jax
    KH, KW = kernel
    SH, SW = stride
    DH, DW = dilate
    PH, PW = pad
    N, C, H, W = data.shape
    OH = (H + 2 * PH - DH * (KH - 1) - 1) // SH + 1
    OW = (W + 2 * PW - DW * (KW - 1) - 1) // SW + 1
    dg = num_deformable_group

    # base sampling positions (KH, KW, OH, OW), in unpadded coords
    oy = jnp.arange(OH, dtype=jnp.float32) * SH - PH
    ox = jnp.arange(OW, dtype=jnp.float32) * SW - PW
    ky = jnp.arange(KH, dtype=jnp.float32) * DH
    kx = jnp.arange(KW, dtype=jnp.float32) * DW
    base_y = oy[None, None, :, None] + ky[:, None, None, None]
    base_x = ox[None, None, None, :] + kx[None, :, None, None]
    base_y = jnp.broadcast_to(base_y, (KH, KW, OH, OW))
    base_x = jnp.broadcast_to(base_x, (KH, KW, OH, OW))

    def per_image(img, off):
        # off: (2*dg*KH*KW, OH, OW) -> (dg, KH, KW, 2, OH, OW)
        off = off.reshape(dg, KH, KW, 2, OH, OW)

        def per_dgroup(img_g, off_g):
            # img_g: (C/dg, H, W); off_g: (KH, KW, 2, OH, OW)
            y = base_y + off_g[:, :, 0]
            x = base_x + off_g[:, :, 1]
            return _bilinear_at(img_g, y, x)  # (C/dg, KH, KW, OH, OW)

        img_d = img.reshape(dg, C // dg, H, W)
        cols = jax.vmap(per_dgroup)(img_d, off)  # (dg, C/dg, KH, KW, OH, OW)
        return cols.reshape(C, KH, KW, OH, OW)

    cols = jax.vmap(per_image)(data, offset)  # (N, C, KH, KW, OH, OW)
    # grouped matmul: weight (F, C/g, KH, KW)
    F = num_filter
    g = num_group
    cols = cols.reshape(N, g, C // g, KH * KW, OH * OW)
    wmat = weight.reshape(g, F // g, (C // g) * KH * KW)
    cols2 = cols.reshape(N, g, (C // g) * KH * KW, OH * OW)
    out = jnp.einsum("gfk,ngko->ngfo", wmat, cols2)
    out = out.reshape(N, F, OH, OW)
    if not no_bias and bias is not None:
        out = out + bias[None, :, None, None]
    return out


alias("_contrib_DeformableConvolution", "DeformableConvolution")


@register_op("_contrib_DeformablePSROIPooling",
             arg_names=lambda p: (["data", "rois"] if p.get("no_trans")
                                  else ["data", "rois", "trans"]),
             param_defaults={"spatial_scale": 1.0, "output_dim": 0,
                             "group_size": 1, "pooled_size": 0,
                             "part_size": 0, "sample_per_part": 1,
                             "trans_std": 0.0, "no_trans": False})
def _deformable_psroi_pooling(data, rois, trans=None, spatial_scale=1.0,
                              output_dim=0, group_size=1, pooled_size=0,
                              part_size=0, sample_per_part=1, trans_std=0.0,
                              no_trans=False):
    """Deformable position-sensitive ROI pooling (Dai et al. 2017).

    Matches the reference kernel (deformable_psroi_pooling.cu:89-162)
    exactly: bins sample a sub-grid at *corners* ``start + i*sub_bin``,
    out-of-range samples (beyond ±0.5 of the border) are excluded from
    both the sum and the divisor, in-range coords are clamped (not
    zeroed) before bilinear interp, and the learned (dx, dy) shift comes
    from `trans` (R, 2·num_classes, part, part) with class index
    ``ctop // (output_dim // num_classes)`` — class-aware R-FCN layout,
    channel 2·cls = x, 2·cls+1 = y.
    """
    import jax
    P = pooled_size
    G = group_size
    PS = part_size if part_size > 0 else P
    N, C, H, W = data.shape
    sp = sample_per_part
    ncls = 1 if (no_trans or trans is None) else trans.shape[1] // 2
    cec = output_dim // max(ncls, 1)  # channels_each_class

    def one_roi(roi, tr):
        b = roi[0].astype(jnp.int32)
        x1 = jnp.floor(roi[1] + 0.5) * spatial_scale - 0.5
        y1 = jnp.floor(roi[2] + 0.5) * spatial_scale - 0.5
        x2 = (jnp.floor(roi[3] + 0.5) + 1.0) * spatial_scale - 0.5
        y2 = (jnp.floor(roi[4] + 0.5) + 1.0) * spatial_scale - 0.5
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bin_w = rw / P
        bin_h = rh / P
        img = data[b]

        ph = jnp.arange(P)
        pw = jnp.arange(P)
        # per-bin part index = floor(bin / P * PS)
        pi = (ph * PS // P).astype(jnp.int32)
        pj = (pw * PS // P).astype(jnp.int32)
        iw = jnp.arange(sp, dtype=jnp.float32)
        ih = jnp.arange(sp, dtype=jnp.float32)

        means_cls = []
        for cls in range(ncls):
            if no_trans or tr is None:
                dx = jnp.zeros((P, P), jnp.float32)
                dy = jnp.zeros((P, P), jnp.float32)
            else:
                t = tr.reshape(ncls, 2, PS, PS)
                dx = t[cls, 0, pi[:, None], pj[None, :]] * trans_std * rw
                dy = t[cls, 1, pi[:, None], pj[None, :]] * trans_std * rh
            wstart = x1 + pw[None, :] * bin_w + dx  # (P, P)
            hstart = y1 + ph[:, None] * bin_h + dy
            # corner sampling: start + i * sub_bin_size
            sy = (hstart[:, :, None, None] +
                  ih[None, None, :, None] * bin_h / sp)
            sx = (wstart[:, :, None, None] +
                  iw[None, None, None, :] * bin_w / sp)
            inb = ((sx >= -0.5) & (sx <= W - 0.5) &
                   (sy >= -0.5) & (sy <= H - 0.5))
            syc = jnp.clip(sy, 0.0, H - 1.0)
            sxc = jnp.clip(sx, 0.0, W - 1.0)
            # only this class's channel slice is ever read downstream
            img_cls = img[cls * cec * G * G:(cls + 1) * cec * G * G]
            vals = _bilinear_clamped(img_cls, syc, sxc)  # (cec·G², P,P,sp,sp)
            vals = jnp.where(inb[None], vals, 0.0)
            cnt = inb.sum(axis=(2, 3)).astype(jnp.float32)  # (P, P)
            s = vals.sum(axis=(3, 4))  # (cec·G², P, P)
            means_cls.append(
                jnp.where(cnt > 0, s / jnp.maximum(cnt, 1.0), 0.0))
        means = jnp.stack(means_cls)  # (ncls, cec·G², P, P)

        # position-sensitive channel select: c = (ctop*G + gh)*G + gw,
        # relative to the class's slice
        gi = jnp.clip((ph * G // P).astype(jnp.int32), 0, G - 1)
        gj = jnp.clip((pw * G // P).astype(jnp.int32), 0, G - 1)
        ctop = jnp.arange(output_dim)
        cls_idx = (ctop // cec).astype(jnp.int32)
        rel_c = ((ctop - cls_idx * cec)[:, None, None] * G +
                 gi[None, :, None]) * G + gj[None, None, :]
        return means[cls_idx[:, None, None], rel_c,
                     ph[None, :, None], pw[None, None, :]]

    if trans is None:
        return jax.vmap(lambda r: one_roi(r, None))(rois)
    return jax.vmap(one_roi)(rois, trans)


alias("_contrib_DeformablePSROIPooling", "DeformablePSROIPooling")
