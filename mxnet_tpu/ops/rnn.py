"""Fused RNN operator.

TPU-native replacement for the reference's GPU-only cuDNN fused RNN
(/root/reference/src/operator/cudnn_rnn-inl.h; the CPU path is
``LOG(FATAL) "Not Implemented"``, rnn-inl.h:124,320).  Lowering strategy:

- the input projection for ALL timesteps is one large (T*N, I) x (I, G*H)
  matmul — MXU-shaped work hoisted out of the recurrence;
- the recurrence itself is ``lax.scan`` over time with the (N, H) x (H, G*H)
  hidden matmul per step — XLA compiles the loop once, static shapes;
- bidirectional runs the reverse direction as a flipped scan and concats;
- multi-layer stacks feed the previous layer's (T, N, D*H) output upward.

Weight layout is a single packed parameter vector like cuDNN's filter blob:
for each layer, then each direction: [W(G*H, in), R(G*H, H), bW(G*H),
bR(G*H)].  Gate order: LSTM i,f,g,o; GRU r,z,n — matching cuDNN so
``mx.rnn.FusedRNNCell.unfuse`` semantics carry over.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def rnn_param_size(num_layers, input_size, state_size, bidirectional, mode):
    """Total packed parameter count (mirrors cudnn_rnn-inl.h filter sizing)."""
    G = _GATES[mode]
    D = 2 if bidirectional else 1
    total = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else state_size * D
        per_dir = G * state_size * (in_sz + state_size) + 2 * G * state_size
        total += per_dir * D
    return total


def _unpack(params, num_layers, input_size, state_size, bidirectional, mode):
    G = _GATES[mode]
    D = 2 if bidirectional else 1
    H = state_size
    out = []
    off = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else H * D
        dirs = []
        for _ in range(D):
            W = params[off:off + G * H * in_sz].reshape((G * H, in_sz))
            off += G * H * in_sz
            R = params[off:off + G * H * H].reshape((G * H, H))
            off += G * H * H
            bW = params[off:off + G * H]
            off += G * H
            bR = params[off:off + G * H]
            off += G * H
            dirs.append((W, R, bW, bR))
        out.append(dirs)
    return out


def _cell_step(mode, H):
    if mode == "lstm":
        def step(carry, xw, R, bR):
            h, c = carry
            gates = xw + jnp.matmul(h, R.T) + bR
            i = jax.nn.sigmoid(gates[:, 0 * H:1 * H])
            f = jax.nn.sigmoid(gates[:, 1 * H:2 * H])
            g = jnp.tanh(gates[:, 2 * H:3 * H])
            o = jax.nn.sigmoid(gates[:, 3 * H:4 * H])
            c2 = f * c + i * g
            h2 = o * jnp.tanh(c2)
            return (h2, c2), h2
    elif mode == "gru":
        def step(carry, xw, R, bR):
            (h,) = carry
            rh = jnp.matmul(h, R.T) + bR
            r = jax.nn.sigmoid(xw[:, 0 * H:1 * H] + rh[:, 0 * H:1 * H])
            z = jax.nn.sigmoid(xw[:, 1 * H:2 * H] + rh[:, 1 * H:2 * H])
            n = jnp.tanh(xw[:, 2 * H:3 * H] + r * rh[:, 2 * H:3 * H])
            h2 = (1 - z) * n + z * h
            return (h2,), h2
    else:
        act = jax.nn.relu if mode == "rnn_relu" else jnp.tanh
        def step(carry, xw, R, bR):
            (h,) = carry
            h2 = act(xw + jnp.matmul(h, R.T) + bR)
            return (h2,), h2
    return step


def _run_direction(x, Wt, R, bW, bR, h0, c0, mode, H, reverse):
    # x: (T, N, in); hoist the input projection out of the scan (MXU batch)
    T, N = x.shape[0], x.shape[1]
    xw = jnp.matmul(x.reshape((T * N, -1)), Wt.T).reshape((T, N, -1)) + bW
    step = _cell_step(mode, H)
    carry = (h0, c0) if mode == "lstm" else (h0,)

    def body(carry, xw_t):
        return step(carry, xw_t, R, bR)

    carry, ys = lax.scan(body, carry, xw, reverse=reverse)
    return carry, ys


@register_op("RNN",
             arg_names=lambda p: (["data", "parameters", "state", "state_cell"]
                                  if p.get("mode") == "lstm"
                                  else ["data", "parameters", "state"]),
             takes_train=True, needs_rng=True,
             num_outputs=lambda p: (
                 (3 if p.get("mode") == "lstm" else 2)
                 if p.get("state_outputs") else 1),
             param_defaults={"state_size": 0, "num_layers": 1,
                             "bidirectional": False, "mode": "lstm",
                             "p": 0.0, "state_outputs": False,
                             "lstm_state_clip_min": None,
                             "lstm_state_clip_max": None})
def _rnn(data, parameters, state, state_cell=None, rng=None, state_size=0,
         num_layers=1, bidirectional=False, mode="lstm", p=0.0,
         state_outputs=False, lstm_state_clip_min=None,
         lstm_state_clip_max=None, _train=False):
    if mode != "lstm" and state_cell is not None and rng is None:
        # non-LSTM callers pass only 3 named inputs, so the appended PRNG
        # key arrives in the state_cell slot — rebind it
        rng, state_cell = state_cell, None
    T, N, I = data.shape
    H = state_size
    D = 2 if bidirectional else 1
    layers = _unpack(parameters, num_layers, I, H, bidirectional, mode)
    x = data
    h_states, c_states = [], []
    for li, dirs in enumerate(layers):
        outs = []
        for di, (W, R, bW, bR) in enumerate(dirs):
            idx = li * D + di
            # begin states may carry batch dim 1 (symbolic zeros from
            # rnn_cell.begin_state) — broadcast up so the scan carry shape
            # is fixed at (N, H)
            h0 = jnp.broadcast_to(state[idx], (N, H))
            c0 = (jnp.broadcast_to(state_cell[idx], (N, H))
                  if mode == "lstm" else None)
            carry, ys = _run_direction(x, W, R, bW, bR, h0, c0, mode, H,
                                       reverse=(di == 1))
            h_states.append(carry[0])
            if mode == "lstm":
                c_states.append(carry[1])
            outs.append(ys)
        x = outs[0] if D == 1 else jnp.concatenate(outs, axis=-1)
        if p > 0.0 and _train and li < num_layers - 1 and rng is not None:
            key = jax.random.fold_in(rng, li)
            mask = jax.random.bernoulli(key, 1.0 - p, x.shape)
            x = jnp.where(mask, x / (1.0 - p), jnp.zeros_like(x))
    out = x  # (T, N, D*H)
    if not state_outputs:
        return out
    hs = jnp.stack(h_states)
    if mode == "lstm":
        return out, hs, jnp.stack(c_states)
    return out, hs
