"""Neural-network operators.

TPU-native lowerings of the reference's core NN ops
(/root/reference/src/operator/{convolution,fully_connected,batch_norm,
pooling,activation,leaky_relu,dropout,lrn,instance_norm,l2_normalization,
softmax_output,...}-inl.h).  Convolutions map straight onto
``lax.conv_general_dilated`` (the MXU path — XLA picks the tiling the
reference delegated to cuDNN's autotuner, cudnn_algoreg-inl.h); pooling is
``lax.reduce_window``; everything else is fused elementwise work that XLA
folds into neighbouring matmuls.

Loss heads (SoftmaxOutput, *RegressionOutput, SVMOutput) reproduce the
reference's *implicit gradient* contract via ``jax.custom_vjp``: their
forward is the prediction, and backward injects (pred - label) style
gradients regardless of what is chained above — exactly the fused
softmax+CE behaviour of src/operator/softmax_output-inl.h.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op, alias


def _tuplize(x, n):
    if x is None or x == ():
        return (1,) * n
    if isinstance(x, int):
        return (x,) * n
    return tuple(x)


# ---------------------------------------------------------------------------
# FullyConnected (/root/reference/src/operator/fully_connected-inl.h)
# ---------------------------------------------------------------------------

@register_op("FullyConnected",
             arg_names=lambda p: (["data", "weight"] if p.get("no_bias")
                                  else ["data", "weight", "bias"]),
             param_defaults={"num_hidden": 0, "no_bias": False,
                             "flatten": True})
def _fully_connected(data, weight, bias=None, num_hidden=0, no_bias=False,
                     flatten=True):
    if flatten and data.ndim > 2:
        data = data.reshape((data.shape[0], -1))
    out = jnp.matmul(data, weight.T)
    if bias is not None:
        out = out + bias
    return out


# ---------------------------------------------------------------------------
# Convolution / Deconvolution (/root/reference/src/operator/convolution-inl.h)
# ---------------------------------------------------------------------------

def _conv_dnums(ndim):
    # NC(spatial...) data, OI(spatial...) weights — MXNet's native layout
    sp = "DHW"[-ndim:]
    return lax.conv_dimension_numbers(
        (1, 1) + (1,) * ndim, (1, 1) + (1,) * ndim,
        ("NC" + sp, "OI" + sp, "NC" + sp))


@register_op("Convolution",
             arg_names=lambda p: (["data", "weight"] if p.get("no_bias")
                                  else ["data", "weight", "bias"]),
             param_defaults={"kernel": (), "stride": (), "dilate": (),
                             "pad": (), "num_filter": 0, "num_group": 1,
                             "no_bias": False, "workspace": 1024,
                             "cudnn_tune": None, "cudnn_off": False,
                             "layout": None})
def _convolution(data, weight, bias=None, kernel=(), stride=(), dilate=(),
                 pad=(), num_filter=0, num_group=1, no_bias=False,
                 workspace=1024, cudnn_tune=None, cudnn_off=False, layout=None):
    ndim = len(kernel)
    stride = _tuplize(stride, ndim)
    dilate = _tuplize(dilate, ndim)
    pad = _tuplize(pad if pad else 0, ndim)
    out = lax.conv_general_dilated(
        data, weight, window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate,
        dimension_numbers=_conv_dnums(ndim),
        feature_group_count=num_group)
    if bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * ndim)
    return out


@register_op("Deconvolution",
             arg_names=lambda p: (["data", "weight"] if p.get("no_bias", True)
                                  else ["data", "weight", "bias"]),
             param_defaults={"kernel": (), "stride": (), "dilate": (),
                             "pad": (), "adj": (), "target_shape": (),
                             "num_filter": 0, "num_group": 1, "no_bias": True,
                             "workspace": 512, "cudnn_tune": None,
                             "cudnn_off": False, "layout": None})
def _deconvolution(data, weight, bias=None, kernel=(), stride=(), dilate=(),
                   pad=(), adj=(), target_shape=(), num_filter=0, num_group=1,
                   no_bias=True, workspace=512, cudnn_tune=None,
                   cudnn_off=False, layout=None):
    # Transposed convolution = gradient of Convolution wrt data
    # (src/operator/deconvolution-inl.h) — lax expresses it as an lhs-dilated
    # conv with flipped kernels.
    ndim = len(kernel)
    stride = _tuplize(stride, ndim)
    dilate = _tuplize(dilate, ndim)
    pad = _tuplize(pad if pad else 0, ndim)
    adj = _tuplize(adj if adj else 0, ndim)
    # effective kernel extent
    pads = []
    for i in range(ndim):
        k_eff = (kernel[i] - 1) * dilate[i] + 1
        pads.append((k_eff - 1 - pad[i], k_eff - 1 - pad[i] + adj[i]))
    # weight layout for Deconvolution is (in_channel, out_channel/group, *k)
    if num_group > 1:
        ci = data.shape[1]
        w = weight.reshape((num_group, ci // num_group, -1) + tuple(kernel))
        w = jnp.flip(w, axis=tuple(range(3, 3 + ndim)))
        w = jnp.swapaxes(w, 1, 2).reshape(
            (-1, ci // num_group) + tuple(kernel))
    else:
        w = jnp.flip(weight, axis=tuple(range(2, 2 + ndim)))
        w = jnp.swapaxes(w, 0, 1)  # → (out, in, *k)
    out = lax.conv_general_dilated(
        data, w, window_strides=(1,) * ndim, padding=pads,
        lhs_dilation=stride, rhs_dilation=dilate,
        dimension_numbers=_conv_dnums(ndim),
        feature_group_count=num_group)
    if bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * ndim)
    return out


# ---------------------------------------------------------------------------
# Pooling (/root/reference/src/operator/pooling-inl.h, nn/pool.h)
# ---------------------------------------------------------------------------

@register_op("Pooling", arg_names=("data",),
             param_defaults={"kernel": (), "pool_type": "max", "stride": (),
                             "pad": (), "global_pool": False,
                             "pooling_convention": "valid", "cudnn_off": False})
def _pooling(data, kernel=(), pool_type="max", stride=(), pad=(),
             global_pool=False, pooling_convention="valid", cudnn_off=False):
    ndim = data.ndim - 2
    if global_pool:
        axes = tuple(range(2, data.ndim))
        if pool_type == "max":
            return jnp.max(data, axis=axes, keepdims=True)
        if pool_type == "sum":
            return jnp.sum(data, axis=axes, keepdims=True)
        return jnp.mean(data, axis=axes, keepdims=True)
    kernel = _tuplize(kernel, ndim)
    stride = _tuplize(stride, ndim)
    pad = _tuplize(pad if pad else 0, ndim)
    pads = []
    for i in range(ndim):
        lo = pad[i]
        hi = pad[i]
        if pooling_convention == "full":
            # ceil-mode output: pad extra on the high side
            size = data.shape[2 + i] + 2 * pad[i]
            out_sz = -(-(size - kernel[i]) // stride[i]) + 1
            need = (out_sz - 1) * stride[i] + kernel[i]
            hi += max(0, need - size)
        pads.append((lo, hi))
    window = (1, 1) + kernel
    strides = (1, 1) + stride
    padding = [(0, 0), (0, 0)] + pads
    if pool_type == "max":
        # literal init value keeps the reduce_window_max pattern (and its
        # VJP) recognizable to JAX
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) else \
            jnp.iinfo(data.dtype).min
        return lax.reduce_window(data, init, lax.max, window, strides,
                                 padding)
    summed = lax.reduce_window(data, 0.0, lax.add, window, strides, padding)
    if pool_type == "sum":
        return summed
    # avg: count includes padding, matching the reference default
    denom = 1.0
    for k in kernel:
        denom *= k
    return summed / jnp.asarray(denom, data.dtype)


# ---------------------------------------------------------------------------
# Activations (/root/reference/src/operator/activation-inl.h, leaky_relu-inl.h)
# ---------------------------------------------------------------------------

@register_op("Activation", arg_names=("data",),
             param_defaults={"act_type": "relu"})
def _activation(data, act_type="relu"):
    if act_type == "relu":
        return jax.nn.relu(data)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(data)
    if act_type == "tanh":
        return jnp.tanh(data)
    if act_type == "softrelu":
        return jax.nn.softplus(data)
    if act_type == "softsign":
        return jax.nn.soft_sign(data)
    if act_type == "gelu":
        # tanh approximation (the GPT-2 form); fused by XLA into the
        # adjacent matmul — TPU-native addition, the 2017 reference's
        # activation set predates gelu
        return jax.nn.gelu(data, approximate=True)
    if act_type == "gelu_erf":
        return jax.nn.gelu(data, approximate=False)
    raise ValueError("unknown act_type %s" % act_type)


@register_op("LeakyReLU",
             arg_names=lambda p: (["data", "gamma"]
                                  if p.get("act_type") == "prelu" else ["data"]),
             param_defaults={"act_type": "leaky", "slope": 0.25,
                             "lower_bound": 0.125, "upper_bound": 0.334})
def _leaky_relu(data, gamma=None, act_type="leaky", slope=0.25,
                lower_bound=0.125, upper_bound=0.334):
    if act_type == "leaky":
        return jnp.where(data > 0, data, slope * data)
    if act_type == "elu":
        return jnp.where(data > 0, data, slope * jnp.expm1(data))
    if act_type == "prelu":
        g = gamma.reshape((1, -1) + (1,) * (data.ndim - 2))
        return jnp.where(data > 0, data, g * data)
    if act_type == "rrelu":
        # deterministic midpoint at inference (reference trains with a drawn
        # slope; the random path rides the Dropout-style rng plumbing later)
        mid = (lower_bound + upper_bound) / 2.0
        return jnp.where(data > 0, data, mid * data)
    raise ValueError("unknown act_type %s" % act_type)


# ---------------------------------------------------------------------------
# Softmax family (/root/reference/src/operator/nn/softmax-inl.h,
# softmax_activation-inl.h, softmax_output-inl.h)
# ---------------------------------------------------------------------------

@register_op("softmax", arg_names=("data",),
             param_defaults={"axis": -1, "temperature": None})
def _softmax(data, axis=-1, temperature=None):
    if temperature:
        data = data / temperature
    return jax.nn.softmax(data, axis=axis)


@register_op("log_softmax", arg_names=("data",),
             param_defaults={"axis": -1, "temperature": None})
def _log_softmax(data, axis=-1, temperature=None):
    if temperature:
        data = data / temperature
    return jax.nn.log_softmax(data, axis=axis)


@register_op("SoftmaxActivation", arg_names=("data",),
             param_defaults={"mode": "instance"})
def _softmax_activation(data, mode="instance"):
    if mode == "channel":
        return jax.nn.softmax(data, axis=1)
    return jax.nn.softmax(data.reshape((data.shape[0], -1)),
                          axis=-1).reshape(data.shape)


def _softmax_output_fwd(data, label, grad_scale, ignore_label, multi_output,
                        use_ignore, preserve_shape, normalization,
                        smooth_alpha, out_grad):
    if multi_output:
        prob = jax.nn.softmax(data, axis=1)
    elif preserve_shape:
        prob = jax.nn.softmax(data, axis=-1)
    else:
        prob = jax.nn.softmax(data.reshape((data.shape[0], -1)),
                              axis=-1).reshape(data.shape)
    return prob


@register_op("SoftmaxOutput", arg_names=("data", "label"),
             backward_ignore=("label",),
             param_defaults={"grad_scale": 1.0, "ignore_label": -1.0,
                             "multi_output": False, "use_ignore": False,
                             "preserve_shape": False, "normalization": "null",
                             "smooth_alpha": 0.0, "out_grad": False})
def _softmax_output(data, label, grad_scale=1.0, ignore_label=-1.0,
                    multi_output=False, use_ignore=False, preserve_shape=False,
                    normalization="null", smooth_alpha=0.0, out_grad=False):
    multi_output = bool(multi_output)
    use_ignore = bool(use_ignore)

    @jax.custom_vjp
    def core(d, l):
        return _softmax_output_fwd(d, l, grad_scale, ignore_label,
                                   multi_output, use_ignore, preserve_shape,
                                   normalization, smooth_alpha, out_grad)

    def core_fwd(d, l):
        prob = core(d, l)
        return prob, (prob, l)

    def core_bwd(res, g):
        prob, l = res
        # fused softmax+cross-entropy gradient: prob - one_hot(label)
        # (/root/reference/src/operator/softmax_output-inl.h)
        axis = 1 if multi_output else -1
        nclass = prob.shape[axis]
        lbl = l.astype(jnp.int32)
        onehot = jax.nn.one_hot(lbl, nclass, dtype=prob.dtype)
        if smooth_alpha:
            onehot = onehot * (1.0 - smooth_alpha) + \
                smooth_alpha / (nclass - 1) * (1.0 - onehot)
        if multi_output:
            onehot = jnp.moveaxis(onehot, -1, 1)
        grad = prob - onehot.reshape(prob.shape)
        valid = None
        if use_ignore:
            mask = (lbl != jnp.asarray(ignore_label, lbl.dtype))
            bmask = jnp.expand_dims(mask, axis).astype(prob.dtype)
            grad = grad * jnp.broadcast_to(bmask, prob.shape).reshape(prob.shape)
            valid = jnp.maximum(jnp.sum(mask.astype(prob.dtype)), 1.0)
        if normalization == "batch":
            grad = grad / prob.shape[0]
        elif normalization == "valid" and valid is not None:
            grad = grad / valid
        grad = grad * grad_scale
        if out_grad:
            grad = grad * g
        return grad.astype(prob.dtype), jnp.zeros_like(l)

    core.defvjp(core_fwd, core_bwd)
    return core(data, label)

alias("SoftmaxOutput", "Softmax")


@register_op("softmax_cross_entropy", arg_names=("data", "label"),
             backward_ignore=("label",))
def _softmax_cross_entropy(data, label):
    logp = jax.nn.log_softmax(data, axis=-1)
    picked = jnp.take_along_axis(
        logp, label.astype(jnp.int32)[:, None], axis=-1)
    return -jnp.sum(picked).reshape((1,))


# ---------------------------------------------------------------------------
# Regression / SVM heads (/root/reference/src/operator/regression_output-inl.h)
# ---------------------------------------------------------------------------

def _make_regression(name, fwd, grad_fn):
    @jax.custom_vjp
    def core(data, label, grad_scale=1.0):
        return fwd(data)

    def core_fwd(data, label, grad_scale):
        out = fwd(data)
        return out, (out, label, grad_scale)

    def core_bwd(res, g):
        out, label, grad_scale = res
        # reference scales by grad_scale / num_output
        # (regression_output-inl.h: out.Size()/out.shape_[0])
        n = out.size // out.shape[0] if out.ndim > 1 else 1
        grad = grad_fn(out, label.reshape(out.shape)) * (grad_scale / n)
        return grad.astype(out.dtype), jnp.zeros_like(label), None

    core.defvjp(core_fwd, core_bwd)

    @register_op(name, arg_names=("data", "label"),
                 backward_ignore=("label",),
                 param_defaults={"grad_scale": 1.0})
    def _op(data, label, grad_scale=1.0):
        return core(data, label, grad_scale)
    return _op


_make_regression("LinearRegressionOutput", lambda x: x,
                 lambda out, label: out - label)
_make_regression("MAERegressionOutput", lambda x: x,
                 lambda out, label: jnp.sign(out - label))
_make_regression("LogisticRegressionOutput", jax.nn.sigmoid,
                 lambda out, label: out - label)


@register_op("SVMOutput", arg_names=("data", "label"),
             backward_ignore=("label",),
             param_defaults={"margin": 1.0, "regularization_coefficient": 1.0,
                             "use_linear": False})
def _svm_output(data, label, margin=1.0, regularization_coefficient=1.0,
                use_linear=False):
    @jax.custom_vjp
    def core(d, l):
        return d

    def core_fwd(d, l):
        return d, (d, l)

    def core_bwd(res, g):
        d, l = res
        lbl = jax.nn.one_hot(l.astype(jnp.int32), d.shape[1], dtype=d.dtype)
        y = 2.0 * lbl - 1.0  # +1 for target class, -1 otherwise
        viol = (margin - y * d) > 0
        if use_linear:
            grad = jnp.where(viol, -y * regularization_coefficient, 0.0)
        else:
            grad = jnp.where(viol, -2.0 * regularization_coefficient *
                             (margin - y * d) * y, 0.0)
        return grad.astype(d.dtype), jnp.zeros_like(l)

    core.defvjp(core_fwd, core_bwd)
    return core(data, label)


# ---------------------------------------------------------------------------
# BatchNorm (/root/reference/src/operator/batch_norm-inl.h)
# ---------------------------------------------------------------------------

@register_op("BatchNorm", arg_names=("data", "gamma", "beta"),
             aux_names=("moving_mean", "moving_var"),
             mutate_aux=True, takes_train=True,
             num_outputs=lambda p: 3 if p.get("output_mean_var") else 1,
             param_defaults={"eps": 1e-3, "momentum": 0.9, "fix_gamma": True,
                             "use_global_stats": False,
                             "output_mean_var": False, "axis": 1,
                             "cudnn_off": False})
def _batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
                momentum=0.9, fix_gamma=True, use_global_stats=False,
                output_mean_var=False, axis=1, cudnn_off=False, _train=False):
    """Returns (visible outputs..., new_moving_mean, new_moving_var).

    The trailing aux values mirror the reference's in-place update of
    aux_states during training (batch_norm-inl.h: moving = moving * momentum
    + batch * (1 - momentum)); the imperative/executor layer writes them back.
    """
    ax = axis % data.ndim
    reduce_axes = tuple(i for i in range(data.ndim) if i != ax)
    bshape = tuple(data.shape[ax] if i == ax else 1 for i in range(data.ndim))
    if fix_gamma:
        gamma = lax.stop_gradient(jnp.ones_like(gamma))
    # stats reduce in >= fp32 — the AMP recipe: bf16/fp16 activations
    # with fp32 statistics (batch_norm-inl.h computes in real_t
    # regardless of the data dtype); f64 test data stays f64
    sdt = jnp.promote_types(data.dtype, jnp.float32)
    if _train and not use_global_stats:
        data_s = data.astype(sdt)
        mean = jnp.mean(data_s, axis=reduce_axes)
        var = jnp.var(data_s, axis=reduce_axes)
        new_mm = moving_mean * momentum + \
            lax.stop_gradient(mean).astype(moving_mean.dtype) * (1 - momentum)
        new_mv = moving_var * momentum + \
            lax.stop_gradient(var).astype(moving_var.dtype) * (1 - momentum)
    else:
        mean = moving_mean.astype(sdt)
        var = moving_var.astype(sdt)
        new_mm, new_mv = moving_mean, moving_var
    inv = lax.rsqrt(var + eps)
    out = ((data.astype(sdt) - mean.reshape(bshape))
           * inv.reshape(bshape) * gamma.astype(sdt).reshape(bshape)
           + beta.astype(sdt).reshape(bshape)).astype(data.dtype)
    if output_mean_var:
        return out, mean, inv, new_mm, new_mv
    return out, new_mm, new_mv


# ---------------------------------------------------------------------------
# Other normalizations
# ---------------------------------------------------------------------------

@register_op("LRN", arg_names=("data",),
             param_defaults={"alpha": 1e-4, "beta": 0.75, "knorm": 2.0,
                             "nsize": 5})
def _lrn(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5):
    # cross-channel local response norm (src/operator/lrn-inl.h)
    sq = jnp.square(data)
    half = nsize // 2
    pad = [(0, 0), (half, half)] + [(0, 0)] * (data.ndim - 2)
    window = (1, nsize) + (1,) * (data.ndim - 2)
    ssum = lax.reduce_window(jnp.pad(sq, pad), 0.0, lax.add, window,
                             (1,) * data.ndim, [(0, 0)] * data.ndim)
    return data / jnp.power(knorm + (alpha / nsize) * ssum, beta)


@register_op("InstanceNorm", arg_names=("data", "gamma", "beta"),
             param_defaults={"eps": 1e-3})
def _instance_norm(data, gamma, beta, eps=1e-3):
    axes = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=axes, keepdims=True)
    var = jnp.var(data, axis=axes, keepdims=True)
    bshape = (1, -1) + (1,) * (data.ndim - 2)
    return (data - mean) * lax.rsqrt(var + eps) * gamma.reshape(bshape) \
        + beta.reshape(bshape)


@register_op("LayerNorm", arg_names=("data", "gamma", "beta"),
             param_defaults={"axis": -1, "eps": 1e-5})
def _layer_norm(data, gamma, beta, axis=-1, eps=1e-5):
    """Layer normalization over one axis.  TPU-native addition (the 2017
    reference predates LayerNorm); statistics in at-least-fp32 (promote,
    don't truncate fp64 tests) so the transformer path keeps MXU-friendly
    bf16 activations with stable norms."""
    x = data.astype(jnp.promote_types(data.dtype, jnp.float32))
    mean = x.mean(axis=axis, keepdims=True)
    var = jnp.square(x - mean).mean(axis=axis, keepdims=True)
    y = (x - mean) * lax.rsqrt(var + eps)
    bshape = [1] * data.ndim
    bshape[axis] = data.shape[axis]
    y = y * gamma.astype(x.dtype).reshape(bshape) \
        + beta.astype(x.dtype).reshape(bshape)
    return y.astype(data.dtype)


@register_op("L2Normalization", arg_names=("data",),
             param_defaults={"eps": 1e-10, "mode": "instance"})
def _l2_normalization(data, eps=1e-10, mode="instance"):
    if mode == "instance":
        axes = tuple(range(1, data.ndim))
    elif mode == "channel":
        axes = (1,)
    else:  # spatial
        axes = tuple(range(2, data.ndim))
    norm = jnp.sqrt(jnp.sum(jnp.square(data), axis=axes, keepdims=True) + eps)
    return data / norm


# ---------------------------------------------------------------------------
# Dropout (/root/reference/src/operator/dropout-inl.h)
# ---------------------------------------------------------------------------

@register_op("Dropout", arg_names=("data",), needs_rng=True, takes_train=True,
             param_defaults={"p": 0.5, "mode": "training"})
def _dropout(data, rng, p=0.5, mode="training", _train=False):
    if not _train and mode != "always":
        return data
    if p <= 0.0:
        return data
    keep = 1.0 - p
    mask = jax.random.bernoulli(rng, keep, data.shape)
    return jnp.where(mask, data / keep, jnp.zeros_like(data))


# ---------------------------------------------------------------------------
# Sequence ops (/root/reference/src/operator/sequence_*.cc)
# ---------------------------------------------------------------------------

@register_op("SequenceLast",
             arg_names=lambda p: (["data", "sequence_length"]
                                  if p.get("use_sequence_length") else ["data"]),
             param_defaults={"use_sequence_length": False})
def _sequence_last(data, sequence_length=None, use_sequence_length=False):
    if not use_sequence_length:
        return data[-1]
    idx = sequence_length.astype(jnp.int32) - 1
    return data[idx, jnp.arange(data.shape[1])]


@register_op("SequenceMask",
             arg_names=lambda p: (["data", "sequence_length"]
                                  if p.get("use_sequence_length") else ["data"]),
             param_defaults={"use_sequence_length": False, "value": 0.0})
def _sequence_mask(data, sequence_length=None, use_sequence_length=False,
                   value=0.0):
    if not use_sequence_length:
        return data
    t = jnp.arange(data.shape[0])[:, None]
    mask = t < sequence_length.astype(jnp.int32)[None, :]
    mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    return jnp.where(mask, data, jnp.asarray(value, data.dtype))


@register_op("SequenceReverse",
             arg_names=lambda p: (["data", "sequence_length"]
                                  if p.get("use_sequence_length") else ["data"]),
             param_defaults={"use_sequence_length": False})
def _sequence_reverse(data, sequence_length=None, use_sequence_length=False):
    if not use_sequence_length:
        return jnp.flip(data, axis=0)
    T = data.shape[0]
    lens = sequence_length.astype(jnp.int32)[None, :]
    t = jnp.arange(T)[:, None]
    src = jnp.where(t < lens, lens - 1 - t, t)
    return data[src, jnp.arange(data.shape[1])[None, :]]


# ---------------------------------------------------------------------------
# Spatial ops: UpSampling, BilinearSampler, GridGenerator, ROIPooling
# ---------------------------------------------------------------------------

def _upsampling_args(p):
    # reference ListArguments: bilinear → {data, weight}; nearest with one
    # input → {data}; multi-input nearest → arg0..argN-1
    if p.get("sample_type") == "bilinear":
        return ["data", "weight"]
    n = int(p.get("num_args", 1))
    return ["data"] if n == 1 else ["arg%d" % i for i in range(n)]


@register_op("UpSampling",
             arg_names=_upsampling_args,
             param_defaults={"scale": 1, "num_filter": 0,
                             "sample_type": "nearest",
                             "multi_input_mode": "concat", "num_args": 1,
                             "workspace": 512})
def _upsampling(*args, scale=1, num_filter=0, sample_type="nearest",
                multi_input_mode="concat", num_args=1, workspace=512):
    if sample_type == "bilinear":
        # learnable deconv upsampling (reference upsampling-inl.h:189-200:
        # kernel 2s-s%2, stride s, pad ceil((s-1)/2), one group per
        # channel); weight shape (C, 1, k, k) — init.Bilinear gives the
        # classic interpolation kernel
        data, weight = args
        c = data.shape[1]
        k = weight.shape[-1]
        pad = int(-(-(scale - 1) // 2))
        return _deconvolution(data, weight, kernel=(k, k),
                              stride=(scale, scale), pad=(pad, pad),
                              num_filter=c, num_group=c, no_bias=True)
    outs = []
    target = args[0].shape[2] * scale
    for a in args:
        s = target // a.shape[2]
        up = jnp.repeat(jnp.repeat(a, s, axis=2), s, axis=3)
        outs.append(up)
    if len(outs) == 1:
        return outs[0]
    if multi_input_mode == "sum":
        out = outs[0]
        for o in outs[1:]:
            out = out + o
        return out
    return jnp.concatenate(outs, axis=1)


@register_op("BilinearSampler", arg_names=("data", "grid"))
def _bilinear_sampler(data, grid):
    # grid: (N, 2, H, W) in [-1, 1] (src/operator/bilinear_sampler-inl.h)
    N, C, H, W = data.shape
    gx = (grid[:, 0] + 1.0) * (W - 1) / 2.0
    gy = (grid[:, 1] + 1.0) * (H - 1) / 2.0
    x0 = jnp.floor(gx); y0 = jnp.floor(gy)
    x1 = x0 + 1; y1 = y0 + 1
    wx = gx - x0; wy = gy - y0

    def gather(y, x):
        yi = jnp.clip(y, 0, H - 1).astype(jnp.int32)
        xi = jnp.clip(x, 0, W - 1).astype(jnp.int32)
        b = jnp.arange(N)[:, None, None]
        return data[b, :, yi, xi]  # (N, Ho, Wo, C)

    val = (gather(y0, x0) * ((1 - wx) * (1 - wy))[..., None]
           + gather(y0, x1) * (wx * (1 - wy))[..., None]
           + gather(y1, x0) * ((1 - wx) * wy)[..., None]
           + gather(y1, x1) * (wx * wy)[..., None])
    return jnp.moveaxis(val, -1, 1)


@register_op("GridGenerator", arg_names=("data",),
             param_defaults={"transform_type": "affine", "target_shape": (0, 0)})
def _grid_generator(data, transform_type="affine", target_shape=(0, 0)):
    H, W = target_shape
    if transform_type == "affine":
        N = data.shape[0]
        theta = data.reshape((N, 2, 3))
        ys = jnp.linspace(-1.0, 1.0, H)
        xs = jnp.linspace(-1.0, 1.0, W)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        coords = jnp.stack([gx.ravel(), gy.ravel(), ones.ravel()])  # (3, HW)
        out = jnp.einsum("nij,jk->nik", theta, coords)  # (N, 2, HW)
        return out.reshape((N, 2, H, W))
    # warp: data is flow field (N, 2, H, W)
    N = data.shape[0]
    ys = jnp.arange(H, dtype=data.dtype)
    xs = jnp.arange(W, dtype=data.dtype)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    gx = (gx + data[:, 0]) * 2.0 / (W - 1) - 1.0
    gy = (gy + data[:, 1]) * 2.0 / (H - 1) - 1.0
    return jnp.stack([gx, gy], axis=1)


@register_op("SpatialTransformer", arg_names=("data", "loc"),
             param_defaults={"target_shape": (0, 0),
                             "transform_type": "affine",
                             "sampler_type": "bilinear"})
def _spatial_transformer(data, loc, target_shape=(0, 0),
                         transform_type="affine", sampler_type="bilinear"):
    grid = _grid_generator(loc, transform_type="affine",
                           target_shape=target_shape)
    return _bilinear_sampler(data, grid)


@register_op("ROIPooling", arg_names=("data", "rois"),
             param_defaults={"pooled_size": (0, 0), "spatial_scale": 1.0})
def _roi_pooling(data, rois, pooled_size=(0, 0), spatial_scale=1.0):
    # rois: (R, 5) = [batch_idx, x1, y1, x2, y2] (src/operator/roi_pooling.cc)
    PH, PW = pooled_size
    N, C, H, W = data.shape

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * spatial_scale).astype(jnp.int32)
        y1 = jnp.round(roi[2] * spatial_scale).astype(jnp.int32)
        x2 = jnp.round(roi[3] * spatial_scale).astype(jnp.int32)
        y2 = jnp.round(roi[4] * spatial_scale).astype(jnp.int32)
        rh = jnp.maximum(y2 - y1 + 1, 1).astype(jnp.float32)
        rw = jnp.maximum(x2 - x1 + 1, 1).astype(jnp.float32)
        img = data[b]  # (C, H, W)
        ph = jnp.arange(PH); pw = jnp.arange(PW)
        hs = jnp.floor(ph * rh / PH).astype(jnp.int32) + y1
        he = jnp.ceil((ph + 1) * rh / PH).astype(jnp.int32) + y1
        ws = jnp.floor(pw * rw / PW).astype(jnp.int32) + x1
        we = jnp.ceil((pw + 1) * rw / PW).astype(jnp.int32) + x1
        yy = jnp.arange(H)[None, :]
        xx = jnp.arange(W)[None, :]
        ymask = (yy >= hs[:, None]) & (yy < he[:, None])  # (PH, H)
        xmask = (xx >= ws[:, None]) & (xx < we[:, None])  # (PW, W)
        m = ymask[:, None, :, None] & xmask[None, :, None, :]  # (PH,PW,H,W)
        neg = jnp.asarray(-jnp.inf, data.dtype)
        masked = jnp.where(m[None], img[:, None, None, :, :], neg)
        out = jnp.max(masked, axis=(-1, -2))  # (C, PH, PW)
        return jnp.where(jnp.isfinite(out), out, 0.0)

    return jax.vmap(one_roi)(rois)


@register_op("Correlation", arg_names=("data1", "data2"),
             param_defaults={"kernel_size": 1, "max_displacement": 1,
                             "stride1": 1, "stride2": 1, "pad_size": 0,
                             "is_multiply": True})
def _correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                 stride2=1, pad_size=0, is_multiply=True):
    # FlowNet-style correlation (src/operator/correlation.cc), simplified to
    # the kernel_size=1 fast path; general kernels average over the patch.
    d = max_displacement
    pad = [(0, 0), (0, 0), (pad_size, pad_size), (pad_size, pad_size)]
    a = jnp.pad(data1, pad)
    b = jnp.pad(data2, pad)
    N, C, H, W = a.shape
    outs = []
    for dy in range(-d, d + 1, stride2):
        for dx in range(-d, d + 1, stride2):
            shifted = jnp.roll(b, (-dy, -dx), axis=(2, 3))
            if is_multiply:
                outs.append(jnp.mean(a * shifted, axis=1))
            else:
                outs.append(jnp.mean(jnp.abs(a - shifted), axis=1))
    out = jnp.stack(outs, axis=1)
    return out[:, :, ::stride1, ::stride1]


@register_op("IdentityAttachKLSparseReg", arg_names=("data",),
             param_defaults={"sparseness_target": 0.1, "penalty": 0.001,
                             "momentum": 0.9})
def _identity_attach_kl(data, sparseness_target=0.1, penalty=0.001,
                        momentum=0.9):
    return data
