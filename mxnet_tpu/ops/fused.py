"""Fused-region operators emitted by the graph rewrite pipeline.

These ops exist so a pattern the pipeline collapses
(mxnet_tpu.graph.passes) stays ONE node in the rewritten graph — a
fused region the reference's NNVM fusion would have handed TVM as a
single generated kernel (arXiv 1802.04799).  Each op composes the
member lowerings bit-exactly where the unfused graph does the same
arithmetic, and applies the algebraic rewrite XLA's fuser cannot where
it can't:

- ``_fused_conv_bn_act`` — Convolution → BatchNorm (→ Activation).  In
  training it IS the composition (same jnp calls, bit-identical, batch
  statistics and moving-stat updates unchanged).  In eval the
  normalization folds into the convolution weights — ``w' = w·γ/√(σ²+ε)``
  per output channel, bias re-centered — an algebraic rewrite, not a
  fusion: the per-feature-map normalize work disappears instead of
  merely fusing into an epilogue.
- ``_fused_dense_act`` — FullyConnected → Activation as one node; the
  matmul contracts with ``dot_general`` directly instead of
  ``matmul(data, w.T)``, so the weight transpose never exists.
- ``_fused_layer_norm_residual`` — LayerNorm(x + r): the transformer
  sublayer epilogue as one node; on TPU it lowers to a single Pallas
  kernel (ops/pallas/layer_norm.py — one VMEM pass over the row does
  add + statistics + normalize), elsewhere to the jnp composition.
- ``_graph_constant`` — a literal produced by constant folding; holds
  the folded value out-of-band (hash/eq by content digest so CSE and
  jit caching stay sound).

The registry coverage sweep (tests/test_operator_grad_sweep.py) points
these at the equivalence-law suite (tests/test_graph_passes.py): every
fused op is tested forward AND backward against its unfused composition.
"""
from __future__ import annotations

import hashlib

import numpy as _np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op
from .nn import _activation, _batch_norm, _convolution

__all__ = ["ConstPayload", "ACT_FUSABLE"]

#: act_type values the fusion pass may fold into a fused region —
#: everything Activation supports, plus "linear" for "no activation"
ACT_FUSABLE = ("relu", "sigmoid", "tanh", "softrelu", "softsign", "gelu",
               "gelu_erf")


def _apply_act(out, act_type):
    if act_type in (None, "linear"):
        return out
    return _activation(out, act_type=act_type)


# ---------------------------------------------------------------------------
# Convolution → BatchNorm (→ Activation)
# ---------------------------------------------------------------------------

def _conv_bn_args(p):
    args = ["data", "weight"] if p.get("no_bias") else \
        ["data", "weight", "bias"]
    return args + ["gamma", "beta"]


@register_op("_fused_conv_bn_act",
             arg_names=_conv_bn_args,
             aux_names=("moving_mean", "moving_var"),
             mutate_aux=True, takes_train=True,
             param_defaults={"kernel": (), "stride": (), "dilate": (),
                             "pad": (), "num_filter": 0, "num_group": 1,
                             "no_bias": False, "workspace": 1024,
                             "cudnn_tune": None, "cudnn_off": False,
                             "layout": None,
                             "eps": 1e-3, "momentum": 0.9,
                             "fix_gamma": True, "use_global_stats": False,
                             "act_type": "linear"})
def _fused_conv_bn_act(data, weight, *rest, kernel=(), stride=(), dilate=(),
                       pad=(), num_filter=0, num_group=1, no_bias=False,
                       workspace=1024, cudnn_tune=None, cudnn_off=False,
                       layout=None, eps=1e-3, momentum=0.9, fix_gamma=True,
                       use_global_stats=False, act_type="linear",
                       _train=False):
    """Returns (out, new_moving_mean, new_moving_var) like BatchNorm."""
    if no_bias:
        bias = None
        gamma, beta, moving_mean, moving_var = rest
    else:
        bias, gamma, beta, moving_mean, moving_var = rest
    if _train and not use_global_stats:
        # training region: the literal composition — same jnp calls as
        # the unfused graph, so outputs, gradients and the moving-stat
        # updates are bit-identical
        out = _convolution(data, weight, bias, kernel=kernel, stride=stride,
                           dilate=dilate, pad=pad, num_filter=num_filter,
                           num_group=num_group, no_bias=no_bias)
        out, new_mm, new_mv = _batch_norm(
            out, gamma, beta, moving_mean, moving_var, eps=eps,
            momentum=momentum, fix_gamma=fix_gamma,
            use_global_stats=use_global_stats, _train=True)
        return _apply_act(out, act_type), new_mm, new_mv
    # eval: fold the normalization into the convolution — the algebraic
    # rewrite (scale lives on the O-sized weight axis, so the NCHW-sized
    # normalize work is gone).  Statistics math in >= fp32, matching
    # BatchNorm's stats dtype discipline.
    sdt = jnp.promote_types(weight.dtype, jnp.float32)
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    scale = g.astype(sdt) * lax.rsqrt(moving_var.astype(sdt) + eps)
    ndim = len(kernel)
    w = (weight.astype(sdt) *
         scale.reshape((-1,) + (1,) * (ndim + 1))).astype(weight.dtype)
    b = beta.astype(sdt) - moving_mean.astype(sdt) * scale
    if bias is not None:
        b = b + bias.astype(sdt) * scale
    out = _convolution(data, w, b.astype(data.dtype), kernel=kernel,
                       stride=stride, dilate=dilate, pad=pad,
                       num_filter=num_filter, num_group=num_group,
                       no_bias=False)
    return _apply_act(out, act_type), moving_mean, moving_var


# ---------------------------------------------------------------------------
# FullyConnected → Activation
# ---------------------------------------------------------------------------

@register_op("_fused_dense_act",
             arg_names=lambda p: (["data", "weight"] if p.get("no_bias")
                                  else ["data", "weight", "bias"]),
             param_defaults={"num_hidden": 0, "no_bias": False,
                             "flatten": True, "act_type": "linear"})
def _fused_dense_act(data, weight, bias=None, num_hidden=0, no_bias=False,
                     flatten=True, act_type="linear"):
    if flatten and data.ndim > 2:
        data = data.reshape((data.shape[0], -1))
    # contract data's feature dim with weight's input dim directly: the
    # (num_hidden, in_dim) weight never transposes
    out = lax.dot_general(data, weight,
                          (((data.ndim - 1,), (1,)), ((), ())))
    if bias is not None:
        out = out + bias
    return _apply_act(out, act_type)


# ---------------------------------------------------------------------------
# LayerNorm(x + r)
# ---------------------------------------------------------------------------

@register_op("_fused_layer_norm_residual",
             arg_names=("lhs", "rhs", "gamma", "beta"),
             param_defaults={"axis": -1, "eps": 1e-5})
def _fused_layer_norm_residual(lhs, rhs, gamma, beta, axis=-1, eps=1e-5):
    from ..ops.pallas import layer_norm as _ln
    # the kernel adds lhs+rhs tile-by-tile: equal shapes only (the fuse
    # matcher already restricts itself to equal-shape adds; this guard
    # keeps a hand-built node safe too)
    if lhs.shape == rhs.shape and _ln.use_pallas(lhs, axis):
        return _ln.fused_layer_norm_residual(lhs, rhs, gamma, beta, eps=eps)
    if axis not in (-1, lhs.ndim - 1):
        # non-last-axis layouts keep the plain composition
        from .nn import _layer_norm
        return _layer_norm(lhs + rhs, gamma, beta, axis=axis, eps=eps)
    # off-TPU last-axis path: the same region hand-lowered with the
    # minimum of ops (single residual+cast add, reductions via lax, no
    # reshape round-trips for gamma/beta) — numerically the LayerNorm
    # recipe (fp32 statistics), within float-reassociation tolerance of
    # the unfused chain
    s = lhs.astype(jnp.float32) + rhs.astype(jnp.float32)
    red = (s.ndim - 1,)
    n = s.shape[-1]
    mean = lax.expand_dims(
        lax.reduce(s, jnp.float32(0), lax.add, red) / n, red)
    d = s - mean
    var = lax.expand_dims(
        lax.reduce(d * d, jnp.float32(0), lax.add, red) / n, red)
    y = d * lax.rsqrt(var + eps) * gamma.astype(jnp.float32) \
        + beta.astype(jnp.float32)
    return y.astype(lhs.dtype)


# ---------------------------------------------------------------------------
# transpose-free batched contraction
# ---------------------------------------------------------------------------

@register_op("_fused_batch_dot", arg_names=("lhs", "rhs"),
             param_defaults={"transpose_a": False, "transpose_b": False})
def _fused_batch_dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """batch_dot with the transpose flags absorbed into the
    ``dot_general`` dimension numbers — the materialized ``swapaxes``
    never exists.  Same contraction order, bit-identical."""
    c_l = lhs.ndim - (2 if transpose_a else 1)
    c_r = rhs.ndim - (1 if transpose_b else 2)
    batch = tuple(range(lhs.ndim - 2))
    return lax.dot_general(lhs, rhs, ((
        (c_l,), (c_r,)), (batch, batch)))


# ---------------------------------------------------------------------------
# Folded constants
# ---------------------------------------------------------------------------

class ConstPayload:
    """Out-of-band value holder for ``_graph_constant`` params.  Hash/eq
    by content digest so two folds of identical subgraphs CSE together
    and per-param jit caches stay correct; repr stays compact so
    ``Symbol.tojson``/``debug_str`` of a rewritten graph never inlines
    megabytes of literal."""

    __slots__ = ("value", "digest")

    def __init__(self, value):
        self.value = _np.asarray(value)
        self.value.setflags(write=False)
        self.digest = hashlib.sha256(
            b"%s|%s|" % (str(self.value.dtype).encode(),
                         str(self.value.shape).encode())
            + self.value.tobytes()).hexdigest()

    def __hash__(self):
        return hash(self.digest)

    def __eq__(self, other):
        return isinstance(other, ConstPayload) and \
            self.digest == other.digest

    def __repr__(self):
        return "<const %s%s sha256:%s>" % (
            self.value.dtype, list(self.value.shape), self.digest[:12])


@register_op("_graph_constant", arg_names=(),
             param_defaults={"value": None})
def _graph_constant(value=None):
    return jnp.asarray(value.value)
