"""Tensor algebra operators.

TPU-native lowerings of the reference op families in
/root/reference/src/operator/tensor/ (~30k LoC of C++/CUDA): elementwise
unary/binary/scalar (+broadcast), broadcast/reduce, matrix manipulation
(reshape/transpose/slice/concat/...), indexing (Embedding/take/one_hot),
init ops, ordering (sort/topk/argsort), control flow (where), and linalg.

Every op is a pure jnp/lax function — XLA fuses the elementwise chains that
the reference's engine bulked by hand, and `jax.grad` supplies the backward
that each NNVM registration declared via FGradient.  Semantics (names, kwargs,
special reshape codes, MXNet-style `dot`) follow the reference's Python
surface so its scripts/tests carry over.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op, alias

# ---------------------------------------------------------------------------
# Elementwise binary (same-shape) + broadcast variants
# (/root/reference/src/operator/tensor/elemwise_binary_op.cc,
#  elemwise_binary_broadcast_op*.cc)
# ---------------------------------------------------------------------------

_BINARY = {
    "add": jnp.add,
    "sub": jnp.subtract,
    "mul": jnp.multiply,
    "div": jnp.divide,
    "mod": jnp.mod,
    "power": jnp.power,
    "maximum": jnp.maximum,
    "minimum": jnp.minimum,
    "hypot": jnp.hypot,
}

for _name, _jfn in _BINARY.items():
    def _make(fn):
        def _op(lhs, rhs):
            return fn(lhs, rhs)
        return _op
    register_op("elemwise_%s" % _name, arg_names=("lhs", "rhs"))(_make(_jfn))
    register_op("broadcast_%s" % _name, arg_names=("lhs", "rhs"))(_make(_jfn))

alias("elemwise_add", "_plus", "_add")
alias("elemwise_sub", "_minus", "_sub")
alias("elemwise_mul", "_mul")
alias("elemwise_div", "_div")
alias("elemwise_mod", "_mod")
alias("elemwise_power", "_power", "_pow")
alias("elemwise_maximum", "_maximum")
alias("elemwise_minimum", "_minimum")
alias("broadcast_add", "broadcast_plus")
alias("broadcast_sub", "broadcast_minus")
alias("broadcast_maximum", "maximum")
alias("broadcast_minimum", "minimum")
alias("broadcast_power", "power")
alias("broadcast_hypot", "hypot")

_BINARY_LOGIC = {
    "equal": jnp.equal,
    "not_equal": jnp.not_equal,
    "greater": jnp.greater,
    "greater_equal": jnp.greater_equal,
    "lesser": jnp.less,
    "lesser_equal": jnp.less_equal,
}

for _name, _jfn in _BINARY_LOGIC.items():
    def _make_logic(fn):
        def _op(lhs, rhs):
            # MXNet logic ops return same dtype as input (float 0/1)
            return fn(lhs, rhs).astype(lhs.dtype)
        return _op
    register_op("broadcast_%s" % _name, arg_names=("lhs", "rhs"))(_make_logic(_jfn))
    register_op("_%s" % _name, arg_names=("lhs", "rhs"))(_make_logic(_jfn))

# ---------------------------------------------------------------------------
# Scalar ops (/root/reference/src/operator/tensor/elemwise_binary_scalar_op*)
# ---------------------------------------------------------------------------

_SCALAR = {
    "_plus_scalar": lambda x, s: x + s,
    "_minus_scalar": lambda x, s: x - s,
    "_rminus_scalar": lambda x, s: s - x,
    "_mul_scalar": lambda x, s: x * s,
    "_div_scalar": lambda x, s: x / s,
    "_rdiv_scalar": lambda x, s: s / x,
    "_mod_scalar": lambda x, s: jnp.mod(x, s),
    "_rmod_scalar": lambda x, s: jnp.mod(jnp.asarray(s, x.dtype), x),
    "_power_scalar": lambda x, s: jnp.power(x, s),
    "_rpower_scalar": lambda x, s: jnp.power(jnp.asarray(s, x.dtype), x),
    "_maximum_scalar": lambda x, s: jnp.maximum(x, s),
    "_minimum_scalar": lambda x, s: jnp.minimum(x, s),
    "_equal_scalar": lambda x, s: (x == s).astype(x.dtype),
    "_not_equal_scalar": lambda x, s: (x != s).astype(x.dtype),
    "_greater_scalar": lambda x, s: (x > s).astype(x.dtype),
    "_greater_equal_scalar": lambda x, s: (x >= s).astype(x.dtype),
    "_lesser_scalar": lambda x, s: (x < s).astype(x.dtype),
    "_lesser_equal_scalar": lambda x, s: (x <= s).astype(x.dtype),
    "_hypot_scalar": lambda x, s: jnp.hypot(x, jnp.asarray(s, x.dtype)),
}

for _name, _jfn in _SCALAR.items():
    def _make_scalar(fn):
        def _op(data, scalar=0.0):
            return fn(data, scalar)
        return _op
    register_op(_name, arg_names=("data",),
                param_defaults={"scalar": 0.0})(_make_scalar(_jfn))

alias("_plus_scalar", "_PlusScalar")
alias("_minus_scalar", "_MinusScalar")
alias("_mul_scalar", "_MulScalar")
alias("_div_scalar", "_DivScalar")

# ---------------------------------------------------------------------------
# Elementwise unary math zoo
# (/root/reference/src/operator/tensor/elemwise_unary_op.cc + mshadow_op.h)
# ---------------------------------------------------------------------------

_UNARY = {
    "abs": jnp.abs,
    "sign": jnp.sign,
    "rint": jnp.rint,
    "ceil": jnp.ceil,
    "floor": jnp.floor,
    "trunc": jnp.trunc,
    "fix": jnp.trunc,
    "round": jnp.round,
    "square": jnp.square,
    "sqrt": jnp.sqrt,
    "rsqrt": lambda x: lax.rsqrt(x),
    "cbrt": jnp.cbrt,
    "rcbrt": lambda x: 1.0 / jnp.cbrt(x),
    "exp": jnp.exp,
    "log": jnp.log,
    "log10": jnp.log10,
    "log2": jnp.log2,
    "log1p": jnp.log1p,
    "expm1": jnp.expm1,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "arcsin": jnp.arcsin,
    "arccos": jnp.arccos,
    "arctan": jnp.arctan,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh,
    "arccosh": jnp.arccosh,
    "arctanh": jnp.arctanh,
    "degrees": jnp.degrees,
    "radians": jnp.radians,
    "sigmoid": jax.nn.sigmoid,
    "softsign": jax.nn.soft_sign,
    "relu": jax.nn.relu,
    "gamma": lambda x: jnp.exp(jax.scipy.special.gammaln(x)),
    "gammaln": jax.scipy.special.gammaln,
    "negative": jnp.negative,
    "reciprocal": jnp.reciprocal,
    "identity": lambda x: x,
    "stop_gradient": lax.stop_gradient,
    "zeros_like": jnp.zeros_like,
    "ones_like": jnp.ones_like,
}

for _name, _jfn in _UNARY.items():
    def _make_unary(fn):
        def _op(data):
            return fn(data)
        return _op
    register_op(_name, arg_names=("data",))(_make_unary(_jfn))

alias("identity", "_copy")
alias("stop_gradient", "BlockGrad")
alias("negative", "_neg")


@register_op("make_loss", arg_names=("data",),
             param_defaults={"grad_scale": 1.0, "normalization": "null",
                             "valid_thresh": 0.0})
def _make_loss(data, grad_scale=1.0, normalization="null",
               valid_thresh=0.0):
    """Identity forward whose input gradient is the CONSTANT grad_scale
    (normalized) — reference src/operator/make_loss-inl.h assigns the
    scale unconditionally in backward, ignoring any incoming out_grad.
    Implemented with jax.custom_vjp so the forward value is exactly
    `data` (no 1-ulp drift) and the cotangent is the constant even when
    the MakeLoss output feeds further computation.  grad_scale=0 blocks
    the gradient (used to expose extra outputs from training symbols)."""
    import jax

    shape = jnp.shape(data)
    dtype = jnp.result_type(data)

    @jax.custom_vjp
    def _ml(x):
        return x

    def _fwd(x):
        if normalization == "batch":
            s = grad_scale / x.shape[0]
        elif normalization == "valid":
            # reference counts data > valid_thresh (mshadow_op::threshold,
            # make_loss-inl.h:107) — signed, not abs
            cnt = jnp.maximum((x > valid_thresh).sum(), 1)
            s = grad_scale / cnt.astype(x.dtype)
        else:
            s = grad_scale
        # O(1) residual: just the scalar scale (shape/dtype via closure)
        return x, jnp.asarray(s, dtype)

    def _bwd(s, g):
        del g  # reference backward ignores out_grad entirely
        return (jnp.full(shape, s, dtype),)

    _ml.defvjp(_fwd, _bwd)
    return _ml(data)

alias("make_loss", "MakeLoss")


@register_op("Cast", arg_names=("data",), param_defaults={"dtype": "float32"})
def _cast(data, dtype="float32"):
    return data.astype(jnp.dtype(dtype))

alias("Cast", "cast")


@register_op("clip", arg_names=("data",),
             param_defaults={"a_min": 0.0, "a_max": 1.0})
def _clip(data, a_min=0.0, a_max=1.0):
    return jnp.clip(data, a_min, a_max)


# ---------------------------------------------------------------------------
# Reductions (/root/reference/src/operator/tensor/broadcast_reduce_op*.cc)
# ---------------------------------------------------------------------------

def _norm_axis(axis):
    if axis is None or axis == ():
        return None
    if isinstance(axis, int):
        return (axis,)
    return tuple(axis)


_REDUCE = {
    "sum": jnp.sum,
    "mean": jnp.mean,
    "prod": jnp.prod,
    "max": jnp.max,
    "min": jnp.min,
    "nansum": jnp.nansum,
    "nanprod": jnp.nanprod,
}

for _name, _jfn in _REDUCE.items():
    def _make_reduce(fn):
        def _op(data, axis=None, keepdims=False, exclude=False):
            ax = _norm_axis(axis)
            if exclude and ax is not None:
                ax = tuple(i for i in range(data.ndim) if i not in
                           tuple(a % data.ndim for a in ax))
            return fn(data, axis=ax, keepdims=bool(keepdims))
        return _op
    register_op(_name, arg_names=("data",),
                param_defaults={"axis": None, "keepdims": False,
                                "exclude": False})(_make_reduce(_jfn))

alias("sum", "sum_axis")
alias("max", "max_axis")
alias("min", "min_axis")


@register_op("_square_sum", arg_names=("data",),
             param_defaults={"axis": None, "keepdims": False,
                             "exclude": False})
def _square_sum(data, axis=None, keepdims=False, exclude=False):
    """sum(data**2) — the reference's fused sparse reduction
    (src/operator/tensor/square_sum*.h); dense here, XLA fuses the square
    into the reduce."""
    ax = _norm_axis(axis)
    if exclude and ax is not None:
        ax = tuple(i for i in range(data.ndim)
                   if i not in tuple(a % data.ndim for a in ax))
    return jnp.sum(data * data, axis=ax, keepdims=bool(keepdims))


@register_op("norm", arg_names=("data",),
             param_defaults={"axis": None, "keepdims": False})
def _norm(data, axis=None, keepdims=False):
    """Reference v0.11 semantics: flatten-L2 returning shape (1,)
    (broadcast_reduce_op_value.cc:226).  ``axis``/``keepdims`` are a
    forward-compatible extension (the 1.x signature)."""
    if axis is None and not keepdims:
        return jnp.sqrt(jnp.sum(jnp.square(data))).reshape((1,))
    return jnp.sqrt(jnp.sum(jnp.square(data), axis=axis,
                            keepdims=bool(keepdims)))


@register_op("argmax", arg_names=("data",),
             param_defaults={"axis": None, "keepdims": False})
def _argmax(data, axis=None, keepdims=False):
    out = jnp.argmax(data, axis=axis, keepdims=bool(keepdims))
    return out.astype(jnp.float32)


@register_op("argmin", arg_names=("data",),
             param_defaults={"axis": None, "keepdims": False})
def _argmin(data, axis=None, keepdims=False):
    return jnp.argmin(data, axis=axis, keepdims=bool(keepdims)).astype(jnp.float32)


@register_op("argmax_channel", arg_names=("data",))
def _argmax_channel(data):
    return jnp.argmax(data, axis=-1).astype(jnp.float32)


@register_op("broadcast_axis", arg_names=("data",),
             param_defaults={"axis": (), "size": ()})
def _broadcast_axis(data, axis=(), size=()):
    axes = _norm_axis(axis) or ()
    sizes = (size,) if isinstance(size, int) else tuple(size)
    shape = list(data.shape)
    for ax, s in zip(axes, sizes):
        shape[ax] = s
    return jnp.broadcast_to(data, tuple(shape))

alias("broadcast_axis", "broadcast_axes")


@register_op("broadcast_to", arg_names=("data",), param_defaults={"shape": ()})
def _broadcast_to(data, shape=()):
    target = [d if s == 0 else s for s, d in zip(shape, data.shape)]
    return jnp.broadcast_to(data, tuple(target))


# ---------------------------------------------------------------------------
# Matrix manipulation (/root/reference/src/operator/tensor/matrix_op.cc)
# ---------------------------------------------------------------------------

def _infer_reshape(data_shape, target, reverse=False):
    """MXNet reshape with special codes 0, -1, -2, -3, -4.

    Reference semantics: src/operator/tensor/matrix_op-inl.h (ReshapeParam).
    """
    target = list(target)
    src = list(data_shape)
    if reverse:
        src = src[::-1]
        # reverse the target, swapping the -4 triplets correctly is subtle;
        # MXNet reverses dims then applies, we mirror the simple cases
        target = target[::-1]
    out = []
    src_idx = 0
    i = 0
    while i < len(target):
        t = target[i]
        if t == 0:
            out.append(src[src_idx]); src_idx += 1
        elif t == -1:
            out.append(-1); src_idx += 1
        elif t == -2:
            out.extend(src[src_idx:]); src_idx = len(src)
        elif t == -3:
            out.append(src[src_idx] * src[src_idx + 1]); src_idx += 2
        elif t == -4:
            d1, d2 = target[i + 1], target[i + 2]
            cur = src[src_idx]; src_idx += 1
            if d1 == -1:
                d1 = cur // d2
            if d2 == -1:
                d2 = cur // d1
            out.extend([d1, d2]); i += 2
        else:
            out.append(int(t))
            if src_idx < len(src):
                src_idx += 1
        i += 1
    if reverse:
        out = out[::-1]
    return tuple(out)


@register_op("Reshape", arg_names=("data",),
             param_defaults={"shape": (), "reverse": False})
def _reshape(data, shape=(), reverse=False, target_shape=None, keep_highest=False):
    if target_shape:  # legacy param (pre-0.9 API)
        shape = target_shape
    new_shape = _infer_reshape(data.shape, shape, reverse=bool(reverse))
    return jnp.reshape(data, new_shape)

alias("Reshape", "reshape")


@register_op("Flatten", arg_names=("data",))
def _flatten(data):
    return jnp.reshape(data, (data.shape[0], -1))

alias("Flatten", "flatten")


@register_op("transpose", arg_names=("data",), param_defaults={"axes": ()})
def _transpose(data, axes=()):
    return jnp.transpose(data, tuple(axes) if axes else None)


@register_op("expand_dims", arg_names=("data",), param_defaults={"axis": 0})
def _expand_dims(data, axis=0):
    return jnp.expand_dims(data, axis)


@register_op("slice", arg_names=("data",),
             param_defaults={"begin": (), "end": (), "step": ()})
def _slice(data, begin=(), end=(), step=()):
    slices = []
    step = step or (None,) * len(begin)
    for i, (b, e) in enumerate(zip(begin, end)):
        s = step[i] if i < len(step) else None
        slices.append(slice(b, e, s))
    return data[tuple(slices)]

alias("slice", "crop")


@register_op("slice_axis", arg_names=("data",),
             param_defaults={"axis": 0, "begin": 0, "end": None})
def _slice_axis(data, axis=0, begin=0, end=None):
    idx = [slice(None)] * data.ndim
    idx[axis] = slice(begin, end)
    return data[tuple(idx)]


@register_op("take", arg_names=("a", "indices"),
             param_defaults={"axis": 0, "mode": "clip"})
def _take(a, indices, axis=0, mode="clip"):
    return jnp.take(a, indices.astype(jnp.int32), axis=axis,
                    mode="clip" if mode != "wrap" else "wrap")


@register_op("batch_take", arg_names=("a", "indices"))
def _batch_take(a, indices):
    return a[jnp.arange(a.shape[0]), indices.astype(jnp.int32)]


@register_op("Embedding", arg_names=("data", "weight"),
             param_defaults={"input_dim": 0, "output_dim": 0, "dtype": "float32"},
             backward_ignore=("data",))
def _embedding(data, weight, input_dim=0, output_dim=0, dtype="float32"):
    return jnp.take(weight, data.astype(jnp.int32), axis=0)


@register_op("one_hot", arg_names=("indices",),
             param_defaults={"depth": 0, "on_value": 1.0, "off_value": 0.0,
                             "dtype": "float32"})
def _one_hot(indices, depth=0, on_value=1.0, off_value=0.0, dtype="float32"):
    oh = jax.nn.one_hot(indices.astype(jnp.int32), depth, dtype=jnp.dtype(dtype))
    return oh * (on_value - off_value) + off_value


@register_op("pick", arg_names=("data", "index"),
             param_defaults={"axis": -1, "keepdims": False})
def _pick(data, index, axis=-1, keepdims=False):
    out = jnp.take_along_axis(data, jnp.expand_dims(index.astype(jnp.int32), axis),
                              axis=axis)
    return out if keepdims else jnp.squeeze(out, axis=axis)


@register_op("Concat", arg_names=lambda p: ["arg%d" % i for i in
                                            range(int(p.get("num_args", 2)))],
             param_defaults={"num_args": 2, "dim": 1})
def _concat(*args, num_args=2, dim=1):
    return jnp.concatenate(args, axis=dim)

alias("Concat", "concat")


@register_op("stack", arg_names=lambda p: ["arg%d" % i for i in
                                           range(int(p.get("num_args", 2)))],
             param_defaults={"num_args": 2, "axis": 0})
def _stack(*args, num_args=2, axis=0):
    return jnp.stack(args, axis=axis)


@register_op("SliceChannel", arg_names=("data",),
             param_defaults={"num_outputs": 1, "axis": 1, "squeeze_axis": False},
             num_outputs=lambda p: int(p.get("num_outputs", 1)))
def _slice_channel(data, num_outputs=1, axis=1, squeeze_axis=False):
    parts = jnp.split(data, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts) if num_outputs > 1 else parts[0]

alias("SliceChannel", "split")


@register_op("repeat", arg_names=("data",),
             param_defaults={"repeats": 1, "axis": None})
def _repeat(data, repeats=1, axis=None):
    return jnp.repeat(data, repeats, axis=axis)


@register_op("tile", arg_names=("data",), param_defaults={"reps": ()})
def _tile(data, reps=()):
    return jnp.tile(data, tuple(reps))


@register_op("reverse", arg_names=("data",), param_defaults={"axis": ()})
def _reverse(data, axis=()):
    return jnp.flip(data, axis=_norm_axis(axis))

alias("reverse", "flip")


@register_op("SwapAxis", arg_names=("data",),
             param_defaults={"dim1": 0, "dim2": 0})
def _swapaxis(data, dim1=0, dim2=0):
    return jnp.swapaxes(data, dim1, dim2)

alias("SwapAxis", "swapaxes")


@register_op("Pad", arg_names=("data",),
             param_defaults={"mode": "constant", "pad_width": (),
                             "constant_value": 0.0})
def _pad(data, mode="constant", pad_width=(), constant_value=0.0):
    pw = [(pad_width[2 * i], pad_width[2 * i + 1]) for i in range(data.ndim)]
    if mode == "constant":
        return jnp.pad(data, pw, mode="constant", constant_values=constant_value)
    return jnp.pad(data, pw, mode="edge" if mode == "edge" else "reflect")

alias("Pad", "pad")


@register_op("dot", arg_names=("lhs", "rhs"),
             param_defaults={"transpose_a": False, "transpose_b": False})
def _dot(lhs, rhs, transpose_a=False, transpose_b=False):
    # MXNet dot: contract last axis of lhs with first axis of rhs
    # (src/operator/tensor/dot-inl.h)
    if transpose_a:
        lhs = jnp.transpose(lhs)
    if transpose_b:
        rhs = jnp.transpose(rhs)
    if lhs.ndim == 1 and rhs.ndim == 1:
        return jnp.dot(lhs, rhs).reshape((1,))
    return jnp.tensordot(lhs, rhs, axes=([lhs.ndim - 1], [0]))


@register_op("batch_dot", arg_names=("lhs", "rhs"),
             param_defaults={"transpose_a": False, "transpose_b": False})
def _batch_dot(lhs, rhs, transpose_a=False, transpose_b=False):
    if transpose_a:
        lhs = jnp.swapaxes(lhs, -1, -2)
    if transpose_b:
        rhs = jnp.swapaxes(rhs, -1, -2)
    return jnp.matmul(lhs, rhs)


@register_op("add_n", arg_names=lambda p: ["arg%d" % i for i in
                                           range(int(p.get("num_args", 1)))],
             param_defaults={"num_args": 1})
def _add_n(*args, num_args=1):
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out

alias("add_n", "ElementWiseSum", "_sum")


# ---------------------------------------------------------------------------
# Init ops (/root/reference/src/operator/tensor/init_op.cc)
# ---------------------------------------------------------------------------

@register_op("_zeros", arg_names=(),
             param_defaults={"shape": (), "dtype": "float32"})
def _zeros(shape=(), dtype="float32"):
    return jnp.zeros(tuple(shape) if not isinstance(shape, int) else (shape,),
                     dtype=jnp.dtype(dtype or "float32"))


@register_op("_ones", arg_names=(),
             param_defaults={"shape": (), "dtype": "float32"})
def _ones(shape=(), dtype="float32"):
    return jnp.ones(tuple(shape) if not isinstance(shape, int) else (shape,),
                    dtype=jnp.dtype(dtype or "float32"))


@register_op("_full", arg_names=(),
             param_defaults={"shape": (), "value": 0.0, "dtype": "float32"})
def _full(shape=(), value=0.0, dtype="float32"):
    return jnp.full(tuple(shape) if not isinstance(shape, int) else (shape,),
                    value, dtype=jnp.dtype(dtype or "float32"))


@register_op("_arange", arg_names=(),
             param_defaults={"start": 0.0, "stop": None, "step": 1.0,
                             "repeat": 1, "dtype": "float32"})
def _arange(start=0.0, stop=None, step=1.0, repeat=1, dtype="float32"):
    out = jnp.arange(start, stop, step, dtype=jnp.dtype(dtype or "float32"))
    if repeat != 1:
        out = jnp.repeat(out, repeat)
    return out


# ---------------------------------------------------------------------------
# Ordering ops (/root/reference/src/operator/tensor/ordering_op.cc)
# ---------------------------------------------------------------------------

@register_op("sort", arg_names=("data",),
             param_defaults={"axis": -1, "is_ascend": True})
def _sort(data, axis=-1, is_ascend=True):
    out = jnp.sort(data, axis=axis)
    return out if is_ascend else jnp.flip(out, axis=axis)


@register_op("argsort", arg_names=("data",),
             param_defaults={"axis": -1, "is_ascend": True, "dtype": "float32"})
def _argsort(data, axis=-1, is_ascend=True, dtype="float32"):
    out = jnp.argsort(data, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out.astype(jnp.dtype(dtype))


@register_op("topk", arg_names=("data",),
             param_defaults={"axis": -1, "k": 1, "ret_typ": "indices",
                             "is_ascend": False, "dtype": "float32"},
             num_outputs=lambda p: 2 if p.get("ret_typ") == "both" else 1)
def _topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False,
          dtype="float32"):
    axis = axis % data.ndim
    moved = jnp.moveaxis(data, axis, -1)
    vals, idx = lax.top_k(-moved if is_ascend else moved, k)
    if is_ascend:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, axis)
    idx = jnp.moveaxis(idx, -1, axis).astype(jnp.dtype(dtype))
    if ret_typ == "value":
        return vals
    if ret_typ == "both":
        return vals, idx
    if ret_typ == "mask":
        raise NotImplementedError("topk ret_typ=mask")
    return idx


# ---------------------------------------------------------------------------
# Control flow (/root/reference/src/operator/tensor/control_flow_op.cc)
# ---------------------------------------------------------------------------

@register_op("where", arg_names=("condition", "x", "y"))
def _where(condition, x, y):
    return jnp.where(condition.astype(bool), x, y)


# ---------------------------------------------------------------------------
# Linear algebra (/root/reference/src/operator/tensor/la_op.cc)
# ---------------------------------------------------------------------------

@register_op("linalg_gemm", arg_names=("A", "B", "C"),
             param_defaults={"transpose_a": False, "transpose_b": False,
                             "alpha": 1.0, "beta": 1.0})
def _linalg_gemm(A, B, C, transpose_a=False, transpose_b=False,
                 alpha=1.0, beta=1.0):
    if transpose_a:
        A = jnp.swapaxes(A, -1, -2)
    if transpose_b:
        B = jnp.swapaxes(B, -1, -2)
    return alpha * jnp.matmul(A, B) + beta * C


@register_op("linalg_gemm2", arg_names=("A", "B"),
             param_defaults={"transpose_a": False, "transpose_b": False,
                             "alpha": 1.0})
def _linalg_gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0):
    if transpose_a:
        A = jnp.swapaxes(A, -1, -2)
    if transpose_b:
        B = jnp.swapaxes(B, -1, -2)
    return alpha * jnp.matmul(A, B)


@register_op("linalg_potrf", arg_names=("A",))
def _linalg_potrf(A):
    return jnp.linalg.cholesky(A)


@register_op("linalg_potri", arg_names=("A",))
def _linalg_potri(A):
    # inverse from Cholesky factor: inv(A A^T)
    eye = jnp.broadcast_to(jnp.eye(A.shape[-1], dtype=A.dtype), A.shape)
    inv_l = jax.scipy.linalg.solve_triangular(A, eye, lower=True)
    return jnp.matmul(jnp.swapaxes(inv_l, -1, -2), inv_l)


@register_op("linalg_trsm", arg_names=("A", "B"),
             param_defaults={"transpose": False, "rightside": False,
                             "alpha": 1.0})
def _linalg_trsm(A, B, transpose=False, rightside=False, alpha=1.0):
    if rightside:
        sol = jax.scipy.linalg.solve_triangular(
            jnp.swapaxes(A, -1, -2), jnp.swapaxes(B, -1, -2),
            lower=not transpose, trans=0)
        return alpha * jnp.swapaxes(sol, -1, -2)
    return alpha * jax.scipy.linalg.solve_triangular(
        A, B, lower=True, trans=1 if transpose else 0)


@register_op("linalg_trmm", arg_names=("A", "B"),
             param_defaults={"transpose": False, "rightside": False,
                             "alpha": 1.0})
def _linalg_trmm(A, B, transpose=False, rightside=False, alpha=1.0):
    L = jnp.tril(A)
    if transpose:
        L = jnp.swapaxes(L, -1, -2)
    return alpha * (jnp.matmul(B, L) if rightside else jnp.matmul(L, B))


@register_op("linalg_sumlogdiag", arg_names=("A",))
def _linalg_sumlogdiag(A):
    return jnp.sum(jnp.log(jnp.diagonal(A, axis1=-2, axis2=-1)), axis=-1)


@register_op("linalg_syrk", arg_names=("A",),
             param_defaults={"transpose": False, "alpha": 1.0})
def _linalg_syrk(A, transpose=False, alpha=1.0):
    At = jnp.swapaxes(A, -1, -2)
    return alpha * (jnp.matmul(At, A) if transpose else jnp.matmul(A, At))


# ---------------------------------------------------------------------------
# Legacy NDArray functions (/root/reference/src/ndarray/ndarray.cc:1208-1240,
# registered there via MXNET_REGISTER_NDARRAY_FUN rather than NNVM — the
# OPDIFF scan covers both registries)
# ---------------------------------------------------------------------------

@register_op("_set_value", arg_names=("out",),
             param_defaults={"src": 0.0})
def _set_value(out, src=0.0):
    """Fill with a scalar (ndarray.cc SetValueOp; backs ``arr[:] = x``)."""
    return jnp.full_like(out, src)


@register_op("_onehot_encode", arg_names=("indices", "out"))
def _onehot_encode_op(indices, out):
    """One-hot rows of ``out``'s shape from ``indices``
    (ndarray.cc BinaryOp<ndarray::OneHotEncode>; public
    ``mx.nd.onehot_encode``)."""
    if indices.shape[0] != out.shape[0]:
        raise ValueError(
            "onehot_encode: indices length %d != out rows %d"
            % (indices.shape[0], out.shape[0]))
    return jax.nn.one_hot(indices.astype(jnp.int32), out.shape[1],
                          dtype=out.dtype)


@register_op("choose_element_0index", arg_names=("lhs", "rhs"))
def _choose_element_0index(lhs, rhs):
    """out[i] = lhs[i, rhs[i]] (ndarray.cc MatChooseRowElem; 0-based
    index)."""
    return jnp.take_along_axis(
        lhs, rhs.astype(jnp.int32)[:, None], axis=1)[:, 0]


@register_op("fill_element_0index", arg_names=("lhs", "mhs", "rhs"))
def _fill_element_0index(lhs, mhs, rhs):
    """out = lhs with out[i, rhs[i]] = mhs[i] (ndarray.cc
    MatFillRowElem)."""
    rows = jnp.arange(lhs.shape[0])
    return lhs.at[rows, rhs.astype(jnp.int32)].set(mhs.astype(lhs.dtype))


@register_op("_copyto", arg_names=("data",))
def _copyto(data):
    """Identity copy (ndarray.cc CopyFromToSimple; device transfer is the
    ``out=`` target's placement, handled by imperative_invoke)."""
    return jnp.asarray(data)
