"""Contrib operators: SSD multibox trio, FFT, quantization, count_sketch.

TPU-native lowerings of /root/reference/src/operator/contrib/*.  The
reference implements these as hand-rolled CPU/CUDA kernels with dynamic
counts (std::vector matching loops, valid_count compaction); here every op
is a static-shape jnp/lax program — matching via masked argmax iterations,
compaction via stable argsort on validity, NMS as a fori_loop over a keep
mask — so the whole SSD head jits onto TPU.

Ops registered under both their ``_contrib_*`` and plain names, matching
the reference's dual registration.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op, alias

_NEG = -1e30


# ---------------------------------------------------------------------------
# MultiBoxPrior (reference src/operator/contrib/multibox_prior.cc:40-71)
# ---------------------------------------------------------------------------

def _multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                    steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    sizes = [float(s) for s in sizes]
    ratios = [float(r) for r in ratios]
    h, w = data.shape[2], data.shape[3]
    step_y = steps[0] if steps[0] > 0 else 1.0 / h
    step_x = steps[1] if steps[1] > 0 else 1.0 / w
    cy = (jnp.arange(h, dtype=jnp.float32) + offsets[0]) * step_y
    cx = (jnp.arange(w, dtype=jnp.float32) + offsets[1]) * step_x
    # anchors per location: all sizes at ratio 1, then ratios[1:] at sizes[0]
    half_wh = []
    for s in sizes:
        half_wh.append((s / 2.0, s / 2.0))
    for r in ratios[1:]:
        sq = math.sqrt(r)
        half_wh.append((sizes[0] * sq / 2.0, sizes[0] / sq / 2.0))
    hw = jnp.asarray(half_wh, jnp.float32)              # [K, 2] (w, h)
    cyx = jnp.stack(jnp.meshgrid(cy, cx, indexing="ij"), -1)  # [H, W, 2]
    cxy = cyx[..., ::-1]                                 # (cx, cy)
    mins = cxy[:, :, None, :] - hw[None, None, :, :]     # [H, W, K, 2]
    maxs = cxy[:, :, None, :] + hw[None, None, :, :]
    out = jnp.concatenate([mins, maxs], -1).reshape(1, -1, 4)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    return out.astype(data.dtype)


register_op("_contrib_MultiBoxPrior",
            arg_names=("data",),
            param_defaults=dict(sizes=(1.0,), ratios=(1.0,), clip=False,
                                steps=(-1.0, -1.0), offsets=(0.5, 0.5)))(_multibox_prior)
alias("_contrib_MultiBoxPrior", "MultiBoxPrior")


# ---------------------------------------------------------------------------
# IoU helpers
# ---------------------------------------------------------------------------

def _iou_matrix(a, b):
    """a [A,4], b [L,4] corner boxes → IoU [A,L]."""
    tl = jnp.maximum(a[:, None, :2], b[None, :, :2])
    br = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(br - tl, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union <= 0, 0.0, inter / jnp.maximum(union, 1e-12))


def _encode_loc(anchor, gt, variances):
    """Center-parameterised regression target (multibox_target.cc:36-54)."""
    aw = anchor[2] - anchor[0]
    ah = anchor[3] - anchor[1]
    ax = (anchor[0] + anchor[2]) * 0.5
    ay = (anchor[1] + anchor[3]) * 0.5
    gw = gt[2] - gt[0]
    gh = gt[3] - gt[1]
    gx = (gt[0] + gt[2]) * 0.5
    gy = (gt[1] + gt[3]) * 0.5
    vx, vy, vw, vh = variances
    return jnp.stack([
        (gx - ax) / aw / vx, (gy - ay) / ah / vy,
        jnp.log(jnp.maximum(gw / aw, 1e-12)) / vw,
        jnp.log(jnp.maximum(gh / ah, 1e-12)) / vh])


# ---------------------------------------------------------------------------
# MultiBoxTarget (reference multibox_target.cc:70-280)
# ---------------------------------------------------------------------------

def _multibox_target_one(anchors, labels, cls_preds, overlap_threshold,
                         ignore_label, negative_mining_ratio,
                         negative_mining_thresh, variances):
    """Single-sample matching. anchors [A,4]; labels [L,W]; cls_preds [C,A]."""
    num_anchors = anchors.shape[0]
    num_labels = labels.shape[0]

    # valid gt prefix: stops at the first class == -1 row (reference :94-103)
    valid_gt = jnp.cumprod(labels[:, 0] != -1).astype(bool)
    num_valid = valid_gt.sum()
    gt_boxes = labels[:, 1:5]
    iou = _iou_matrix(anchors, gt_boxes)                 # [A, L]
    iou_valid = jnp.where(valid_gt[None, :], iou, -1.0)

    # stage 1: greedy bipartite matching — each iteration matches the
    # globally best (anchor, gt) pair, stops when best IoU <= 1e-6
    def bip_step(_, state):
        anchor_gt, anchor_flag, gt_used = state
        masked = jnp.where(anchor_flag[:, None] == 1, _NEG, iou_valid)
        masked = jnp.where(gt_used[None, :], _NEG, masked)
        flat = masked.reshape(-1)
        best = jnp.argmax(flat)
        best_iou = flat[best]
        ba = (best // num_labels).astype(jnp.int32)
        bg = (best % num_labels).astype(jnp.int32)
        ok = best_iou > 1e-6
        anchor_gt = anchor_gt.at[ba].set(jnp.where(ok, bg, anchor_gt[ba]))
        anchor_flag = anchor_flag.at[ba].set(
            jnp.where(ok, 1, anchor_flag[ba]))
        gt_used = gt_used.at[bg].set(jnp.where(ok, True, gt_used[bg]))
        return anchor_gt, anchor_flag, gt_used

    anchor_gt = jnp.full((num_anchors,), -1, jnp.int32)
    anchor_flag = jnp.full((num_anchors,), -1, jnp.int32)  # -1 ignore, 0 neg, 1 pos
    gt_used = jnp.zeros((num_labels,), bool)
    anchor_gt, anchor_flag, gt_used = lax.fori_loop(
        0, num_labels, bip_step, (anchor_gt, anchor_flag, gt_used))

    # stage 2: threshold matching for remaining anchors (:150-178)
    best_gt = jnp.argmax(iou_valid, axis=1).astype(jnp.int32)
    best_iou = jnp.max(iou_valid, axis=1)
    has_gt = num_valid > 0
    thr_pos = (anchor_flag != 1) & (best_iou > overlap_threshold) & has_gt \
        if overlap_threshold > 0 else jnp.zeros((num_anchors,), bool)
    anchor_gt = jnp.where(thr_pos, best_gt, anchor_gt)
    anchor_flag = jnp.where(thr_pos, 1, anchor_flag)
    n_pos = (anchor_flag == 1).sum()

    if negative_mining_ratio > 0:
        # hard negative mining (:181-240): among still-unmatched anchors
        # with max IoU < mining_thresh, pick the ones with the LOWEST
        # background probability (hardest), n_neg = ratio * n_pos
        cls_t = cls_preds.T                              # [A, C]
        bg_prob = jax.nn.softmax(cls_t, axis=-1)[:, 0]
        eligible = (anchor_flag == -1) & (best_iou < negative_mining_thresh)
        n_neg = jnp.minimum(
            (n_pos * negative_mining_ratio).astype(jnp.int32),
            num_anchors - n_pos)
        score = jnp.where(eligible, -bg_prob, _NEG)      # harder = higher
        order = jnp.argsort(-score)                      # descending
        rank = jnp.zeros((num_anchors,), jnp.int32).at[order].set(
            jnp.arange(num_anchors, dtype=jnp.int32))
        make_neg = eligible & (rank < n_neg)
        anchor_flag = jnp.where(make_neg, 0, anchor_flag)
    else:
        anchor_flag = jnp.where(anchor_flag != 1, 0, anchor_flag)
    anchor_flag = jnp.where(has_gt, anchor_flag, -1)

    # targets (:249-278)
    matched_gt = jnp.clip(anchor_gt, 0, num_labels - 1)
    cls_target = jnp.where(
        anchor_flag == 1, labels[matched_gt, 0] + 1.0,
        jnp.where(anchor_flag == 0, 0.0, float(ignore_label)))
    loc = jax.vmap(_encode_loc, in_axes=(0, 0, None))(
        anchors, gt_boxes[matched_gt], tuple(variances))
    loc_mask = (anchor_flag == 1).astype(anchors.dtype)
    loc_target = jnp.where(loc_mask[:, None].astype(bool), loc, 0.0)
    loc_mask4 = jnp.repeat(loc_mask[:, None], 4, axis=1)
    return (loc_target.reshape(-1), loc_mask4.reshape(-1), cls_target)


def _multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                     ignore_label=-1.0, negative_mining_ratio=-1.0,
                     negative_mining_thresh=0.5, minimum_negative_samples=0,
                     variances=(0.1, 0.1, 0.2, 0.2)):
    """anchor (1,A,4); label (B,L,W>=5); cls_pred (B,C,A) →
    loc_target (B,4A), loc_mask (B,4A), cls_target (B,A)."""
    anchors = anchor.reshape(-1, 4)
    f = jax.vmap(lambda lb, cp: _multibox_target_one(
        anchors, lb, cp, overlap_threshold, ignore_label,
        negative_mining_ratio, negative_mining_thresh, variances))
    loc_t, loc_m, cls_t = f(label, cls_pred)
    return loc_t.astype(anchor.dtype), loc_m.astype(anchor.dtype), \
        cls_t.astype(anchor.dtype)


register_op("_contrib_MultiBoxTarget",
            arg_names=("anchor", "label", "cls_pred"), num_outputs=3,
            param_defaults=dict(overlap_threshold=0.5, ignore_label=-1.0,
                                negative_mining_ratio=-1.0,
                                negative_mining_thresh=0.5,
                                minimum_negative_samples=0,
                                variances=(0.1, 0.1, 0.2, 0.2)),
            backward_ignore=("anchor", "label", "cls_pred"))(_multibox_target)
alias("_contrib_MultiBoxTarget", "MultiBoxTarget")


# ---------------------------------------------------------------------------
# MultiBoxDetection (reference multibox_detection.cc:44-180)
# ---------------------------------------------------------------------------

def _decode_loc(anchors, loc_pred, variances, clip):
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    ax = (anchors[:, 0] + anchors[:, 2]) * 0.5
    ay = (anchors[:, 1] + anchors[:, 3]) * 0.5
    vx, vy, vw, vh = variances
    ox = loc_pred[:, 0] * vx * aw + ax
    oy = loc_pred[:, 1] * vy * ah + ay
    ow = jnp.exp(loc_pred[:, 2] * vw) * aw * 0.5
    oh = jnp.exp(loc_pred[:, 3] * vh) * ah * 0.5
    box = jnp.stack([ox - ow, oy - oh, ox + ow, oy + oh], -1)
    if clip:
        box = jnp.clip(box, 0.0, 1.0)
    return box


def _multibox_detection_one(cls_prob, loc_pred, anchors, threshold, clip,
                            variances, nms_threshold, force_suppress,
                            nms_topk):
    """cls_prob [C,A]; loc_pred [A*4]; anchors [A,4] → [A,6]."""
    num_classes, num_anchors = cls_prob.shape
    scores = cls_prob[1:, :]                             # skip background
    best = jnp.argmax(scores, axis=0)
    score = scores[best, jnp.arange(num_anchors)]
    cid = jnp.where(score >= threshold, best.astype(jnp.float32), -1.0)
    boxes = _decode_loc(anchors, loc_pred.reshape(-1, 4), variances, clip)

    valid = cid >= 0
    # compact valid rows to the front preserving anchor order (stable)
    order = jnp.argsort(~valid, stable=True)
    cid_c, score_c, boxes_c = cid[order], score[order], boxes[order]
    valid_c = valid[order]

    # sort by confidence desc among valid (reference sorts all valid;
    # nms_topk>0 keeps only the top-k in sorted positions)
    conf_order = jnp.argsort(jnp.where(valid_c, -score_c, jnp.inf),
                             stable=True)
    nkeep = num_anchors if nms_topk <= 0 else min(nms_topk, num_anchors)
    rank = jnp.arange(num_anchors)
    take = jnp.where(rank < nkeep, conf_order[jnp.minimum(rank, num_anchors - 1)],
                     rank)
    cid_s, score_s, boxes_s = cid_c[take], score_c[take], boxes_c[take]
    valid_s = valid_c[take]

    if 0 < nms_threshold <= 1:
        def body(i, keep):
            active = keep[i] & valid_s[i]
            iou = _iou_matrix(boxes_s[i][None], boxes_s)[0]
            same = force_suppress | (cid_s == cid_s[i])
            sup = active & (iou > nms_threshold) & same & \
                (jnp.arange(num_anchors) > i) & valid_s
            return keep & ~sup

        keep = lax.fori_loop(0, num_anchors, body,
                             jnp.ones((num_anchors,), bool))
    else:
        keep = jnp.ones((num_anchors,), bool)

    cid_f = jnp.where(keep & valid_s, cid_s, -1.0)
    out = jnp.concatenate(
        [cid_f[:, None],
         jnp.where(valid_s, score_s, -1.0)[:, None],
         jnp.where(valid_s[:, None], boxes_s, -1.0)], -1)
    return out


def _multibox_detection(cls_prob, loc_pred, anchor, clip=True, threshold=0.01,
                        background_id=0, nms_threshold=0.5,
                        force_suppress=False,
                        variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """cls_prob (B,C,A); loc_pred (B,A*4); anchor (1,A,4) → (B,A,6)
    rows are [class_id, score, xmin, ymin, xmax, ymax], -1 = invalid."""
    anchors = anchor.reshape(-1, 4)
    f = jax.vmap(lambda cp, lp: _multibox_detection_one(
        cp, lp, anchors, threshold, clip, tuple(variances), nms_threshold,
        force_suppress, int(nms_topk)))
    return f(cls_prob, loc_pred).astype(cls_prob.dtype)


register_op("_contrib_MultiBoxDetection",
            arg_names=("cls_prob", "loc_pred", "anchor"),
            param_defaults=dict(clip=True, threshold=0.01, background_id=0,
                                nms_threshold=0.5, force_suppress=False,
                                variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1),
            backward_ignore=("cls_prob", "loc_pred", "anchor"))(_multibox_detection)
alias("_contrib_MultiBoxDetection", "MultiBoxDetection")


# ---------------------------------------------------------------------------
# smooth_l1 (reference src/operator/mshadow_op.h smooth_l1_loss; used by SSD)
# ---------------------------------------------------------------------------

def _smooth_l1(data, scalar=1.0):
    s2 = scalar * scalar
    absd = jnp.abs(data)
    return jnp.where(absd < 1.0 / s2, 0.5 * s2 * data * data,
                     absd - 0.5 / s2)


register_op("smooth_l1",
 arg_names=("data",),
            param_defaults=dict(scalar=1.0))(_smooth_l1)


# ---------------------------------------------------------------------------
# FFT / IFFT (reference contrib/fft-inl.h: real input → interleaved
# re/im output of length 2*n on the last dim; ifft inverse, scaled by 1/n)
# ---------------------------------------------------------------------------

def _fft(data, compute_size=128):
    c = jnp.fft.fft(data.astype(jnp.float32), axis=-1)
    out = jnp.stack([c.real, c.imag], axis=-1)
    return out.reshape(*data.shape[:-1], data.shape[-1] * 2) \
        .astype(data.dtype)


def _ifft(data, compute_size=128):
    n = data.shape[-1] // 2
    pairs = data.reshape(*data.shape[:-1], n, 2).astype(jnp.float32)
    c = lax.complex(pairs[..., 0], pairs[..., 1])
    # reference ifft does NOT normalise (cuFFT inverse is unscaled)
    out = jnp.fft.ifft(c, axis=-1).real * n
    return out.astype(data.dtype)


register_op("_contrib_fft",
 arg_names=("data",),
            param_defaults=dict(compute_size=128))(_fft)
alias("_contrib_fft", "fft")
register_op("_contrib_ifft",
 arg_names=("data",),
            param_defaults=dict(compute_size=128))(_ifft)
alias("_contrib_ifft", "ifft")


# ---------------------------------------------------------------------------
# quantize / dequantize (reference contrib/quantize-inl.h: affine uint8)
# ---------------------------------------------------------------------------

def _quantize(data, min_range, max_range, out_type="uint8"):
    if out_type != "uint8":
        raise ValueError("only uint8 supported (reference quantize-inl.h)")
    qmin, qmax = 0.0, 255.0
    scale = (qmax - qmin) / (max_range - min_range)
    q = jnp.round((data - min_range) * scale + qmin)
    return (jnp.clip(q, qmin, qmax).astype(jnp.uint8), min_range, max_range)


def _dequantize(data, min_range, max_range, out_type="float32"):
    scale = (max_range - min_range) / 255.0
    return data.astype(jnp.float32) * scale + min_range


register_op("_contrib_quantize",
            arg_names=("data", "min_range", "max_range"), num_outputs=3,
            param_defaults=dict(out_type="uint8"),
            backward_ignore=("data", "min_range", "max_range"))(_quantize)
alias("_contrib_quantize", "quantize")
register_op("_contrib_dequantize",
            arg_names=("data", "min_range", "max_range"),
            param_defaults=dict(out_type="float32"),
            backward_ignore=("data", "min_range", "max_range"))(_dequantize)
alias("_contrib_dequantize", "dequantize")


# ---------------------------------------------------------------------------
# count_sketch (reference contrib/count_sketch-inl.h: random feature
# hashing h: [in_dim]→[out_dim] indices, s: ±1 signs)
# ---------------------------------------------------------------------------

def _count_sketch(data, h, s, out_dim, processing_batch_size=32):
    idx = h.reshape(-1).astype(jnp.int32)
    sign = s.reshape(-1).astype(data.dtype)
    signed = data * sign[None, :]
    out = jnp.zeros((data.shape[0], int(out_dim)), data.dtype)
    return out.at[:, idx].add(signed)


register_op("_contrib_count_sketch",
            arg_names=("data", "h", "s"),
            param_defaults=dict(out_dim=0, processing_batch_size=32),
            backward_ignore=("h", "s"))(_count_sketch)
alias("_contrib_count_sketch", "count_sketch")


# ---------------------------------------------------------------------------
# ctc_loss op (reference contrib/ctc_loss-inl.h, warp-ctc semantics:
# data (T,N,C) softmax applied internally, labels (N,L) 0-padded,
# blank = 0)
# ---------------------------------------------------------------------------

def _ctc_loss(data, label, use_data_lengths=False, use_label_lengths=False,
              blank_label="first"):
    from ..gluon.loss import _ctc_loss_jax
    logits = jnp.swapaxes(data, 0, 1)        # (T,N,C) → (N,T,C)
    lbl = label.astype(jnp.int32)
    if blank_label == "first":
        # reference contrib op: blank=0, labels are 1-based with 0 padding;
        # shift to the blank-last convention of the shared kernel
        C = data.shape[-1]
        lbl = jnp.where(lbl > 0, lbl - 1, -1)
        return _ctc_loss_jax(jnp.roll(logits, -1, axis=-1), lbl,
                             blank_last=True)
    lbl = jnp.where(lbl >= 0, lbl, -1)
    return _ctc_loss_jax(logits, lbl, blank_last=True)


register_op("_contrib_ctc_loss",
 arg_names=("data", "label"),
            param_defaults=dict(use_data_lengths=False,
                                use_label_lengths=False,
                                blank_label="first"),
            backward_ignore=("label",))(_ctc_loss)
alias("_contrib_ctc_loss", "ctc_loss")
