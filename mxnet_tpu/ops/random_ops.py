"""Random sampling operators.

TPU-native equivalents of /root/reference/src/operator/random/ — uniform,
normal, gamma, exponential, poisson, negative binomial samplers plus the
per-row ``sample_*`` family and ``sample_multinomial``.

The reference draws from a per-device PRNG resource
(ResourceRequest::kRandom); here every random op takes an explicit JAX PRNG
key as its last positional input (``needs_rng``), threaded by the caller
from ``mxnet_tpu.random``'s global seed state — functional randomness is
the TPU-native discipline (XLA-friendly, reproducible across shardings).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op, alias


def _shape(shape):
    if shape is None or shape == ():
        return ()
    if isinstance(shape, int):
        return (shape,)
    return tuple(shape)


@register_op("_random_uniform", arg_names=(), needs_rng=True,
             param_defaults={"low": 0.0, "high": 1.0, "shape": (),
                             "dtype": "float32"})
def _random_uniform(rng, low=0.0, high=1.0, shape=(), dtype="float32"):
    return jax.random.uniform(rng, _shape(shape), jnp.dtype(dtype or "float32"),
                              minval=low, maxval=high)


@register_op("_random_normal", arg_names=(), needs_rng=True,
             param_defaults={"loc": 0.0, "scale": 1.0, "shape": (),
                             "dtype": "float32"})
def _random_normal(rng, loc=0.0, scale=1.0, shape=(), dtype="float32"):
    return loc + scale * jax.random.normal(rng, _shape(shape),
                                           jnp.dtype(dtype or "float32"))


@register_op("_random_gamma", arg_names=(), needs_rng=True,
             param_defaults={"alpha": 1.0, "beta": 1.0, "shape": (),
                             "dtype": "float32"})
def _random_gamma(rng, alpha=1.0, beta=1.0, shape=(), dtype="float32"):
    return beta * jax.random.gamma(rng, alpha, _shape(shape),
                                   jnp.dtype(dtype or "float32"))


@register_op("_random_exponential", arg_names=(), needs_rng=True,
             param_defaults={"lam": 1.0, "shape": (), "dtype": "float32"})
def _random_exponential(rng, lam=1.0, shape=(), dtype="float32"):
    return jax.random.exponential(rng, _shape(shape),
                                  jnp.dtype(dtype or "float32")) / lam


@register_op("_random_poisson", arg_names=(), needs_rng=True,
             param_defaults={"lam": 1.0, "shape": (), "dtype": "float32"})
def _random_poisson(rng, lam=1.0, shape=(), dtype="float32"):
    return jax.random.poisson(rng, lam, _shape(shape)).astype(
        jnp.dtype(dtype or "float32"))


@register_op("_random_negative_binomial", arg_names=(), needs_rng=True,
             param_defaults={"k": 1, "p": 1.0, "shape": (),
                             "dtype": "float32"})
def _random_negative_binomial(rng, k=1, p=1.0, shape=(), dtype="float32"):
    # NB(k, p) = Poisson(Gamma(k, (1-p)/p))
    kg, kp = jax.random.split(rng)
    lam = jax.random.gamma(kg, k, _shape(shape)) * ((1 - p) / p)
    return jax.random.poisson(kp, lam).astype(jnp.dtype(dtype or "float32"))


@register_op("_random_generalized_negative_binomial", arg_names=(),
             needs_rng=True,
             param_defaults={"mu": 1.0, "alpha": 1.0, "shape": (),
                             "dtype": "float32"})
def _random_gnb(rng, mu=1.0, alpha=1.0, shape=(), dtype="float32"):
    kg, kp = jax.random.split(rng)
    r = 1.0 / alpha
    lam = jax.random.gamma(kg, r, _shape(shape)) * (mu * alpha)
    return jax.random.poisson(kp, lam).astype(jnp.dtype(dtype or "float32"))


alias("_random_uniform", "uniform", "random_uniform")
alias("_random_normal", "normal", "random_normal")
alias("_random_gamma", "random_gamma")
alias("_random_exponential", "random_exponential")
alias("_random_poisson", "random_poisson")
alias("_random_negative_binomial", "random_negative_binomial")


# -- per-row sample_* family (tensor distribution params) -------------------

@register_op("sample_uniform", arg_names=("low", "high"), needs_rng=True,
             param_defaults={"shape": (), "dtype": "float32"})
def _sample_uniform(low, high, rng, shape=(), dtype="float32"):
    s = _shape(shape)
    u = jax.random.uniform(rng, low.shape + s, jnp.dtype(dtype or "float32"))
    return low.reshape(low.shape + (1,) * len(s)) + \
        u * (high - low).reshape(low.shape + (1,) * len(s))


@register_op("sample_normal", arg_names=("mu", "sigma"), needs_rng=True,
             param_defaults={"shape": (), "dtype": "float32"})
def _sample_normal(mu, sigma, rng, shape=(), dtype="float32"):
    s = _shape(shape)
    n = jax.random.normal(rng, mu.shape + s, jnp.dtype(dtype or "float32"))
    return mu.reshape(mu.shape + (1,) * len(s)) + \
        n * sigma.reshape(sigma.shape + (1,) * len(s))


@register_op("sample_gamma", arg_names=("alpha", "beta"), needs_rng=True,
             param_defaults={"shape": (), "dtype": "float32"})
def _sample_gamma(alpha, beta, rng, shape=(), dtype="float32"):
    s = _shape(shape)
    a = alpha.reshape(alpha.shape + (1,) * len(s))
    g = jax.random.gamma(rng, jnp.broadcast_to(a, alpha.shape + s),
                         dtype=jnp.dtype(dtype or "float32"))
    return g * beta.reshape(beta.shape + (1,) * len(s))


@register_op("sample_exponential", arg_names=("lam",), needs_rng=True,
             param_defaults={"shape": (), "dtype": "float32"})
def _sample_exponential(lam, rng, shape=(), dtype="float32"):
    s = _shape(shape)
    e = jax.random.exponential(rng, lam.shape + s,
                               jnp.dtype(dtype or "float32"))
    return e / lam.reshape(lam.shape + (1,) * len(s))


@register_op("sample_poisson", arg_names=("lam",), needs_rng=True,
             param_defaults={"shape": (), "dtype": "float32"})
def _sample_poisson(lam, rng, shape=(), dtype="float32"):
    s = _shape(shape)
    l = jnp.broadcast_to(lam.reshape(lam.shape + (1,) * len(s)),
                         lam.shape + s)
    return jax.random.poisson(rng, l).astype(jnp.dtype(dtype or "float32"))


@register_op("sample_multinomial", arg_names=("data",), needs_rng=True,
             param_defaults={"shape": (), "get_prob": False,
                             "dtype": "int32"},
             num_outputs=lambda p: 2 if p.get("get_prob") else 1)
def _sample_multinomial(data, rng, shape=(), get_prob=False, dtype="int32"):
    # data: (..., K) probabilities (src/operator/random/multisample_op.cc)
    s = _shape(shape)
    n = 1
    for d in s:
        n *= d
    logits = jnp.log(jnp.maximum(data, 1e-20))
    flat = logits.reshape((-1, logits.shape[-1]))
    draws = jax.random.categorical(rng, flat[:, None, :], axis=-1,
                                   shape=(flat.shape[0], max(n, 1)))
    out = draws.reshape(data.shape[:-1] + (s if s else ()))
    out = out.astype(jnp.dtype(dtype or "int32"))
    if get_prob:
        logp = jnp.take_along_axis(
            jax.nn.log_softmax(flat, axis=-1), draws.astype(jnp.int32),
            axis=-1).reshape(out.shape)
        return out, logp
    return out
