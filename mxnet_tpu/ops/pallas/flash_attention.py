"""Flash attention as Pallas TPU kernels (fwd + bwd), with custom VJP.

Design (standard two-pass scheme, Dao et al., TPU grid-streamed):
- forward: grid (batch·heads, q-blocks, k-blocks) with the k axis as the
  sequential innermost dimension — Pallas pipelines each K/V block
  HBM→VMEM while the online-softmax (o, m, l) state lives in VMEM
  scratch across the sweep.  VMEM use is O(block), independent of
  sequence length (T=512k compiles the same program as T=4k); the T×T
  score matrix never exists.  Saves out + logsumexp for backward.
- backward: dq kernel (grid ..., q-blocks, k-blocks) and dk/dv kernel
  (grid ..., k-blocks, q-blocks) recompute P = exp(S - lse) blockwise on
  the MXU, accumulating into VMEM scratch the same way.
- causal masking skips fully-masked blocks via pl.when on the grid
  coordinates.

All matmuls run with preferred_element_type=float32 (MXU accumulates in
fp32 even for bf16 inputs).  Off-TPU the same kernels run under the
Pallas interpreter, so tests pass on CPU unchanged.

The 2017 reference has no attention op at all (SURVEY §5: pre-attention
era — its sequence story was bucketing); this kernel is the long-context
foundation `parallel/ring_attention.py` documents.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


def _pl():
    """Import pallas lazily: under the axon tunnel's forced-CPU test env
    the checkify import chain can fail at process level; real TPU and
    clean-CPU processes import fine."""
    from jax.experimental import pallas as pl
    return pl


def _use_interpret():
    return jax.default_backend() != "tpu"


def _causal_mask(q_off, k_off, bq, bk):
    q_pos = q_off + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = k_off + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return q_pos >= k_pos


def _kv_bounds_mask(k_off, bq, bk, tk):
    """False on K columns beyond the true sequence length (block padding
    when tk is not a multiple of block_k)."""
    k_pos = k_off + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return k_pos < tk


def _q_bounds_mask(q_off, bq, bk, tq):
    q_pos = q_off + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    return q_pos < tq


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(*refs, scale, causal, tk_true, has_seg=False):
    """One (q-block, k-block) step; the k dimension is the grid's
    innermost (sequential) axis, so K/V stream HBM->VMEM one block at a
    time — VMEM use is O(block), independent of sequence length — while
    the online-softmax state lives in VMEM scratch across the k sweep.

    With ``has_seg`` two extra int32 refs carry per-position segment
    ids (sequence packing: tokens attend within their segment only —
    the TPU-first replacement for the reference's bucketing)."""
    pl = _pl()
    if has_seg:
        (q_ref, k_ref, v_ref, qs_ref, ks_ref, o_ref, lse_ref,
         o_acc, m_acc, l_acc) = refs
    else:
        (q_ref, k_ref, v_ref, o_ref, lse_ref,
         o_acc, m_acc, l_acc) = refs
        qs_ref = ks_ref = None
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    bq = q_ref.shape[1]
    bk = k_ref.shape[1]
    q_off = qi * bq
    k_off = ki * bk

    @pl.when(ki == 0)
    def _init():
        o_acc[...] = jnp.zeros_like(o_acc)
        m_acc[...] = jnp.full_like(m_acc, _NEG_INF)
        l_acc[...] = jnp.zeros_like(l_acc)

    def _accumulate():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # (bq, bk)
        mask = _kv_bounds_mask(k_off, bq, bk, tk_true)
        if causal:
            mask &= _causal_mask(q_off, k_off, bq, bk)
        if has_seg:
            mask &= _segment_mask(qs_ref, ks_ref)
        s = jnp.where(mask, s, _NEG_INF)
        m_prev = m_acc[...]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_acc[...] = l_acc[...] * corr + p.sum(axis=-1, keepdims=True)
        o_acc[...] = o_acc[...] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_acc[...] = m_new

    if causal:
        # blocks fully above the diagonal contribute nothing; skip them
        pl.when(k_off <= q_off + bq - 1)(_accumulate)
    else:
        _accumulate()

    @pl.when(ki == nk - 1)
    def _emit():
        l_safe = jnp.maximum(l_acc[...], 1e-30)
        o_ref[0] = (o_acc[...] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = m_acc[...] + jnp.log(l_safe)


def _pad_to_val(x, axis, mult, val):
    """Pad axis up to a multiple of mult with a constant (pl.ds clamps
    out-of-range block starts, silently shifting the window — aligned
    shapes + masks keep the math exact; segment ids pad with ids that
    can never match a real segment)."""
    size = x.shape[axis]
    rem = size % mult
    if rem == 0:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, mult - rem)
    return jnp.pad(x, pad, constant_values=val)


def _pad_to(x, axis, mult):
    return _pad_to_val(x, axis, mult, 0)


def _segment_mask(qs_ref, ks_ref):
    """Packing mask: attend iff the q and k positions share a segment
    (sibling of _causal_mask; refs are (1, block) int32)."""
    return qs_ref[0][:, None] == ks_ref[0][None, :]


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, qseg=None,
               kseg=None, h=1):
    pl = _pl()
    bh, tq, d = q.shape
    tk = k.shape[1]
    dv = v.shape[2]
    block_q = min(block_q, tq)
    block_k = min(block_k, tk)
    if kseg is not None:
        kseg = _pad_to_val(kseg, 1, block_k, -1)
    if qseg is not None:
        # q itself stays unpadded: Pallas block-pads non-divisible dims
        # (interpret and Mosaic alike), so pre-padding qseg to the same
        # multiple keeps rows aligned while giving the tail a sentinel
        # id no real segment uses (tests: odd-length seg cases in
        # flash_attention_driver.check_segment_packing)
        qseg = _pad_to_val(qseg, 1, block_q, -2)
    if tk % block_k:
        # kernels mask on the padded length's tail via tk_true
        kp = _pad_to(k, 1, block_k)
        vp = _pad_to(v, 1, block_k)
        out, lse = _flash_fwd_aligned(q, kp, vp, scale, causal, block_q,
                                      block_k, tk_true=tk, qseg=qseg,
                                      kseg=kseg, h=h)
        return out, lse
    return _flash_fwd_aligned(q, k, v, scale, causal, block_q, block_k,
                              tk_true=tk, qseg=qseg, kseg=kseg, h=h)


def _scratch(shape):
    """VMEM scratch allocation (accumulators carried across the grid's
    sequential innermost dimension)."""
    from jax.experimental.pallas import tpu as pltpu
    return pltpu.VMEM(shape, jnp.float32)


def _flash_fwd_aligned(q, k, v, scale, causal, block_q, block_k, tk_true,
                       qseg=None, kseg=None, h=1):
    pl = _pl()
    bh, tq, d = q.shape
    tk = k.shape[1]
    dv = v.shape[2]
    has_seg = qseg is not None
    grid = (bh, pl.cdiv(tq, block_q), pl.cdiv(tk, block_k))
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_k, dv), lambda b, i, j: (b, j, 0)),
    ]
    operands = [q, k, v]
    if has_seg:
        # seg ids are [B, T] (not duplicated per head): grid dim 0 is
        # b*h, so the index map divides the head factor away
        in_specs += [
            pl.BlockSpec((1, block_q), lambda b, i, j: (b // h, i)),
            pl.BlockSpec((1, block_k), lambda b, i, j: (b // h, j)),
        ]
        operands += [qseg, kseg]
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          tk_true=tk_true, has_seg=has_seg),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, dv), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tq, dv), q.dtype),
            jax.ShapeDtypeStruct((bh, tq, 1), jnp.float32),
        ],
        scratch_shapes=[_scratch((block_q, dv)), _scratch((block_q, 1)),
                        _scratch((block_q, 1))],
        interpret=_use_interpret(),
    )(*operands)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_block_p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    mask, scale):
    """Shared backward block math for one (q-block, k-block) pair:
    recompute p = exp(S − lse) under ``mask`` and ds = p·(dO·Vᵀ − Δ).
    All three backward kernels (dq, dk/dv, fused) consume these; the
    explicit p zeroing handles rows whose lse is the padding sentinel
    (exp(−inf − (−inf)) would be 1)."""
    q = q_ref[0].astype(jnp.float32)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0]      # (bq, 1)
    delta = delta_ref[0]  # (bq, 1)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale  # (bq, bk)
    s = jnp.where(mask, s, _NEG_INF)
    p = jnp.exp(s - lse)
    p = jnp.where(mask, p, 0.0)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    ds = p * (dp - delta)
    return p, ds, q, k, do


def _bwd_dq_kernel(*refs, scale, causal, tk_true, has_seg=False):
    """dq for one (q-block, k-block) grid step; K/V stream via the
    sequential innermost grid axis, dq accumulates in VMEM scratch."""
    pl = _pl()
    if has_seg:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qs_ref,
         ks_ref, dq_ref, dq_acc) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dq_ref, dq_acc) = refs
        qs_ref = ks_ref = None
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    bq = q_ref.shape[1]
    bk = k_ref.shape[1]
    q_off = qi * bq
    k_off = ki * bk

    @pl.when(ki == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    def _accumulate():
        mask = _kv_bounds_mask(k_off, bq, bk, tk_true)
        if causal:
            mask &= _causal_mask(q_off, k_off, bq, bk)
        if has_seg:
            mask &= _segment_mask(qs_ref, ks_ref)
        _, ds, _, k, _ = _bwd_block_p_ds(q_ref, k_ref, v_ref, do_ref,
                                         lse_ref, delta_ref, mask, scale)
        dq_acc[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    if causal:
        pl.when(k_off <= q_off + bq - 1)(_accumulate)
    else:
        _accumulate()

    @pl.when(ki == nk - 1)
    def _emit():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, scale, causal, tq_true, has_seg=False):
    """dk/dv for one (k-block, q-block) grid step; Q/dO/lse/delta stream
    via the sequential innermost grid axis."""
    pl = _pl()
    if has_seg:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qs_ref,
         ks_ref, dk_ref, dv_ref, dk_acc, dv_acc) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
        qs_ref = ks_ref = None
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)
    bk = k_ref.shape[1]
    bq = q_ref.shape[1]
    k_off = ki * bk
    q_off = qi * bq

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def _accumulate():
        # padded q rows (tq % block_q) must contribute zero to dk/dv
        mask = _q_bounds_mask(q_off, bq, bk, tq_true)
        if causal:
            mask &= _causal_mask(q_off, k_off, bq, bk)
        if has_seg:
            mask &= _segment_mask(qs_ref, ks_ref)
        p, ds, q, _, do = _bwd_block_p_ds(q_ref, k_ref, v_ref, do_ref,
                                          lse_ref, delta_ref, mask, scale)
        # dv += P^T @ dO
        dv_acc[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_acc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    if causal:
        # a k-block sees only q rows at or below the diagonal
        pl.when(q_off + bq - 1 >= k_off)(_accumulate)
    else:
        _accumulate()

    @pl.when(qi == nq - 1)
    def _emit():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd_fused_kernel(*refs, scale, causal, tq_true, tk_true, k_base=0,
                      has_seg=False):
    """Fused backward: one grid pass (bh, k-blocks, q-blocks) computes
    dq, dk AND dv.  Per (q,k) block pair the split kernels spend 7 MXU
    matmuls (s and dp are computed twice); fusing shares them — 5
    matmuls/pair, a 1.4x FLOP cut on the backward (the PERF.md §7 gap).

    dk/dv accumulate in VMEM scratch across the sequential q sweep.  dq
    blocks would be revisited once per outer k step, NON-consecutively —
    which no TPU-grid accumulator expresses soundly (output revisits
    don't reload, and input/output aliases snapshot their input) — so
    each (k,q) step writes its dq contribution to its own fp32 partial
    slot and the caller reduces over the nk axis.  Extra HBM traffic is
    O(nk·Tq·D) written + read once, the same volume the split dq kernel
    re-read k/v with.  The caller bounds that partial buffer by chunking
    the k axis (``k_base`` is this call's absolute k offset, so the
    causal/bounds masks stay exact across chunks)."""
    pl = _pl()
    if has_seg:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qs_ref,
         ks_ref, dq_ref, dk_ref, dv_ref, dk_acc, dv_acc) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dq_ref, dk_ref, dv_ref, dk_acc, dv_acc) = refs
        qs_ref = ks_ref = None
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)
    bk = k_ref.shape[1]
    bq = q_ref.shape[1]
    k_off = k_base + ki * bk
    q_off = qi * bq

    @pl.when(qi == 0)
    def _init_kv():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    # every slot is written exactly once; fully-skipped causal pairs
    # still need their zero
    dq_ref[0, 0] = jnp.zeros_like(dq_ref[0, 0])

    def _accumulate():
        # both bounds masks: padded q rows must not touch dk/dv, padded
        # k columns must not touch dq (belt over the zero-pad brace)
        mask = _q_bounds_mask(q_off, bq, bk, tq_true)
        mask &= _kv_bounds_mask(k_off, bq, bk, tk_true)
        if causal:
            mask &= _causal_mask(q_off, k_off, bq, bk)
        if has_seg:
            mask &= _segment_mask(qs_ref, ks_ref)
        p, ds, q, k, do = _bwd_block_p_ds(q_ref, k_ref, v_ref, do_ref,
                                          lse_ref, delta_ref, mask, scale)
        dv_acc[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_acc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        dq_ref[0, 0] = jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    if causal:
        pl.when(q_off + bq - 1 >= k_off)(_accumulate)
    else:
        _accumulate()

    @pl.when(qi == nq - 1)
    def _emit():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _dq_partial_budget():
    """HBM byte cap for the fused backward's dq partial buffer
    (MXTPU_FLASH_BWD_DQ_BYTES, default in the config registry).
    Unbounded, the buffer is O(nk·B·H·Tq·D) fp32 — quadratic in T —
    which at T=32k B1 H8 D128 block 512 would be ~8.6 GB, most of a
    v5e's 16 GB HBM."""
    from mxnet_tpu import config
    return int(config.flag("MXTPU_FLASH_BWD_DQ_BYTES"))


#: Past this many k-chunks the fused path falls back to split: each
#: chunk is a separately-traced pallas_call (compile size grows with the
#: count) and re-reads all of q/do/lse/delta, eroding the shared-matmul
#: FLOP win the fusion exists for.
_MAX_DQ_CHUNKS = 16


def _flash_bwd_fused(res, g, scale, causal, block_q, block_k, h=1):
    """Single-pass fused backward; dq comes out as fp32 partials reduced
    by XLA after the kernel.  The k axis is chunked so at most
    ``MXTPU_FLASH_BWD_DQ_BYTES`` of partials exist at once: each chunk
    runs the fused kernel over its k-blocks (dk/dv for those blocks come
    out final; dq contributions are reduced and accumulated across
    chunks).  Falls back to split when even one k-block's partial slot
    exceeds the budget (no memory advantage left) or when the budget
    would need more than _MAX_DQ_CHUNKS sequential kernel launches
    (compile size and q/do re-reads erode the fusion win)."""
    pl = _pl()
    q, k, v, out, lse, qseg, kseg = _unpack_res(res)
    do = g
    bh, tq, d = q.shape
    tk = k.shape[1]
    dv_dim = v.shape[2]
    block_q = min(block_q, tq)
    block_k = min(block_k, tk)

    # regime check BEFORE any padding/delta work so the fallback path
    # computes nothing it throws away
    tqp = -(-tq // block_q) * block_q
    tkp = -(-tk // block_k) * block_k
    nk = tkp // block_k
    slot_bytes = bh * tqp * d * 4
    chunk_nk = min(nk, _dq_partial_budget() // slot_bytes)
    if chunk_nk < 1 or -(-nk // chunk_nk) > _MAX_DQ_CHUNKS:
        return _flash_bwd_split(res, g, scale, causal, block_q, block_k,
                                h=h)

    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)
    qp = _pad_to(q, 1, block_q)
    dop = _pad_to(do, 1, block_q)
    lsep = _pad_to(lse, 1, block_q)
    deltap = _pad_to(delta, 1, block_q)
    kp = _pad_to(k, 1, block_k)
    vp = _pad_to(v, 1, block_k)
    has_seg = qseg is not None
    qsegp = _pad_to_val(qseg, 1, block_q, -2) if has_seg else None
    ksegp = _pad_to_val(kseg, 1, block_k, -1) if has_seg else None

    def _fused_call(kc, vc, ksegc, nk_c, k_base):
        in_specs = [
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, dv_dim), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, dv_dim), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, j, 0)),
        ]
        operands = [qp, kc, vc, dop, lsep, deltap]
        if has_seg:
            in_specs += [
                pl.BlockSpec((1, block_q), lambda b, i, j: (b // h, j)),
                pl.BlockSpec((1, block_k), lambda b, i, j: (b // h, i)),
            ]
            operands += [qsegp, ksegc]
        return pl.pallas_call(
            functools.partial(_bwd_fused_kernel, scale=scale,
                              causal=causal, tq_true=tq, tk_true=tk,
                              k_base=k_base, has_seg=has_seg),
            grid=(bh, nk_c, tqp // block_q),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, 1, block_q, d),
                             lambda b, i, j: (i, b, j, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_k, dv_dim),
                             lambda b, i, j: (b, i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((nk_c, bh, tqp, d), jnp.float32),
                jax.ShapeDtypeStruct((bh, nk_c * block_k, d), k.dtype),
                jax.ShapeDtypeStruct((bh, nk_c * block_k, dv_dim),
                                     v.dtype),
            ],
            scratch_shapes=[_scratch((block_k, d)),
                            _scratch((block_k, dv_dim))],
            interpret=_use_interpret(),
        )(*operands)

    dq_acc = None
    dk_chunks, dv_chunks = [], []
    for start in range(0, nk, chunk_nk):
        nk_c = min(chunk_nk, nk - start)
        lo, hi = start * block_k, (start + nk_c) * block_k
        if dq_acc is not None:
            # chunk kernels share no data, so without this barrier XLA's
            # scheduler could run them concurrently and keep several
            # dq_parts buffers live at once — the byte cap must bound
            # PEAK HBM, so chunk i+1 is made to depend on chunk i's
            # reduced dq
            qp, dq_acc = lax.optimization_barrier((qp, dq_acc))
        dq_parts, dk_c, dv_c = _fused_call(
            kp[:, lo:hi], vp[:, lo:hi],
            ksegp[:, lo:hi] if has_seg else None, nk_c, k_base=lo)
        dq_c = dq_parts.sum(axis=0)
        dq_acc = dq_c if dq_acc is None else dq_acc + dq_c
        dk_chunks.append(dk_c)
        dv_chunks.append(dv_c)
    dq = dq_acc[:, :tq].astype(q.dtype)
    dk = (dk_chunks[0] if len(dk_chunks) == 1
          else jnp.concatenate(dk_chunks, axis=1))
    dv = (dv_chunks[0] if len(dv_chunks) == 1
          else jnp.concatenate(dv_chunks, axis=1))
    return dq, dk[:, :tk], dv[:, :tk]


def _bwd_impl():
    """MXTPU_FLASH_BWD=fused|split.  Default split — the measured
    round-3 baseline; tools/tpu_validate.sh times both and the faster
    one becomes the default once hardware-confirmed."""
    from mxnet_tpu import config
    return config.flag("MXTPU_FLASH_BWD")


def _flash_bwd(res, g, scale, causal, block_q, block_k, h=1):
    if _bwd_impl() == "fused":
        return _flash_bwd_fused(res, g, scale, causal, block_q, block_k,
                                h=h)
    return _flash_bwd_split(res, g, scale, causal, block_q, block_k,
                            h=h)


def _unpack_res(res):
    """(q, k, v, out, lse[, qseg, kseg]) -> 7-tuple with None segs."""
    if len(res) == 7:
        return res
    q, k, v, out, lse = res
    return q, k, v, out, lse, None, None


def _flash_bwd_split(res, g, scale, causal, block_q, block_k, h=1):
    pl = _pl()
    q, k, v, out, lse, qseg, kseg = _unpack_res(res)
    do = g
    bh, tq, d = q.shape
    tk = k.shape[1]
    dv_dim = v.shape[2]
    block_q = min(block_q, tq)
    block_k = min(block_k, tk)
    # delta_i = rowsum(dO_i * O_i)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)

    # pad every block-streamed operand to its block multiple (partial
    # final blocks would read out of range otherwise); kernels mask on
    # the true lengths, outputs are sliced back
    kp = _pad_to(k, 1, block_k)
    vp = _pad_to(v, 1, block_k)
    qp = _pad_to(q, 1, block_q)
    dop = _pad_to(do, 1, block_q)
    lsep = _pad_to(lse, 1, block_q)
    deltap = _pad_to(delta, 1, block_q)
    tkp = kp.shape[1]
    tqp = qp.shape[1]
    has_seg = qseg is not None
    qsegp = _pad_to_val(qseg, 1, block_q, -2) if has_seg else None
    ksegp = _pad_to_val(kseg, 1, block_k, -1) if has_seg else None

    dq_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_k, dv_dim), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_q, dv_dim), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
    ]
    dq_ops = [q, kp, vp, do, lse, delta]
    if has_seg:
        dq_specs += [
            pl.BlockSpec((1, block_q), lambda b, i, j: (b // h, i)),
            pl.BlockSpec((1, block_k), lambda b, i, j: (b // h, j)),
        ]
        dq_ops += [qseg, ksegp]
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          tk_true=tk, has_seg=has_seg),
        grid=(bh, pl.cdiv(tq, block_q), tkp // block_k),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[_scratch((block_q, d))],
        interpret=_use_interpret(),
    )(*dq_ops)

    dkv_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, dv_dim), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_q, dv_dim), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, j, 0)),
    ]
    dkv_ops = [qp, k, v, dop, lsep, deltap]
    if has_seg:
        dkv_specs += [
            pl.BlockSpec((1, block_q), lambda b, i, j: (b // h, j)),
            pl.BlockSpec((1, block_k), lambda b, i, j: (b // h, i)),
        ]
        dkv_ops += [qsegp, kseg]
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          tq_true=tq, has_seg=has_seg),
        grid=(bh, pl.cdiv(tk, block_k), tqp // block_q),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, dv_dim), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[_scratch((block_k, d)),
                        _scratch((block_k, dv_dim))],
        interpret=_use_interpret(),
    )(*dkv_ops)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public entry with custom VJP
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q3, k3, v3, scale, causal, block_q, block_k):
    out, _ = _flash_fwd(q3, k3, v3, scale, causal, block_q, block_k)
    return out


def _flash_vjp_fwd(q3, k3, v3, scale, causal, block_q, block_k):
    out, lse = _flash_fwd(q3, k3, v3, scale, causal, block_q, block_k)
    return out, (q3, k3, v3, out, lse)


def _flash_vjp_bwd(scale, causal, block_q, block_k, res, g):
    return _flash_bwd(res, g, scale, causal, block_q, block_k)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def _int_zero_tangent(x):
    """The cotangent custom_vjp must return for an integer primal."""
    import numpy as _np
    return _np.zeros(x.shape, jax.dtypes.float0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash_seg(q3, k3, v3, qseg, kseg, scale, causal, block_q, block_k,
               h):
    out, _ = _flash_fwd(q3, k3, v3, scale, causal, block_q, block_k,
                        qseg=qseg, kseg=kseg, h=h)
    return out


def _flash_seg_vjp_fwd(q3, k3, v3, qseg, kseg, scale, causal, block_q,
                       block_k, h):
    out, lse = _flash_fwd(q3, k3, v3, scale, causal, block_q, block_k,
                          qseg=qseg, kseg=kseg, h=h)
    return out, (q3, k3, v3, out, lse, qseg, kseg)


def _flash_seg_vjp_bwd(scale, causal, block_q, block_k, h, res, g):
    dq, dk, dv = _flash_bwd(res, g, scale, causal, block_q, block_k,
                            h=h)
    qseg, kseg = res[5], res[6]
    return dq, dk, dv, _int_zero_tangent(qseg), _int_zero_tangent(kseg)


_flash_seg.defvjp(_flash_seg_vjp_fwd, _flash_seg_vjp_bwd)


def flash_attention(q, k, v, causal=False, scale=None, block_q=512,
                    block_k=512, segment_ids=None, kv_segment_ids=None):
    """Fused attention over [B, H, T, D] tensors.

    Memory O(T) per program instead of O(T²); differentiable (flash
    backward kernels).  Off-TPU backends run the same kernels in the
    Pallas interpreter.

    ``segment_ids`` ([B, Tq] int32) enables SEQUENCE PACKING: tokens
    attend only within their own segment — multiple short documents
    share one fixed-shape row, the TPU-first replacement for the
    reference's bucketing (python/mxnet/module/bucketing_module.py).
    ``kv_segment_ids`` defaults to ``segment_ids`` (self-attention);
    give it for cross-attention over packed keys.  Use a dedicated id
    for padding tokens and they attend nothing/nobody.
    """
    b, h, tq, d = q.shape
    scale = scale if scale is not None else d ** -0.5
    q3 = q.reshape(b * h, tq, d)
    k3 = k.reshape(b * h, k.shape[2], k.shape[3])
    v3 = v.reshape(b * h, v.shape[2], v.shape[3])
    if segment_ids is None:
        if kv_segment_ids is not None:
            raise ValueError(
                "kv_segment_ids without segment_ids: packed keys need "
                "query ids too (pass segment_ids=jnp.ones for unpacked "
                "queries)")
        out = _flash(q3, k3, v3, float(scale), bool(causal),
                     int(block_q), int(block_k))
    else:
        if kv_segment_ids is None:
            kv_segment_ids = segment_ids
        qs = jnp.asarray(segment_ids, jnp.int32)
        ks = jnp.asarray(kv_segment_ids, jnp.int32)
        out = _flash_seg(q3, k3, v3, qs, ks, float(scale), bool(causal),
                         int(block_q), int(block_k), int(h))
    return out.reshape(b, h, tq, v.shape[3])


def flash_forward_with_lse(q, k, v, causal=False, scale=None, block_q=512,
                           block_k=512, segment_ids=None,
                           kv_segment_ids=None):
    """Forward-only kernel call returning (out, lse) over [B,H,T,D].

    ``lse = m + log l`` per query row — the merge quantity ring attention
    needs to combine per-block results (parallel/ring_attention.py).  Not
    differentiable; ring attention defines its own vjp around it.
    ``segment_ids``/``kv_segment_ids`` ([B, Tq]/[B, Tk] int32) apply the
    packing mask; rows with no visible key report ``lse = -inf`` so the
    ring merge weighs them zero.
    """
    b, h, tq, d = q.shape
    scale = scale if scale is not None else d ** -0.5
    q3 = q.reshape(b * h, tq, d)
    k3 = k.reshape(b * h, k.shape[2], k.shape[3])
    v3 = v.reshape(b * h, v.shape[2], v.shape[3])
    qs = ks = None
    if segment_ids is not None:
        qs = jnp.asarray(segment_ids, jnp.int32)
        ks = jnp.asarray(kv_segment_ids if kv_segment_ids is not None
                         else segment_ids, jnp.int32)
    out, lse = _flash_fwd(q3, k3, v3, float(scale), bool(causal),
                          int(block_q), int(block_k), qseg=qs, kseg=ks,
                          h=h)
    return (out.reshape(b, h, tq, v.shape[3]),
            lse.reshape(b, h, tq, 1))


def flash_attention_reference(q, k, v, causal=False, scale=None,
                              segment_ids=None, kv_segment_ids=None):
    """O(T²) jnp oracle for tests."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        tq, tk = q.shape[2], k.shape[2]
        mask = _causal_mask(0, 0, tq, tk)
        s = jnp.where(mask[None, None], s, _NEG_INF)
    if segment_ids is not None:
        if kv_segment_ids is None:
            kv_segment_ids = segment_ids
        seg = segment_ids[:, None, :, None] == \
            kv_segment_ids[:, None, None, :]
        s = jnp.where(seg, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)
