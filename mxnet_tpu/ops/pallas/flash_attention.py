"""Flash attention as Pallas TPU kernels (fwd + bwd), with custom VJP.

Design (standard two-pass scheme, Dao et al.):
- forward: grid over (batch·heads, q-blocks); each program streams K/V
  blocks through VMEM with an online-softmax (m, l) accumulator — the
  T×T score matrix never exists; saves out + logsumexp for backward.
- backward: dq kernel (grid over q-blocks) and dk/dv kernel (grid over
  k-blocks) recompute P = exp(S - lse) blockwise on the MXU.

All matmuls run with preferred_element_type=float32 (MXU accumulates in
fp32 even for bf16 inputs).  Off-TPU the same kernels run under the
Pallas interpreter, so tests pass on CPU unchanged.

The 2017 reference has no attention op at all (SURVEY §5: pre-attention
era — its sequence story was bucketing); this kernel is the long-context
foundation `parallel/ring_attention.py` documents.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


def _pl():
    """Import pallas lazily: under the axon tunnel's forced-CPU test env
    the checkify import chain can fail at process level; real TPU and
    clean-CPU processes import fine."""
    from jax.experimental import pallas as pl
    return pl


def _use_interpret():
    return jax.default_backend() != "tpu"


def _causal_mask(q_off, k_off, bq, bk):
    q_pos = q_off + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = k_off + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return q_pos >= k_pos


def _kv_bounds_mask(k_off, bq, bk, tk):
    """False on K columns beyond the true sequence length (block padding
    when tk is not a multiple of block_k)."""
    k_pos = k_off + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return k_pos < tk


def _q_bounds_mask(q_off, bq, bk, tq):
    q_pos = q_off + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    return q_pos < tq


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                block_k, q_off_base, tk_true):
    pl = _pl()
    qi = pl.program_id(1)
    bq = q_ref.shape[1]
    d = q_ref.shape[2]
    tk = k_ref.shape[1]
    nk = pl.cdiv(tk, block_k)

    q = q_ref[0].astype(jnp.float32) * scale  # (bq, d)
    q_off = q_off_base + qi * bq

    def body(step, carry):
        o, m, l = carry
        k = k_ref[0, pl.ds(step * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(step * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # (bq, bk)
        mask = _kv_bounds_mask(step * block_k, bq, block_k, tk_true)
        if causal:
            mask &= _causal_mask(q_off, step * block_k, bq, block_k)
        s = jnp.where(mask, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        o_new = o * corr + pv
        return o_new, m_new, l_new

    o0 = jnp.zeros((bq, v_ref.shape[2]), jnp.float32)
    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    o, m, l = lax.fori_loop(0, nk, body, (o0, m0, l0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (o / l_safe).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l_safe)  # (bq, 1)


def _pad_to(x, axis, mult):
    """Zero-pad axis up to a multiple of mult (pl.ds clamps out-of-range
    block starts, silently shifting the window — aligned shapes + masks
    keep the math exact)."""
    size = x.shape[axis]
    rem = size % mult
    if rem == 0:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, mult - rem)
    return jnp.pad(x, pad)


def _flash_fwd(q, k, v, scale, causal, block_q, block_k):
    pl = _pl()
    bh, tq, d = q.shape
    tk = k.shape[1]
    dv = v.shape[2]
    block_q = min(block_q, tq)
    block_k = min(block_k, tk)
    if tk % block_k:
        # kernels mask on the padded length's tail via tk_true
        kp = _pad_to(k, 1, block_k)
        vp = _pad_to(v, 1, block_k)
        out, lse = _flash_fwd_aligned(q, kp, vp, scale, causal, block_q,
                                      block_k, tk_true=tk)
        return out, lse
    return _flash_fwd_aligned(q, k, v, scale, causal, block_q, block_k,
                              tk_true=tk)


def _flash_fwd_aligned(q, k, v, scale, causal, block_q, block_k, tk_true):
    pl = _pl()
    bh, tq, d = q.shape
    tk = k.shape[1]
    dv = v.shape[2]
    grid = (bh, pl.cdiv(tq, block_q))
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          block_k=block_k, q_off_base=0, tk_true=tk_true),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, tk, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, tk, dv), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, dv), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tq, dv), q.dtype),
            jax.ShapeDtypeStruct((bh, tq, 1), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, *, scale, causal, block_k, tk_true):
    pl = _pl()
    qi = pl.program_id(1)
    bq = q_ref.shape[1]
    tk = k_ref.shape[1]
    nk = pl.cdiv(tk, block_k)

    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0]      # (bq, 1)
    delta = delta_ref[0]  # (bq, 1)
    q_off = qi * bq

    def body(step, dq):
        k = k_ref[0, pl.ds(step * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(step * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        mask = _kv_bounds_mask(step * block_k, bq, block_k, tk_true)
        if causal:
            mask &= _causal_mask(q_off, step * block_k, bq, block_k)
        s = jnp.where(mask, s, _NEG_INF)
        p = jnp.exp(s - lse)  # (bq, bk)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dq_step = jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dq + dq_step * scale

    dq = lax.fori_loop(0, nk, body,
                       jnp.zeros((bq, q_ref.shape[2]), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, scale, causal, block_q, tq_true):
    pl = _pl()
    ki = pl.program_id(1)
    bk = k_ref.shape[1]
    tq = q_ref.shape[1]
    nq = pl.cdiv(tq, block_q)

    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    k_off = ki * bk

    def body(step, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(step * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(step * block_q, block_q), :].astype(
            jnp.float32)
        lse = lse_ref[0, pl.ds(step * block_q, block_q), :]
        delta = delta_ref[0, pl.ds(step * block_q, block_q), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bq, bk)
        # padded/clamped q rows (tq % block_q: pl.ds clamps, duplicating
        # the tail rows) must contribute zero to dk/dv
        mask = _q_bounds_mask(step * block_q, block_q, bk, tq_true)
        if causal:
            mask &= _causal_mask(step * block_q, k_off, block_q, bk)
        s = jnp.where(mask, s, _NEG_INF)
        p = jnp.exp(s - lse)
        p = jnp.where(mask, p, 0.0)
        # dv += P^T @ dO
        dv_step = jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)  # (bq, bk)
        dk_step = jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk + dk_step * scale, dv + dv_step

    dk0 = jnp.zeros((bk, k_ref.shape[2]), jnp.float32)
    dv0 = jnp.zeros((bk, v_ref.shape[2]), jnp.float32)
    dk, dv = lax.fori_loop(0, nq, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd(res, g, scale, causal, block_q, block_k):
    pl = _pl()
    q, k, v, out, lse = res
    do = g
    bh, tq, d = q.shape
    tk = k.shape[1]
    dv_dim = v.shape[2]
    block_q = min(block_q, tq)
    block_k = min(block_k, tk)
    # delta_i = rowsum(dO_i * O_i)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)

    # pad every pl.ds-streamed operand to its block multiple (clamped
    # dynamic-slice starts would silently shift the window otherwise);
    # kernels mask on the true lengths, outputs are sliced back
    kp = _pad_to(k, 1, block_k)
    vp = _pad_to(v, 1, block_k)
    qp = _pad_to(q, 1, block_q)
    dop = _pad_to(do, 1, block_q)
    lsep = _pad_to(lse, 1, block_q)
    deltap = _pad_to(delta, 1, block_q)
    tkp = kp.shape[1]
    tqp = qp.shape[1]

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_k=block_k, tk_true=tk),
        grid=(bh, pl.cdiv(tq, block_q)),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, tkp, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, tkp, dv_dim), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, dv_dim), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=_use_interpret(),
    )(q, kp, vp, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, tq_true=tq),
        grid=(bh, pl.cdiv(tk, block_k)),
        in_specs=[
            pl.BlockSpec((1, tqp, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, dv_dim), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, tqp, dv_dim), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, tqp, 1), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, tqp, 1), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, dv_dim), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        interpret=_use_interpret(),
    )(qp, k, v, dop, lsep, deltap)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public entry with custom VJP
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q3, k3, v3, scale, causal, block_q, block_k):
    out, _ = _flash_fwd(q3, k3, v3, scale, causal, block_q, block_k)
    return out


def _flash_vjp_fwd(q3, k3, v3, scale, causal, block_q, block_k):
    out, lse = _flash_fwd(q3, k3, v3, scale, causal, block_q, block_k)
    return out, (q3, k3, v3, out, lse)


def _flash_vjp_bwd(scale, causal, block_q, block_k, res, g):
    return _flash_bwd(res, g, scale, causal, block_q, block_k)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, causal=False, scale=None, block_q=512,
                    block_k=512):
    """Fused attention over [B, H, T, D] tensors.

    Memory O(T) per program instead of O(T²); differentiable (flash
    backward kernels).  Off-TPU backends run the same kernels in the
    Pallas interpreter.
    """
    b, h, tq, d = q.shape
    scale = scale if scale is not None else d ** -0.5
    q3 = q.reshape(b * h, tq, d)
    k3 = k.reshape(b * h, k.shape[2], k.shape[3])
    v3 = v.reshape(b * h, v.shape[2], v.shape[3])
    out = _flash(q3, k3, v3, float(scale), bool(causal), int(block_q),
                 int(block_k))
    return out.reshape(b, h, tq, v.shape[3])


def flash_forward_with_lse(q, k, v, causal=False, scale=None, block_q=512,
                           block_k=512):
    """Forward-only kernel call returning (out, lse) over [B,H,T,D].

    ``lse = m + log l`` per query row — the merge quantity ring attention
    needs to combine per-block results (parallel/ring_attention.py).  Not
    differentiable; ring attention defines its own vjp around it.
    """
    b, h, tq, d = q.shape
    scale = scale if scale is not None else d ** -0.5
    q3 = q.reshape(b * h, tq, d)
    k3 = k.reshape(b * h, k.shape[2], k.shape[3])
    v3 = v.reshape(b * h, v.shape[2], v.shape[3])
    out, lse = _flash_fwd(q3, k3, v3, float(scale), bool(causal),
                          int(block_q), int(block_k))
    return (out.reshape(b, h, tq, v.shape[3]),
            lse.reshape(b, h, tq, 1))


def flash_attention_reference(q, k, v, causal=False, scale=None):
    """O(T²) jnp oracle for tests."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        tq, tk = q.shape[2], k.shape[2]
        mask = _causal_mask(0, 0, tq, tk)
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)
