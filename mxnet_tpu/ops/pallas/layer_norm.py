"""Fused residual-add + LayerNorm as a Pallas TPU kernel.

The transformer sublayer epilogue ``LayerNorm(x + r)`` appears twice per
block; unfused, XLA materializes the sum and runs two cross-row
reductions over separate HBM round-trips.  This kernel makes the whole
epilogue ONE VMEM pass: a row block streams HBM→VMEM once, the residual
add, mean/variance (fp32), normalize and γ/β scale all happen on the VPU
while the block is resident, and only the normalized result goes back.
The pattern-fusion graph pass (mxnet_tpu.graph.passes) emits it for the
``elemwise_add → LayerNorm`` chain alongside ``flash_attention`` /
``paged_attention`` on the Pallas path.

Grid: one dimension over row blocks (all leading axes collapsed to R
rows of D features; D is the normalized axis and must be the last).
Statistics accumulate in fp32 regardless of input dtype (the LayerNorm
op's AMP discipline) and come OUT of the kernel as extra row outputs.
Backward is a custom VJP computed with plain jnp from the saved inputs
plus those (mean, rstd) — one recomputed add, no fp32 copy of the sum
ever materializes.

Off-TPU the kernel runs under the Pallas interpreter (tests), but the
graph pass only emits the Pallas path on real TPU backends — interpret
mode would bloat the lowered HLO the pipeline exists to shrink.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["fused_layer_norm_residual", "use_pallas"]


def _pl():
    """Lazy pallas import (flash_attention.py discipline: the checkify
    import chain can fail at process level in forced-CPU test envs)."""
    from jax.experimental import pallas as pl
    return pl


def use_pallas(x, axis):
    """Should the graph-pass fused op lower through this kernel?  TPU
    backends with a last-axis norm only; MXTPU_LN_PALLAS=0 forces the
    jnp path, =1 forces the kernel (interpret mode off-TPU — tests)."""
    import os
    flag = os.environ.get("MXTPU_LN_PALLAS")
    if flag == "0":
        return False
    ok_axis = axis in (-1, x.ndim - 1)
    if flag == "1":
        return ok_axis
    return ok_axis and jax.default_backend() == "tpu"


def _ln_kernel(x_ref, r_ref, g_ref, b_ref, o_ref, m_ref, s_ref, *, eps):
    s = x_ref[...].astype(jnp.float32) + r_ref[...].astype(jnp.float32)
    mean = s.mean(axis=-1, keepdims=True)
    d = s - mean
    var = (d * d).mean(axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = d * rstd * g_ref[...].astype(jnp.float32) \
        + b_ref[...].astype(jnp.float32)
    o_ref[...] = y.astype(o_ref.dtype)
    # statistics are kernel OUTPUTS: the VJP saves (mean, rstd) instead
    # of re-deriving them with a duplicate full-tensor jnp pass
    m_ref[...] = mean
    s_ref[...] = rstd


def _rows(shape):
    r = 1
    for s in shape[:-1]:
        r *= s
    return r


def _kernel_call(x2, r2, gamma, beta, eps, interpret, block_rows=256):
    """Returns (y, mean, rstd) — the normalized rows plus the per-row
    statistics the backward needs, all from the one VMEM pass."""
    pl = _pl()
    R, D = x2.shape
    bm = min(block_rows, R)
    grid = ((R + bm - 1) // bm,)
    return pl.pallas_call(
        functools.partial(_ln_kernel, eps=eps),
        out_shape=(jax.ShapeDtypeStruct((R, D), x2.dtype),
                   jax.ShapeDtypeStruct((R, 1), jnp.float32),
                   jax.ShapeDtypeStruct((R, 1), jnp.float32)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, D), lambda i: (i, 0)),
            pl.BlockSpec((bm, D), lambda i: (i, 0)),
            pl.BlockSpec((D,), lambda i: (0,)),
            pl.BlockSpec((D,), lambda i: (0,)),
        ],
        out_specs=(pl.BlockSpec((bm, D), lambda i: (i, 0)),
                   pl.BlockSpec((bm, 1), lambda i: (i, 0)),
                   pl.BlockSpec((bm, 1), lambda i: (i, 0))),
        interpret=interpret,
    )(x2, r2, gamma, beta)


@functools.lru_cache(maxsize=None)
def _make_fused(eps, interpret):
    """One custom-VJP function per (eps, interpret) — forward through the
    kernel, backward the standard LayerNorm gradient in jnp over saved
    (s, mean, rstd)."""

    @jax.custom_vjp
    def fused(x, r, gamma, beta):
        y, _res = _fwd(x, r, gamma, beta)
        return y

    def _fwd(x, r, gamma, beta):
        shape = x.shape
        D = shape[-1]
        x2 = x.reshape((_rows(shape), D))
        r2 = r.reshape((_rows(shape), D))
        y2, mean, rstd = _kernel_call(x2, r2, gamma, beta, eps, interpret)
        # residuals: the INPUT rows (references, no new buffers) + the
        # kernel's own statistics; backward recomputes s = x+r with one
        # add instead of the forward materializing an fp32 copy
        return y2.reshape(shape), (x2, r2, mean, rstd, gamma)

    def _bwd(res, g):
        x2, r2, mean, rstd, gamma = res
        s = x2.astype(jnp.float32) + r2.astype(jnp.float32)
        # the cotangent carries the caller's shape/dtype — residuals
        # stay pure arrays (custom_vjp pytree discipline)
        g2 = g.reshape(s.shape).astype(jnp.float32)
        xhat = (s - mean) * rstd
        dgamma = (g2 * xhat).sum(axis=0).astype(gamma.dtype)
        dbeta = g2.sum(axis=0).astype(gamma.dtype)
        gg = g2 * gamma.astype(jnp.float32)
        # dL/ds for y = xhat*gamma + beta, xhat = (s - mean) * rstd
        ds = rstd * (gg - gg.mean(axis=-1, keepdims=True)
                     - xhat * (gg * xhat).mean(axis=-1, keepdims=True))
        ds = ds.reshape(g.shape).astype(g.dtype)
        return ds, ds, dgamma, dbeta

    fused.defvjp(_fwd, _bwd)
    return fused


def fused_layer_norm_residual(x, r, gamma, beta, eps=1e-5, interpret=None):
    """``LayerNorm(x + r)`` over the LAST axis as one Pallas kernel.
    ``interpret=None`` auto-selects interpreter mode off-TPU (the
    flash_attention convention)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _make_fused(float(eps), bool(interpret))(x, r, gamma, beta)
