"""Pallas TPU kernels for hot ops.

The reference's hand-written CUDA kernels (src/operator/*.cu) map to XLA
lowerings almost everywhere — XLA's fusion already covers what mshadow
kernel launches did.  The kernels here cover the cases XLA does NOT fuse
well: flash attention (online-softmax blockwise attention, the long-
context workhorse the 2017 reference predates) and ragged paged
attention (the serving runtime's block-table decode gather, SERVING.md).
"""
from .flash_attention import flash_attention, flash_attention_reference
from .paged_attention import paged_attention, paged_attention_reference

__all__ = ["flash_attention", "flash_attention_reference",
           "paged_attention", "paged_attention_reference"]
