"""Ragged paged attention as a Pallas TPU kernel (decode path).

The serving runtime (mxnet_tpu/serving/) keeps every resident sequence's
KV history in fixed-size PAGES drawn from one shared pool
(``k_pages``/``v_pages``: [num_pages, page_size, K_kv, D]) with a
per-sequence BLOCK TABLE mapping logical page index -> physical page id
— the vLLM/"Ragged Paged Attention" memory model (PAPERS.md, arXiv
2604.15464) that lets mixed-length sequences share one kernel launch
with zero padding waste beyond the last partial page.

Kernel shape (one launch serves ALL resident slots, any lengths):

- grid ``(num_slots, max_pages_per_seq)`` with the page axis as the
  sequential innermost dimension, exactly like ``flash_attention.py``'s
  k-block sweep: each step streams ONE physical K/V page HBM->VMEM
  while the online-softmax state (o, m, l) rides in VMEM scratch;
- **grouped-query attention** (ISSUE 15): the pools carry ``K_kv <= H``
  KV heads; the ``H`` query heads are processed in ``H // K_kv``-sized
  GROUPS, one 2-D matmul pair per KV head, all inside the cell — the
  one physical page fetch serves the WHOLE query group, so KV bytes
  per token shrink by ``H / K_kv`` while the FLOPs stay put.
  ``K_kv == H`` degenerates to classic multi-head (bit-identical to
  the pre-GQA kernel: same shapes, same op order); ``K_kv == 1`` is
  multi-query attention;
- the block table and per-slot context lengths arrive via scalar
  prefetch (``pltpu.PrefetchScalarGridSpec``) so the BlockSpec index
  maps can do the logical->physical page translation — the gather IS
  the pipeline's address computation, no materialized per-sequence
  contiguous KV ever exists;
- pages at or beyond a slot's context length are skipped with
  ``pl.when`` (raggedness costs control flow, not FLOPs) and the final
  in-range page is masked per position.

A slot with ``context_len == 0`` (an empty serving slot) attends to
nothing and emits zeros.  Off-TPU the same kernel runs under the Pallas
interpreter, so CPU tests exercise the identical code path.

All matmuls accumulate in fp32 (MXU ``preferred_element_type``), same
discipline as flash_attention.py.

**Quantized pages** (ISSUE 20): with ``k_scales``/``v_scales`` given
(fp32 ``[num_pages, K_kv]`` — one absmax scale per page per KV head)
the pools may hold int8 payloads; each kernel cell dequantizes its ONE
fetched page row in VMEM (``int8 * scale``) right before the score
matmul, so HBM moves a quarter of the fp32 bytes while scores, softmax
and the output accumulate in fp32 exactly as before.  The scale rows
ride the SAME block-table index map as their pages — the gather stays
the address computation.  ``k_scales is None`` is byte-for-byte the
pre-quantization kernel (same specs, same op order, same AOT keys).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .flash_attention import _use_interpret

_NEG_INF = -1e30


def _pl():
    from jax.experimental import pallas as pl
    return pl


def _scratch(shape):
    from jax.experimental.pallas import tpu as pltpu
    return pltpu.VMEM(shape, jnp.float32)


def _decode_kernel(ctx_ref, bt_ref, q_ref, k_ref, v_ref, *rest,
                   page_size, n_heads, n_kv, scale, quantized=False):
    """One (slot, page) grid step: online-softmax accumulate the
    physical page the block table routed in.  The KV-head axis is an
    UNROLLED loop of 2-D matmuls inside the cell — each KV head's
    page-row feeds its WHOLE query-head group (``g = n_heads // n_kv``
    rows of the VMEM scratch) from one fetch, so grouped-query heads
    cost no extra page bandwidth and folding heads into one cell cuts
    grid-cell overhead ``n_kv``-fold (on the interpret/CPU path that
    overhead is most of the decode step's cost).  ``ctx_ref``/
    ``bt_ref`` are the scalar-prefetched context lengths and block
    table (the index maps already consumed ``bt_ref`` for the page
    gather; only masking reads it here).  With ``quantized`` the cell
    additionally receives the page's (1, n_kv) scale rows and
    dequantizes the fetched K/V in VMEM before the fp32 matmuls."""
    if quantized:
        ks_ref, vs_ref, o_ref, o_acc, m_acc, l_acc = rest
    else:
        o_ref, o_acc, m_acc, l_acc = rest
    pl = _pl()
    s = pl.program_id(0)
    j = pl.program_id(1)
    nj = pl.num_programs(1)
    ctx = ctx_ref[s]
    g = n_heads // n_kv

    @pl.when(j == 0)
    def _init():
        o_acc[...] = jnp.zeros_like(o_acc)
        m_acc[...] = jnp.full_like(m_acc, _NEG_INF)
        l_acc[...] = jnp.zeros_like(l_acc)

    @pl.when(j * page_size < ctx)
    def _accumulate():
        # positions past the context length (the ragged tail of the
        # slot's final in-range page) contribute nothing
        pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        in_range = pos < ctx
        for kv in range(n_kv):
            grp = slice(kv * g, (kv + 1) * g)
            q = q_ref[0, grp, :].astype(jnp.float32) * scale   # (g, D)
            k = k_ref[0, :, kv, :].astype(jnp.float32)   # (page, D)
            v = v_ref[0, :, kv, :].astype(jnp.float32)   # (page, D)
            if quantized:
                k = k * ks_ref[0, kv]
                v = v * vs_ref[0, kv]
            st = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)        # (g, page)
            st = jnp.where(in_range, st, _NEG_INF)
            m_prev = m_acc[grp, :]
            m_new = jnp.maximum(m_prev, st.max(axis=-1, keepdims=True))
            p = jnp.exp(st - m_new)
            corr = jnp.exp(m_prev - m_new)
            l_acc[grp, :] = l_acc[grp, :] * corr + \
                p.sum(axis=-1, keepdims=True)
            o_acc[grp, :] = o_acc[grp, :] * corr + \
                jax.lax.dot_general(
                    p, v, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
            m_acc[grp, :] = m_new

    @pl.when(j == nj - 1)
    def _emit():
        # an empty slot (ctx == 0) never accumulated: l == 0, emit zeros
        l_safe = jnp.maximum(l_acc[...], 1e-30)
        o_ref[0] = (o_acc[...] / l_safe).astype(o_ref.dtype)


def _check_scales(k_pages, k_scales, v_scales):
    """Both scale pools or neither; shape must be [num_pages, K_kv]."""
    if (k_scales is None) != (v_scales is None):
        raise ValueError("k_scales and v_scales must be given together")
    if k_scales is None:
        return False
    want = (k_pages.shape[0], k_pages.shape[2])
    for name, s in (("k_scales", k_scales), ("v_scales", v_scales)):
        if tuple(s.shape) != want:
            raise ValueError(
                "%s must be [num_pages, K_kv] = %r, got %r"
                % (name, want, tuple(s.shape)))
    return True


def paged_attention(q, k_pages, v_pages, block_tables, context_lens,
                    scale=None, k_scales=None, v_scales=None):
    """Decode attention for every resident slot in ONE kernel launch.

    - ``q``: [S, H, D] — the current token's query per slot;
    - ``k_pages``/``v_pages``: [num_pages, page_size, K_kv, D] — the
      shared physical page pools (page 0 is the serving allocator's
      scratch page, never referenced by an in-range block-table entry).
      ``K_kv`` must divide ``H``; each KV head serves a contiguous
      group of ``H // K_kv`` query heads (GQA; ``K_kv == H`` is classic
      multi-head, ``K_kv == 1`` multi-query);
    - ``block_tables``: int32 [S, max_pages_per_seq] — logical page j of
      slot s lives in physical page ``block_tables[s, j]``;
    - ``context_lens``: int32 [S] — tokens of history per slot (0 for an
      empty slot, whose output row is zeros);
    - ``k_scales``/``v_scales``: optional fp32 [num_pages, K_kv] —
      per-page-per-KV-head dequant scales for quantized (int8) pools;
      each cell multiplies its fetched page row by its scale row in
      VMEM before the fp32 score matmul.  ``None`` (the default) is
      the identical pre-quantization kernel.

    Returns [S, H, D] in ``q``'s dtype.  Raggedness is free of FLOPs:
    pages past ``context_lens[s]`` are skipped, the final partial page
    is masked per position.
    """
    pl = _pl()
    from jax.experimental.pallas import tpu as pltpu
    s_n, h, d = q.shape
    page_size = k_pages.shape[1]
    n_kv = k_pages.shape[2]
    if h % n_kv:
        raise ValueError(
            "query heads (%d) must be a multiple of KV heads (%d)"
            % (h, n_kv))
    quantized = _check_scales(k_pages, k_scales, v_scales)
    max_pages = block_tables.shape[1]
    if scale is None:
        scale = d ** -0.5
    ctx = jnp.asarray(context_lens, jnp.int32)
    bt = jnp.asarray(block_tables, jnp.int32)

    page_spec = lambda: pl.BlockSpec(                       # noqa: E731
        (1, page_size, n_kv, d), lambda s, j, c, b: (b[s, j], 0, 0, 0))
    in_specs = [
        pl.BlockSpec((1, h, d), lambda s, j, c, b: (s, 0, 0)),
        page_spec(), page_spec(),
    ]
    args = [ctx, bt, q, k_pages, v_pages]
    if quantized:
        # the scale rows ride the SAME logical->physical translation as
        # their pages — one (1, n_kv) row per fetched page
        scale_spec = lambda: pl.BlockSpec(                  # noqa: E731
            (1, n_kv), lambda s, j, c, b: (b[s, j], 0))
        in_specs += [scale_spec(), scale_spec()]
        args += [k_scales, v_scales]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s_n, max_pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, h, d), lambda s, j, c, b: (s, 0, 0)),
        scratch_shapes=[_scratch((h, d)), _scratch((h, 1)),
                        _scratch((h, 1))],
    )
    return pl.pallas_call(
        functools.partial(_decode_kernel, page_size=page_size,
                          n_heads=h, n_kv=n_kv, scale=float(scale),
                          quantized=quantized),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s_n, h, d), q.dtype),
        interpret=_use_interpret(),
    )(*args)


def _verify_kernel(ctx_ref, bt_ref, q_ref, k_ref, v_ref, *rest,
                   page_size, n_heads, n_kv, n_q, scale,
                   quantized=False):
    """One (slot, page) grid step of the speculative-verify sweep: the
    SAME page stream as ``_decode_kernel`` but ``n_q`` query positions
    per slot, each with its OWN context length (query position ``i``
    attends through the draft token written at its position — the
    per-position causal mask of batched verification).  One physical
    page fetch serves every query position and every query-head group,
    and ALL positions accumulate in one vectorised pass — the per-page
    op count matches the single-query kernel instead of growing with
    ``n_q`` (masked positions multiply their softmax weights by zero,
    so a page past a row's context leaves that row's accumulators
    untouched, exactly as if the page had been skipped).  Positions
    with ``ctx == 0`` (inactive slot, or a query row past the slot's
    draft length) never accumulate and emit zeros.  The scratch rows
    are laid out ``[n_q * n_heads, D]`` KV-head major: row
    ``kv * n_q * g + i * g + h`` holds position ``i``, group head
    ``h`` of KV head ``kv``."""
    if quantized:
        ks_ref, vs_ref, o_ref, o_acc, m_acc, l_acc = rest
    else:
        o_ref, o_acc, m_acc, l_acc = rest
    pl = _pl()
    s = pl.program_id(0)
    j = pl.program_id(1)
    nj = pl.num_programs(1)
    g = n_heads // n_kv

    @pl.when(j == 0)
    def _init():
        o_acc[...] = jnp.zeros_like(o_acc)
        m_acc[...] = jnp.full_like(m_acc, _NEG_INF)
        l_acc[...] = jnp.zeros_like(l_acc)

    ctxv = ctx_ref[s]
    ctx_max = jnp.max(ctxv)

    @pl.when(j * page_size < ctx_max)
    def _accumulate():
        d = o_acc.shape[-1]
        pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        # [1, n_q * g, page] — broadcasts over the KV-head batch dim
        maskf = jnp.repeat(pos < ctxv[:, None], g,
                           axis=0)[None].astype(jnp.float32)
        # every KV head in ONE batched dot: q [KV, n_q * g, D] against
        # the page's k/v [page, KV, D] (batch dim 1), so the per-page
        # op count stays constant in both heads and query positions
        q = (q_ref[0].astype(jnp.float32) * scale).reshape(
            n_q, n_kv, g, d).transpose(1, 0, 2, 3).reshape(
            n_kv, n_q * g, d)
        kf = k_ref[0].astype(jnp.float32)          # (page, KV, D)
        vf = v_ref[0].astype(jnp.float32)
        if quantized:
            kf = kf * ks_ref[0][None, :, None]
            vf = vf * vs_ref[0][None, :, None]
        st = jax.lax.dot_general(
            q, kf,
            (((2,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)    # [KV, n_q * g, page]
        st = jnp.where(maskf > 0, st, _NEG_INF)
        m_prev = m_acc[...].reshape(n_kv, n_q * g, 1)
        m_new = jnp.maximum(m_prev, st.max(axis=-1, keepdims=True))
        p = jnp.exp(st - m_new) * maskf
        corr = jnp.exp(m_prev - m_new)
        l_new = l_acc[...].reshape(n_kv, n_q * g, 1) * corr + \
            p.sum(axis=-1, keepdims=True)
        o_new = o_acc[...].reshape(n_kv, n_q * g, d) * corr + \
            jax.lax.dot_general(
                p, vf,
                (((2,), (0,)), ((0,), (1,))),
                preferred_element_type=jnp.float32)
        m_acc[...] = m_new.reshape(n_kv * n_q * g, 1)
        l_acc[...] = l_new.reshape(n_kv * n_q * g, 1)
        o_acc[...] = o_new.reshape(n_kv * n_q * g, d)

    @pl.when(j == nj - 1)
    def _emit():
        l_safe = jnp.maximum(l_acc[...], 1e-30)
        d = o_acc.shape[-1]
        o_ref[0] = (o_acc[...] / l_safe).reshape(
            n_kv, n_q, g, d).transpose(1, 0, 2, 3).reshape(
            n_q, n_heads, d).astype(o_ref.dtype)


def paged_attention_multi(q, k_pages, v_pages, block_tables,
                          context_lens, scale=None, k_scales=None,
                          v_scales=None):
    """Speculative-verify attention: ``n_q`` query positions per slot in
    ONE kernel launch over the same paged pools.

    - ``q``: [S, G, H, D] — G query positions per slot (the last
      emitted token plus the draft tokens, already scattered into the
      pages this step);
    - ``context_lens``: int32 [S, G] — per-POSITION context length
      (query ``i`` of slot ``s`` attends to positions
      ``< context_lens[s, i]``; 0 masks the row to zeros — inactive
      slots and rows past the slot's draft length).

    Same grid, page stream, and per-page online softmax as
    :func:`paged_attention` — one page fetch serves all G positions —
    so ``G == 1`` with the same contexts reproduces the single-query
    kernel's op order exactly.  ``k_scales``/``v_scales`` dequantize
    the fetched page in VMEM exactly as in :func:`paged_attention`.
    Returns [S, G, H, D].
    """
    pl = _pl()
    from jax.experimental.pallas import tpu as pltpu
    s_n, n_q, h, d = q.shape
    page_size = k_pages.shape[1]
    n_kv = k_pages.shape[2]
    if h % n_kv:
        raise ValueError(
            "query heads (%d) must be a multiple of KV heads (%d)"
            % (h, n_kv))
    quantized = _check_scales(k_pages, k_scales, v_scales)
    max_pages = block_tables.shape[1]
    if scale is None:
        scale = d ** -0.5
    ctx = jnp.asarray(context_lens, jnp.int32)
    if ctx.shape != (s_n, n_q):
        raise ValueError(
            "context_lens must be [S, G] = %r, got %r"
            % ((s_n, n_q), tuple(ctx.shape)))
    bt = jnp.asarray(block_tables, jnp.int32)

    page_spec = lambda: pl.BlockSpec(                       # noqa: E731
        (1, page_size, n_kv, d), lambda s, j, c, b: (b[s, j], 0, 0, 0))
    in_specs = [
        pl.BlockSpec((1, n_q, h, d),
                     lambda s, j, c, b: (s, 0, 0, 0)),
        page_spec(), page_spec(),
    ]
    args = [ctx, bt, q, k_pages, v_pages]
    if quantized:
        scale_spec = lambda: pl.BlockSpec(                  # noqa: E731
            (1, n_kv), lambda s, j, c, b: (b[s, j], 0))
        in_specs += [scale_spec(), scale_spec()]
        args += [k_scales, v_scales]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s_n, max_pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, n_q, h, d),
                               lambda s, j, c, b: (s, 0, 0, 0)),
        scratch_shapes=[_scratch((n_q * h, d)),
                        _scratch((n_q * h, 1)),
                        _scratch((n_q * h, 1))],
    )
    return pl.pallas_call(
        functools.partial(_verify_kernel, page_size=page_size,
                          n_heads=h, n_kv=n_kv, n_q=n_q,
                          scale=float(scale), quantized=quantized),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s_n, n_q, h, d), q.dtype),
        interpret=_use_interpret(),
    )(*args)


def _dequant_pools(k_pages, v_pages, k_scales, v_scales):
    """fp32 pools for the oracles: broadcast each page's per-KV-head
    scale over its (page_size, D) payload."""
    if _check_scales(k_pages, k_scales, v_scales):
        k_pages = k_pages.astype(jnp.float32) * \
            k_scales[:, None, :, None]
        v_pages = v_pages.astype(jnp.float32) * \
            v_scales[:, None, :, None]
    return k_pages, v_pages


def paged_attention_multi_reference(q, k_pages, v_pages, block_tables,
                                    context_lens, scale=None,
                                    k_scales=None, v_scales=None):
    """jnp oracle for :func:`paged_attention_multi`: per-position dense
    masked softmax over the gathered pages; rows with ``ctx == 0``
    come back zero (the kernel's empty-row contract)."""
    s_n, n_q, h, d = q.shape
    page_size = k_pages.shape[1]
    n_kv = k_pages.shape[2]
    g = h // n_kv
    max_pages = block_tables.shape[1]
    if scale is None:
        scale = d ** -0.5
    k_pages, v_pages = _dequant_pools(k_pages, v_pages,
                                      k_scales, v_scales)
    bt = jnp.asarray(block_tables, jnp.int32)
    ctx = jnp.asarray(context_lens, jnp.int32)
    k_seq = k_pages[bt].reshape(s_n, max_pages * page_size, n_kv, d)
    v_seq = v_pages[bt].reshape(s_n, max_pages * page_size, n_kv, d)
    if g > 1:
        k_seq = jnp.repeat(k_seq, g, axis=2)
        v_seq = jnp.repeat(v_seq, g, axis=2)
    st = jnp.einsum("sihd,sthd->siht", q.astype(jnp.float32),
                    k_seq.astype(jnp.float32)) * scale
    mask = (jnp.arange(max_pages * page_size)[None, None, None, :]
            < ctx[:, :, None, None])
    st = jnp.where(mask, st, _NEG_INF)
    p = jax.nn.softmax(st, axis=-1)
    p = jnp.where(ctx[:, :, None, None] > 0, p, 0.0)
    out = jnp.einsum("siht,sthd->sihd", p, v_seq.astype(jnp.float32))
    return out.astype(q.dtype)


def paged_attention_reference(q, k_pages, v_pages, block_tables,
                              context_lens, scale=None, k_scales=None,
                              v_scales=None):
    """O(S·T) jnp oracle: gather each slot's pages contiguous, broadcast
    each KV head over its query group, dense masked softmax attention.
    Tests pin the kernel against this and against ``flash_attention``
    on the densely-packed equivalent."""
    s_n, h, d = q.shape
    page_size = k_pages.shape[1]
    n_kv = k_pages.shape[2]
    g = h // n_kv
    max_pages = block_tables.shape[1]
    if scale is None:
        scale = d ** -0.5
    k_pages, v_pages = _dequant_pools(k_pages, v_pages,
                                      k_scales, v_scales)
    bt = jnp.asarray(block_tables, jnp.int32)
    ctx = jnp.asarray(context_lens, jnp.int32)
    # [S, max_pages, page, K_kv, D] -> [S, T_max, K_kv, D]
    k_seq = k_pages[bt].reshape(s_n, max_pages * page_size, n_kv, d)
    v_seq = v_pages[bt].reshape(s_n, max_pages * page_size, n_kv, d)
    if g > 1:
        k_seq = jnp.repeat(k_seq, g, axis=2)
        v_seq = jnp.repeat(v_seq, g, axis=2)
    st = jnp.einsum("shd,sthd->sht", q.astype(jnp.float32),
                    k_seq.astype(jnp.float32)) * scale
    mask = (jnp.arange(max_pages * page_size)[None, None, :]
            < ctx[:, None, None])
    st = jnp.where(mask, st, _NEG_INF)
    p = jax.nn.softmax(st, axis=-1)
    # empty slots (ctx == 0): softmax over all -inf is uniform garbage —
    # zero those rows to match the kernel's empty-slot contract
    p = jnp.where(ctx[:, None, None] > 0, p, 0.0)
    out = jnp.einsum("sht,sthd->shd", p, v_seq.astype(jnp.float32))
    return out.astype(q.dtype)
