"""Optimizer update operators.

TPU-native equivalents of /root/reference/src/operator/optimizer_op-inl.h.
In the reference these run as graph ops so the KVStore server can execute
updates remotely (update_on_kvstore); here they are pure functions returning
the *new* (weight, states...) — the optimizer/KVStore layer writes results
back, and inside a pjit'd train step XLA turns the write-back into an
in-place donation.

Semantics match the reference exactly (rescale_grad, clip_gradient applied
before wd, update order) so convergence curves are comparable.
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from .registry import register_op


def _rescale(grad, rescale_grad, clip_gradient):
    grad = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        grad = jnp.clip(grad, -clip_gradient, clip_gradient)
    return grad


def _live_rows(grad):
    """Rows the (masked-dense row_sparse) gradient actually touches —
    the lazy-update predicate the reference evaluated over the sparse
    gradient's idx array (src/operator/optimizer_op.cc SGDUpdateRsp).
    Shares the liveness definition with RowSparseNDArray.indices."""
    from ..ndarray.sparse import live_row_mask
    return live_row_mask(grad).reshape((-1,) + (1,) * (grad.ndim - 1))


#: per-step scalars (a scheduler's lr, Adam's bias-corrected lr) are traced
#: arguments, not compile-time constants — one executable per shape, not one
#: per value (registry.OpDef.dynamic_params)
_DYN = ("lr", "wd", "rescale_grad")


@register_op("sgd_update", arg_names=("weight", "grad"),
             param_defaults={"lr": 0.01, "wd": 0.0, "rescale_grad": 1.0,
                             "clip_gradient": -1.0, "lazy_update": False},
             dynamic_params=_DYN)
def _sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                clip_gradient=-1.0, lazy_update=False):
    g = _rescale(grad, rescale_grad, clip_gradient)
    new_w = weight - lr * (g + wd * weight)
    if lazy_update:
        # rows absent from the gradient stay untouched — including their
        # weight-decay term, matching the reference's sparse sgd_update
        return jnp.where(_live_rows(grad), new_w, weight)
    return new_w


@register_op("sgd_mom_update", arg_names=("weight", "grad", "mom"),
             num_outputs=2,
             param_defaults={"lr": 0.01, "momentum": 0.0, "wd": 0.0,
                             "rescale_grad": 1.0, "clip_gradient": -1.0,
                             "lazy_update": False},
             dynamic_params=_DYN)
def _sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0, lazy_update=False):
    g = _rescale(grad, rescale_grad, clip_gradient)
    new_mom = momentum * mom - lr * (g + wd * weight)
    if lazy_update:
        live = _live_rows(grad)
        new_mom = jnp.where(live, new_mom, mom)
        return jnp.where(live, weight + new_mom, weight), new_mom
    return weight + new_mom, new_mom


@register_op("mp_sgd_update", arg_names=("weight", "grad", "weight32"),
             num_outputs=2,
             param_defaults={"lr": 0.01, "wd": 0.0, "rescale_grad": 1.0,
                             "clip_gradient": -1.0},
             dynamic_params=_DYN)
def _mp_sgd_update(weight, grad, weight32, lr=0.01, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0):
    # fp16 weights with fp32 master copy (mp_sgd_update in the reference)
    grad = _rescale(grad.astype(jnp.float32), rescale_grad, clip_gradient)
    new_w32 = weight32 - lr * (grad + wd * weight32)
    return new_w32.astype(weight.dtype), new_w32


@register_op("mp_sgd_mom_update",
             arg_names=("weight", "grad", "mom", "weight32"), num_outputs=3,
             param_defaults={"lr": 0.01, "momentum": 0.0, "wd": 0.0,
                             "rescale_grad": 1.0, "clip_gradient": -1.0},
             dynamic_params=_DYN)
def _mp_sgd_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                       wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    grad = _rescale(grad.astype(jnp.float32), rescale_grad, clip_gradient)
    new_mom = momentum * mom - lr * (grad + wd * weight32)
    new_w32 = weight32 + new_mom
    return new_w32.astype(weight.dtype), new_mom, new_w32


@register_op("adam_update", arg_names=("weight", "grad", "mean", "var"),
             num_outputs=3,
             param_defaults={"lr": 0.001, "beta1": 0.9, "beta2": 0.999,
                             "epsilon": 1e-8, "wd": 0.0, "rescale_grad": 1.0,
                             "clip_gradient": -1.0, "lazy_update": False},
             dynamic_params=_DYN)
def _adam_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                 lazy_update=False):
    g = _rescale(grad, rescale_grad, clip_gradient) + wd * weight
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    new_weight = weight - lr * new_mean / (jnp.sqrt(new_var) + epsilon)
    if lazy_update:
        # reference AdamUpdateRsp: m/v/w advance only on rows the sparse
        # gradient carries
        live = _live_rows(grad)
        return (jnp.where(live, new_weight, weight),
                jnp.where(live, new_mean, mean),
                jnp.where(live, new_var, var))
    return new_weight, new_mean, new_var


@register_op("rmsprop_update", arg_names=("weight", "grad", "n"),
             num_outputs=2,
             param_defaults={"lr": 0.001, "gamma1": 0.95, "epsilon": 1e-8,
                             "wd": 0.0, "rescale_grad": 1.0,
                             "clip_gradient": -1.0, "clip_weights": -1.0},
             dynamic_params=_DYN)
def _rmsprop_update(weight, grad, n, lr=0.001, gamma1=0.95, epsilon=1e-8,
                    wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                    clip_weights=-1.0):
    grad = _rescale(grad, rescale_grad, clip_gradient) + wd * weight
    new_n = (1 - gamma1) * jnp.square(grad) + gamma1 * n
    new_weight = weight - lr * grad / jnp.sqrt(new_n + epsilon)
    if clip_weights is not None and clip_weights > 0:
        new_weight = jnp.clip(new_weight, -clip_weights, clip_weights)
    return new_weight, new_n


@register_op("rmspropalex_update",
             arg_names=("weight", "grad", "n", "g", "delta"), num_outputs=4,
             param_defaults={"lr": 0.001, "gamma1": 0.95, "gamma2": 0.9,
                             "epsilon": 1e-8, "wd": 0.0, "rescale_grad": 1.0,
                             "clip_gradient": -1.0, "clip_weights": -1.0},
             dynamic_params=_DYN)
def _rmspropalex_update(weight, grad, n, g, delta, lr=0.001, gamma1=0.95,
                        gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                        clip_gradient=-1.0, clip_weights=-1.0):
    grad = _rescale(grad, rescale_grad, clip_gradient) + wd * weight
    new_n = (1 - gamma1) * jnp.square(grad) + gamma1 * n
    new_g = (1 - gamma1) * grad + gamma1 * g
    new_delta = gamma2 * delta - lr * grad / \
        jnp.sqrt(new_n - jnp.square(new_g) + epsilon)
    new_weight = weight + new_delta
    if clip_weights is not None and clip_weights > 0:
        new_weight = jnp.clip(new_weight, -clip_weights, clip_weights)
    return new_weight, new_n, new_g, new_delta


# -- tree-wide fused apply ---------------------------------------------------
#
# The per-op updates above dispatch one XLA kernel per parameter when called
# imperatively (the reference's server-side/kvstore shape).  The fused train
# step instead maps ONE update rule over the whole parameter pytree inside a
# single jitted program: per-parameter lr_mult/wd_mult are baked in as a
# static aux tree (they come from symbol attrs / Parameter objects and only
# change on reconfiguration, which rebuilds the program), while lr / wd /
# rescale_grad / t stay dynamic scalars so schedulers and Trainer.step's
# 1/batch_size rescale never trigger a recompile.

FUSED_KINDS = ("sgd", "sgd_mom", "adam")


def zero_stage(default=0):
    """The cross-replica weight-update sharding stage (arXiv 2004.13336 /
    ZeRO-1): 0 = replicated optimizer state, 1 = optimizer state +
    update sharded 1/N over the ``dp`` mesh axis (grads reduce-scattered,
    updated params all-gathered — still ONE donated program per step).
    Env contract: ``MXTPU_ZERO=1`` (SCALING.md)."""
    try:
        return int(os.environ.get("MXTPU_ZERO", "") or default)
    except ValueError:
        return default


def make_fused_apply(kind, mults, momentum=0.0, beta1=0.9, beta2=0.999,
                     epsilon=1e-8, clip_gradient=None, zero_shardings=None):
    """Build (init_state, apply) for a tree-wide optimizer update.

    ``kind``  — one of FUSED_KINDS.
    ``mults`` — static dict name -> (lr_mult, wd_mult).
    ``zero_shardings`` — ZeRO-1 mode: {name: NamedSharding} placing each
        param's optimizer state 1/N over the data-parallel mesh axis;
        init_state then materializes state ALREADY sharded (a replicated
        zeros tree for a billion-param model would defeat the point of
        sharding it).  The matching gradient reduce-scatter / param
        all-gather live in :func:`make_guarded_apply` — the apply body
        itself stays placement-agnostic arithmetic.

    init_state(params) -> state dict (name -> per-param state pytree)
    apply(params, grads, state, lr, wd, rescale_grad, t)
        -> (new_params, new_state); pure, jit/donation-friendly.  ``t`` is
        the 1-based update count (Adam bias correction); unused by sgd.
    """
    if kind not in FUSED_KINDS:
        raise ValueError("unsupported fused optimizer kind %r (want one of "
                         "%s)" % (kind, list(FUSED_KINDS)))
    mults = {k: (float(lm), float(wm)) for k, (lm, wm) in mults.items()}
    clip = float(clip_gradient) if clip_gradient is not None and \
        clip_gradient > 0 else None

    def _placed(name, z):
        if zero_shardings is None or name not in zero_shardings:
            return z
        # fresh buffers, not device_put: this state tree is DONATED by
        # the fused step (sharding.fresh_device_put docs)
        from ..parallel.sharding import fresh_device_put
        return fresh_device_put(z, zero_shardings[name])

    def init_state(params):
        if kind == "sgd":
            return {name: () for name in params}
        if kind == "sgd_mom":
            return {name: _placed(name, jnp.zeros_like(w))
                    for name, w in params.items()}
        return {name: (_placed(name, jnp.zeros_like(w)),
                       _placed(name, jnp.zeros_like(w)))
                for name, w in params.items()}

    def apply(params, grads, state, lr, wd, rescale_grad, t):
        if kind == "adam":
            # reference Adam bias correction folded into lr
            # (optimizer.py Adam.update); t is dynamic so consecutive
            # steps reuse the same program
            lr = lr * jnp.sqrt(1.0 - beta2 ** t) / (1.0 - beta1 ** t)
        new_params, new_state = {}, {}
        for name in params:
            w, g = params[name], grads[name]
            lm, wm = mults.get(name, (1.0, 1.0))
            p_lr, p_wd = lr * lm, wd * wm
            if kind == "sgd":
                new_params[name] = _sgd_update(
                    w, g, lr=p_lr, wd=p_wd, rescale_grad=rescale_grad,
                    clip_gradient=clip)
                new_state[name] = ()
            elif kind == "sgd_mom":
                new_params[name], new_state[name] = _sgd_mom_update(
                    w, g, state[name], lr=p_lr, momentum=momentum, wd=p_wd,
                    rescale_grad=rescale_grad, clip_gradient=clip)
            else:
                mean, var = state[name]
                new_w, new_mean, new_var = _adam_update(
                    w, g, mean, var, lr=p_lr, beta1=beta1, beta2=beta2,
                    epsilon=epsilon, wd=p_wd, rescale_grad=rescale_grad,
                    clip_gradient=clip)
                new_params[name] = new_w
                new_state[name] = (new_mean, new_var)
        return new_params, new_state

    return init_state, apply


# -- divergence guard --------------------------------------------------------
#
# The fused train step applies the optimizer inside the same XLA program as
# forward+backward; one batch producing a non-finite gradient would silently
# drive the whole parameter tree to NaN and every subsequent step would
# compound it.  The guard below folds an all-finite check on the GLOBAL
# gradient tree into that same program (still one dispatch per step): when
# any gradient leaf is NaN/Inf the update is a tree-wide no-op — params and
# optimizer state pass through unchanged — and the scalar verdict is
# returned so the host can count skips and fail loudly after K consecutive
# ones (see max_consecutive_skips / MXNetError in module.py & trainer.py).


def all_finite(tree):
    """Scalar bool: every leaf of ``tree`` is entirely finite.  One
    fused reduction chain, no host sync."""
    ok = jnp.bool_(True)
    for leaf in jax.tree_util.tree_leaves(tree):
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(leaf)))
    return ok


def make_guarded_apply(apply_fn, zero_shardings=None, param_shardings=None):
    """Wrap a tree-wide ``apply`` (from make_fused_apply) with the
    divergence guard.

    Returns ``guarded(params, grads, state, lr, wd, rescale_grad, t,
    poison) -> (new_params, new_state, ok)``: when the (poisoned) gradient
    tree contains NaN/Inf, params/state pass through unchanged and ``ok``
    is False.  ``poison`` is a dynamic scalar added to every gradient —
    0.0 in production, NaN when the ``grad.nan`` fault-injection site
    fires — so tests drive the skip path through the very same compiled
    program, with no trace divergence between guarded and injected runs.

    **ZeRO-1** (``zero_shardings`` = {name: NamedSharding} over the dp
    axis, ``param_shardings`` = each param's resident sharding, normally
    replicated): the guard becomes the cross-replica weight-update
    sharding of arXiv 2004.13336, still inside the ONE donated program —

    - gradients are constrained onto ``zero_shardings`` straight out of
      the backward pass: XLA lowers the dp gradient sum as a
      reduce-scatter instead of an all-reduce (each replica keeps only
      its 1/N slice, at half the all-reduce's bytes);
    - the all-finite verdict reduces over the SHARDED grads (each device
      scans 1/N, one tiny cross-replica AND joins the verdicts);
    - the optimizer arithmetic — and the guard's no-op select — runs on
      the 1/N shards against the sharded optimizer state;
    - only the final updated params are constrained back to
      ``param_shardings``, the one all-gather of the step.

    The skip/rollback contract is untouched: the select happens before
    the all-gather, so a non-finite batch republishes the OLD param
    shards and the gathered result is bit-identical to never updating.
    """
    def _wsc(tree, shardings):
        return {name: jax.lax.with_sharding_constraint(v, shardings[name])
                for name, v in tree.items()} if shardings else tree

    def guarded(params, grads, state, lr, wd, rescale_grad, t, poison):
        grads = {name: g + poison for name, g in grads.items()}
        grads = _wsc(grads, zero_shardings)  # dp grad sum → reduce-scatter
        ok = all_finite(grads)
        new_params, new_state = apply_fn(params, grads, state, lr, wd,
                                         rescale_grad, t)
        new_params = _wsc(new_params, zero_shardings)  # 1/N update compute
        new_params = jax.tree_util.tree_map(
            lambda n, o: jnp.where(ok, n, o), new_params, params)
        new_state = jax.tree_util.tree_map(
            lambda n, o: jnp.where(ok, n, o), new_state, state)
        if zero_shardings:
            new_params = _wsc(new_params, param_shardings)  # all-gather
        return new_params, new_state, ok

    return guarded


def max_consecutive_skips():
    """K in the graceful-degradation contract: after K consecutive
    guard-skipped steps the training loop raises MXNetError instead of
    silently looping on a permanently-divergent configuration.
    Overridable per-run via MXTPU_MAX_CONSECUTIVE_SKIPS."""
    return int(os.environ.get("MXTPU_MAX_CONSECUTIVE_SKIPS", "100"))


def raise_skip_limit_error(limit):
    from ..base import MXNetError
    raise MXNetError(
        "divergence guard: %d consecutive steps produced non-finite "
        "gradients — training cannot progress (lower the learning "
        "rate, check the data pipeline, or raise "
        "MXTPU_MAX_CONSECUTIVE_SKIPS)" % limit)


def handle_guard_verdict(ok, optimizer, indices, streak, pre_num_update,
                         raise_on_limit=True, backfill_verdict=False):
    """Host-side bookkeeping shared by Module.fit_step and
    gluon.Trainer._fused_step after the guarded program returns.

    On a skipped step the optimizer clock is rewound so the batch is
    indistinguishable from one that never arrived: ``_index_update_count``
    (Adam's t) for every updated index and ``num_update`` (the lr
    scheduler's clock, captured by the caller BEFORE its _update_count
    calls) both roll back.  Returns the new consecutive-skip streak;
    with ``raise_on_limit`` it raises MXNetError at
    max_consecutive_skips().  The Trainer resolves verdicts from its
    save/flush paths with ``raise_on_limit=False`` — a checkpoint write
    must never be aborted by a training-health error — and re-checks the
    limit at the top of the next step() instead.
    """
    ok_host = bool(ok)
    if backfill_verdict:
        # flight recorder: the Trainer records its step with a pending
        # (None) verdict before this resolves one step late; back-fill
        # both ways — ok steps become False-skipped, diverged True.
        # Module.fit_step records the verdict inline instead (marking
        # here would force a flight-ring drain on every step).
        from .. import telemetry as _telemetry
        _telemetry.mark_last_step_verdict(ok_host)
    if ok_host:
        return 0
    from .. import profiler as _profiler
    for i in indices:
        optimizer._index_update_count[i] -= 1
    optimizer.num_update = pre_num_update
    _profiler.note_skipped_step()
    streak += 1
    limit = max_consecutive_skips()
    if raise_on_limit and streak >= limit:
        raise_skip_limit_error(limit)
    return streak


@register_op("ftrl_update", arg_names=("weight", "grad", "z", "n"),
             num_outputs=3,
             param_defaults={"lr": 0.1, "lamda1": 0.01, "beta": 1.0,
                             "wd": 0.0, "rescale_grad": 1.0,
                             "clip_gradient": -1.0},
             dynamic_params=_DYN)
def _ftrl_update(weight, grad, z, n, lr=0.1, lamda1=0.01, beta=1.0, wd=0.0,
                 rescale_grad=1.0, clip_gradient=-1.0):
    grad = _rescale(grad, rescale_grad, clip_gradient)
    new_n = n + jnp.square(grad)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + grad - sigma * weight
    new_weight = jnp.where(
        jnp.abs(new_z) <= lamda1, jnp.zeros_like(weight),
        -(new_z - jnp.sign(new_z) * lamda1) /
        ((beta + jnp.sqrt(new_n)) / lr + wd))
    return new_weight, new_z, new_n


@register_op("adamax_update", arg_names=("weight", "grad", "m", "u"),
             num_outputs=3,
             param_defaults={"lr": 0.002, "beta1": 0.9, "beta2": 0.999,
                             "wd": 0.0, "rescale_grad": 1.0,
                             "clip_gradient": -1.0},
             dynamic_params=_DYN)
def _adamax_update(weight, grad, m, u, lr=0.002, beta1=0.9, beta2=0.999,
                   wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    # ``lr`` arrives bias-corrected (lr / (1 - beta1^t)) from the host,
    # like adam_update's — reference optimizer.py:927 AdaMax
    g = _rescale(grad, rescale_grad, clip_gradient) + wd * weight
    new_m = beta1 * m + (1.0 - beta1) * g
    new_u = jnp.maximum(beta2 * u, jnp.abs(g))
    return weight - lr * new_m / new_u, new_m, new_u


@register_op("nadam_update", arg_names=("weight", "grad", "m", "v"),
             num_outputs=3,
             param_defaults={"lr": 0.001, "beta1": 0.9, "beta2": 0.999,
                             "epsilon": 1e-8, "wd": 0.0, "rescale_grad": 1.0,
                             "clip_gradient": -1.0, "momentum_t": 0.9,
                             "momentum_t_1": 0.9, "m_schedule": 0.9,
                             "m_schedule_next": 0.81, "coef2": 1.0},
             dynamic_params=_DYN + ("momentum_t", "momentum_t_1",
                                    "m_schedule", "m_schedule_next",
                                    "coef2"))
def _nadam_update(weight, grad, m, v, lr=0.001, beta1=0.9, beta2=0.999,
                  epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                  momentum_t=0.9, momentum_t_1=0.9, m_schedule=0.9,
                  m_schedule_next=0.81, coef2=1.0):
    # Nesterov Adam (reference optimizer.py:975).  The momentum schedule
    # (mu_t, mu_{t+1}, their running products, and 1 - beta2^t) is t-bound
    # host state, so it rides in as dynamic scalars — one compiled program
    # serves the whole training run.
    g = _rescale(grad, rescale_grad, clip_gradient) + wd * weight
    new_m = beta1 * m + (1.0 - beta1) * g
    new_v = beta2 * v + (1.0 - beta2) * jnp.square(g)
    g_prime = g / (1.0 - m_schedule)
    m_prime = new_m / (1.0 - m_schedule_next)
    v_prime = new_v / coef2
    m_bar = (1.0 - momentum_t) * g_prime + momentum_t_1 * m_prime
    return (weight - lr * m_bar / (jnp.sqrt(v_prime) + epsilon),
            new_m, new_v)
