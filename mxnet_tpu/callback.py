"""Training callbacks.

Port of /root/reference/python/mxnet/callback.py: checkpointing each epoch,
log_train_metric, Speedometer throughput logging, and ProgressBar.
"""
from __future__ import annotations

import logging
import math
import time

__all__ = ["module_checkpoint", "do_checkpoint", "log_train_metric",
           "Speedometer", "ProgressBar"]


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False,
                      keep_last=None):
    """Checkpoint the Module each `period` epochs (reference callback.py:29).

    Writes are crash-safe (atomic + manifest, checkpoint.py); pass
    ``keep_last`` to prune to the N newest complete checkpoints.  With
    ``MXTPU_ASYNC_CKPT=1`` the write overlaps the next epoch's compute
    (fit drains the queue at exit; writer errors surface on the next
    step / epoch boundary)."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            mod.save_checkpoint(prefix, iter_no + 1, save_optimizer_states,
                                keep_last=keep_last)
    return _callback


def do_checkpoint(prefix, period=1, keep_last=None):
    """Checkpoint params each `period` epochs (reference callback.py:55).

    Crash-safe like module_checkpoint; ``keep_last`` enables retention."""
    from .model import save_checkpoint
    period = int(max(1, period))

    def _callback(iter_no, sym, arg, aux):
        if (iter_no + 1) % period == 0:
            save_checkpoint(prefix, iter_no + 1, sym, arg, aux,
                            keep_last=keep_last)
    return _callback


def log_train_metric(period, auto_reset=False):
    """Log metric every `period` batches (reference callback.py:83)."""
    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            for name, value in name_value:
                logging.info("Iter[%d] Batch[%d] Train-%s=%f",
                             param.epoch, param.nbatch, name, value)
            if auto_reset:
                param.eval_metric.reset()
    return _callback


class Speedometer:
    """Log samples/sec every `frequent` batches (reference callback.py:106)."""

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.init = False
        self.tic = 0
        self.last_count = 0
        self.auto_reset = auto_reset

    def __call__(self, param):
        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count
        if self.init:
            if count % self.frequent == 0:
                speed = self.frequent * self.batch_size / \
                    (time.time() - self.tic)
                if param.eval_metric is not None:
                    name_value = param.eval_metric.get_name_value()
                    if self.auto_reset:
                        param.eval_metric.reset()
                    msg = "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec"
                    msg += "\t%s=%f" * len(name_value)
                    logging.info(msg, param.epoch, count, speed,
                                 *sum(name_value, ()))
                else:
                    logging.info("Iter[%d] Batch [%d]\tSpeed: %.2f "
                                 "samples/sec", param.epoch, count, speed)
                self.tic = time.time()
        else:
            self.init = True
            self.tic = time.time()


class ProgressBar:
    """ASCII progress bar per batch (reference callback.py:151)."""

    def __init__(self, total, length=80):
        self.bar_len = length
        self.total = total

    def __call__(self, param):
        count = param.nbatch
        filled_len = int(round(self.bar_len * count / float(self.total)))
        percents = math.ceil(100.0 * count / float(self.total))
        prog_bar = "=" * filled_len + "-" * (self.bar_len - filled_len)
        logging.info("[%s] %s%s\r", prog_bar, percents, "%")
