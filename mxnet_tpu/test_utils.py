"""Testing utilities.

Lean TPU-native port of the reference's test harness surface
(/root/reference/python/mxnet/test_utils.py, 1,287 L): per-dtype tolerances,
random data generators, finite-difference gradient checking, and
cross-context consistency checks.  The finite-difference checker validates
``jax.grad``-derived backwards exactly as the reference's
``check_numeric_gradient`` validated hand-written FGradient kernels.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from .context import Context, cpu, current_context

_DEFAULT_RTOL = {
    np.dtype(np.float16): 1e-2,
    np.dtype(np.float32): 1e-4,
    np.dtype(np.float64): 1e-5,
}
_DEFAULT_ATOL = {
    np.dtype(np.float16): 1e-2,
    np.dtype(np.float32): 1e-5,
    np.dtype(np.float64): 1e-8,
}


def default_context():
    return current_context()


def set_default_context(ctx):
    Context._default_ctx.value = ctx


def default_dtype():
    return np.float32


def get_rtol(rtol=None, dtype=np.float32):
    return rtol if rtol is not None else _DEFAULT_RTOL.get(np.dtype(dtype), 1e-4)


def get_atol(atol=None, dtype=np.float32):
    return atol if atol is not None else _DEFAULT_ATOL.get(np.dtype(dtype), 1e-5)


def _as_numpy(a):
    from .ndarray.ndarray import NDArray
    if isinstance(a, NDArray):
        return a.asnumpy()
    return np.asarray(a)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b")):
    a, b = _as_numpy(a), _as_numpy(b)
    rtol = get_rtol(rtol, a.dtype)
    atol = get_atol(atol, a.dtype)
    np.testing.assert_allclose(a, b, rtol=rtol, atol=atol,
                               err_msg="%s vs %s" % names)


def almost_equal(a, b, rtol=None, atol=None):
    a, b = _as_numpy(a), _as_numpy(b)
    return np.allclose(a, b, rtol=get_rtol(rtol, a.dtype),
                       atol=get_atol(atol, a.dtype))


def same(a, b):
    return np.array_equal(_as_numpy(a), _as_numpy(b))


def rand_ndarray(shape, stype="default", density=None, dtype=np.float32,
                 ctx=None):
    from . import nd
    arr = np.random.uniform(-1.0, 1.0, size=shape).astype(dtype)
    out = nd.array(arr, ctx=ctx, dtype=dtype)
    if stype != "default":
        out = out.tostype(stype)
    return out


def rand_shape_2d(dim0=10, dim1=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1),
            np.random.randint(1, dim2 + 1))


def numeric_grad(fn, inputs, eps=1e-4):
    """Central finite differences of scalar-output fn over numpy inputs."""
    grads = [np.zeros_like(x) for x in inputs]
    for i, x in enumerate(inputs):
        flat = x.reshape(-1)
        gflat = grads[i].reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps
            fp = float(fn(*inputs))
            flat[j] = orig - eps
            fm = float(fn(*inputs))
            flat[j] = orig
            gflat[j] = (fp - fm) / (2 * eps)
    return grads


def check_numeric_gradient(sym, location, aux_states=None, rtol=1e-2,
                           atol=None, eps=1e-4, ignore=(), fixed=()):
    """Finite-difference check of a Symbol's backward.

    Mirrors the reference check_numeric_gradient (test_utils.py:620): bind
    the symbol with float64 data, compare the symbolic gradient of
    sum(outputs) against central differences.

    ``fixed`` names non-differentiable inputs (integer indices, labels):
    they keep their dtype, are not perturbed, and get no gradient compare.
    ``ignore`` checks forward/backward but skips the compare for a name.
    """
    from . import nd
    from .executor import Executor  # noqa: F401 - ensures module exists

    arg_names = sym.list_arguments()
    if isinstance(location, (list, tuple)):
        location = dict(zip(arg_names, location))
    fixed = set(fixed)
    loc_np = {k: (_as_numpy(v) if k in fixed
                  else _as_numpy(v).astype(np.float64))
              for k, v in location.items()}
    aux_np = {k: _as_numpy(v).astype(np.float64)
              for k, v in (aux_states or {}).items()}

    diff_names = [n for n in arg_names if n not in fixed]
    args = {k: nd.array(v, dtype=v.dtype) for k, v in loc_np.items()}
    args_grad = {k: nd.zeros(loc_np[k].shape, dtype=np.float64)
                 for k in diff_names}
    grad_req = {k: ("write" if k in diff_names else "null")
                for k in arg_names}
    aux = {k: nd.array(v, dtype=np.float64) for k, v in aux_np.items()}
    exe = sym.bind(default_context(), args=args, args_grad=args_grad,
                   grad_req=grad_req, aux_states=aux)
    outs = exe.forward(is_train=True)
    exe.backward([nd.ones(o.shape, dtype=np.float64) for o in outs])

    # one executor reused for every finite-difference evaluation — its
    # jitted forward is traced once; per-eval cost is a compiled call
    a0 = {k: nd.array(v, dtype=v.dtype) for k, v in loc_np.items()}
    ex2 = sym.bind(default_context(), args=a0, grad_req="null",
                   aux_states={k: nd.array(v, dtype=np.float64)
                               for k, v in aux_np.items()})

    def f(*vals):
        for k, v in zip(diff_names, vals):
            ex2.arg_dict[k]._set_data(jnp.asarray(v))
        os_ = ex2.forward(is_train=True)
        return sum(float(o.asnumpy().sum()) for o in os_)

    vals = [loc_np[k] for k in diff_names]
    ngrads = numeric_grad(f, vals, eps=eps)
    for name, ng in zip(diff_names, ngrads):
        if name in ignore:
            continue
        sg = exe.grad_dict[name].asnumpy()
        np.testing.assert_allclose(
            sg, ng, rtol=rtol, atol=atol if atol is not None else 1e-4,
            err_msg="gradient mismatch for %s" % name)


def check_consistency(fn, ctx_list=None, rtol=1e-4, atol=1e-5):
    """Run fn under each context and assert identical outputs.

    The analogue of the reference's CPU-vs-GPU check_consistency; here it
    validates TPU vs host-CPU lowerings of the same XLA program.
    """
    ctx_list = ctx_list or [cpu(0), current_context()]
    results = []
    for ctx in ctx_list:
        with ctx:
            results.append(_as_numpy(fn()))
    for r in results[1:]:
        np.testing.assert_allclose(results[0], r, rtol=rtol, atol=atol)


def _bind_location(sym, location, aux_states, ctx, grad_req):
    """Shared setup for check_symbolic_forward/backward: normalize the
    location to a dict and build bound args/grads/aux.  grad_req "null"
    binds without gradient buffers."""
    from . import nd
    arg_names = sym.list_arguments()
    if isinstance(location, (list, tuple)):
        location = dict(zip(arg_names, location))
    args = {k: nd.array(_as_numpy(v)) for k, v in location.items()}
    grads = None if grad_req == "null" else \
        {k: nd.zeros(_as_numpy(v).shape) for k, v in location.items()}
    aux = {k: nd.array(_as_numpy(v))
           for k, v in (aux_states or {}).items()} or None
    exe = sym.bind(ctx, args=args, args_grad=grads, grad_req=grad_req,
                   aux_states=aux)
    return exe, grads


def check_symbolic_forward(sym, location, expected, rtol=1e-4, atol=None,
                           aux_states=None, ctx=None):
    """Compare a symbol's forward outputs against expected arrays
    (reference test_utils.py:744 signature)."""
    ctx = ctx or default_context()
    exe, _ = _bind_location(sym, location, aux_states, ctx, "null")
    outs = exe.forward(is_train=False)
    if isinstance(expected, dict):
        expected = [expected[k] for k in sym.list_outputs()]
    assert len(expected) == len(outs), \
        "expected %d outputs, symbol has %d" % (len(expected), len(outs))
    for out, want in zip(outs, expected):
        np.testing.assert_allclose(
            out.asnumpy(), _as_numpy(want), rtol=rtol, atol=get_atol(atol))
    return [o.asnumpy() for o in outs]


def check_symbolic_backward(sym, location, out_grads, expected, rtol=1e-5,
                            atol=None, aux_states=None, grad_req="write",
                            ctx=None):
    """Compare a symbol's backward input-gradients against expected
    arrays (reference test_utils.py:809 signature)."""
    from . import nd
    ctx = ctx or default_context()
    if isinstance(expected, (list, tuple)):
        expected = dict(zip(sym.list_arguments(), expected))
    exe, grads = _bind_location(sym, location, aux_states, ctx, grad_req)
    grads = grads if grads is not None else {}
    outs = exe.forward(is_train=True)
    if out_grads is None:
        ograds = [nd.ones(o.shape) for o in outs]
    elif isinstance(out_grads, dict):
        ograds = [nd.array(_as_numpy(out_grads[k]))
                  for k in sym.list_outputs()]
    else:
        ograds = [nd.array(_as_numpy(g)) for g in out_grads]
    exe.backward(ograds)
    for name, want in expected.items():
        if name not in grads:
            raise ValueError(
                "no gradient bound for %r (grad_req=%r): cannot compare "
                "an expected backward value" % (name, grad_req))
        np.testing.assert_allclose(
            grads[name].asnumpy(), _as_numpy(want), rtol=rtol,
            atol=get_atol(atol),
            err_msg="backward mismatch for %s" % name)
    return {k: v.asnumpy() for k, v in grads.items()}
