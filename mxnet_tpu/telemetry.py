"""Unified telemetry: metrics registry, cross-layer spans, flight recorder.

The reference MXNet engine profiled every pushed op
(src/engine/profiler.cc: one OprExecStat per engine op); the XLA-fused
rebuild collapsed the graph into one program per step, so per-op hooks
vanished and visibility shrank to profiler.py's five global counters.
This module is the always-on observability substrate the fused design
needs instead:

- **metrics registry** — named :class:`Counter` / :class:`Gauge` /
  :class:`Histogram` (fixed log2 buckets, so percentile queries need no
  sample storage).  Hot-path mutation is lock-free on purpose, exactly
  like ``profiler.count_dispatch``: a GIL-raced increment merely
  miscounts telemetry, and the fused step budget (<1% of a ~0.3 ms CPU
  MLP step) has no room for a lock acquire per observation.
- **span(name, cat)** — a context manager timing one named phase.  Every
  span feeds a phase histogram (always on) and, while the profiler is
  collecting, a chrome-tracing duration event in the same stream the
  executor writes, so data-loading / checkpoint / kvstore phases land in
  the same trace as ``executor_forward``.  Nested spans carry a ``depth``
  arg so the hierarchy survives trace viewers that don't infer nesting.
- **flight recorder** — a bounded ring of the last K per-step records
  (dispatch/sync wall time, dispatch/compile deltas, skipped flag, loss
  when the step has a scalar head, fault-site firings).  On an unhandled
  exception (``MXNetError`` from the divergence guard included) or at
  exit with a nonzero skip count, the ring is dumped as a postmortem
  JSON into ``MXTPU_POSTMORTEM_DIR`` via the checkpoint layer's plain
  atomic writer (no fault sites — a postmortem must never tear) —
  the last seconds of a run that died are never lost.
- **XLA compile attribution** — a ``jax.monitoring`` listener counts
  every backend compile (``xla.compiles`` counter +
  ``xla.compile_seconds`` histogram); ``profiler.instrument`` uses the
  same monotonic event count to attribute *steady-state recompiles* of
  an instrumented program to ``profiler.count_compile`` (its own
  first-call heuristic only ever sees the initial compile).
- **periodic emitter** — ``MXTPU_TELEMETRY=path[:interval]`` appends one
  ``report()`` JSON line every ``interval`` seconds (default 10) so a
  soak run leaves a machine-readable timeline behind.

**Job scope** (schema ``mxtpu-telemetry-2``, OBSERVABILITY.md §8): every
report line and postmortem carries an ``identity`` block (world size /
rank / slot / attempt / pid from :mod:`mxnet_tpu.elastic`'s launch
contract) and a ``clock`` anchor — the one ``(unix, perf_counter_ns)``
base pair every perf-stamp in this process is relative to — so
``tools/perf_probe/job_report.py`` can merge N ranks' streams into one
job timeline and one cross-rank chrome trace on a common clock.  The
emitter's final line additionally carries the flight ring
(``last_steps``) so a cleanly-exited rank leaves its recent per-step
spans behind the way a crashed rank leaves them in its postmortem.

**Request scope** (ISSUE 13, OBSERVABILITY.md §12): the serving twin of
the per-step flight recorder.  :func:`mint_trace` issues a process-unique
trace id at ``Router.submit`` / ``ServingEngine.submit``;
:func:`note_request_event` records one lifecycle event (submit, place,
admit, prefill, token batches, retry, swap, terminal verdict) with the
SAME hot-path discipline as ``note_train_step`` — one tuple append, all
folding deferred to a batched drain into a bounded event ring.  The
periodic emitter ships each line's NEWLY-drained events
(``req_events``, a cursor over the monotonic per-process ``seq``) so the
stream accumulates the full lifecycle record while each line stays
bounded; ring evictions of never-emitted events are counted
(``serving.trace_dropped`` / per-line ``req_dropped`` — no silent caps).
Crash postmortems carry the whole ring (``request_trace``), and every
``report()`` from a process with live serving engines carries a
``serving`` status block (occupancy, free pages, SLO controller state,
current weights epoch) — the periodic serving status line.
``tools/perf_probe/serve_report.py`` merges router journal + replica
streams into the fleet view.

``tools/perf_probe/telemetry_report.py`` renders the per-rank artifacts
(JSON-lines timeline and postmortem) for humans;
``tools/perf_probe/job_report.py`` aggregates a whole run dir;
OBSERVABILITY.md is the metric-name / span-taxonomy / schema contract.

Env vars: ``MXTPU_TELEMETRY``, ``MXTPU_POSTMORTEM_DIR``,
``MXTPU_FLIGHT_RECORDER_STEPS`` (ring size, default 64),
``MXTPU_REQUEST_TRACE_EVENTS`` (request-event ring size, default 8192),
``MXTPU_TELEMETRY_OFF=1`` (disable hot-path recording; the A/B side of
``BENCH_MODE=telemetry``'s overhead measurement).
"""
from __future__ import annotations

import atexit
import collections
import contextlib
import itertools
import json
import math
import os
import sys
import threading
import time

import numpy as _np

__all__ = ["Counter", "Gauge", "Histogram", "counter", "gauge",
           "histogram", "span", "observe_phase", "report", "reset",
           "note_train_step", "note_fault", "mark_last_step_verdict",
           "flight_records", "flight_capacity", "dump_postmortem",
           "start_emitter", "stop_emitter", "set_enabled", "enabled",
           "identity", "clock_anchor", "suppress_compile_accounting",
           "mint_trace", "note_request_event", "request_events",
           "consume_request_events", "count_token_events",
           "request_events_since", "flight_records_since",
           "pull_snapshot", "AlertRule", "add_alert_rule",
           "alert_rules", "clear_alert_rules",
           "install_default_alert_rules", "check_alerts"]

SCHEMA_REPORT = "mxtpu-telemetry-2"
SCHEMA_POSTMORTEM = "mxtpu-postmortem-2"


def _env_int(name, default):
    try:
        return int(os.environ.get(name, ""))
    except ValueError:
        return default


_DISABLED = os.environ.get("MXTPU_TELEMETRY_OFF", "0") == "1"


def set_enabled(flag):
    """Toggle hot-path recording (spans, per-step records).  Registry
    objects stay queryable either way; BENCH_MODE=telemetry flips this
    to measure the always-on overhead against a dark run."""
    global _DISABLED
    _DISABLED = not flag


def enabled():
    return not _DISABLED


# -- lazy intra-package bindings (telemetry must stay importable from the
# very bottom of the package: only .base above it) -------------------------
_prof = None


def _profiler():
    global _prof
    if _prof is None:
        from . import profiler
        _prof = profiler
    return _prof


# -- metrics registry ------------------------------------------------------
_reg_lock = threading.Lock()     # creation only; mutation is lock-free
_counters = {}
_gauges = {}
_histograms = {}
_span_names = set()              # histogram names that came from spans


class Counter(object):
    """Monotonic named counter.  ``inc`` is a bare int add — lock-free
    like profiler.count_dispatch; a GIL race miscounts, never corrupts."""

    __slots__ = ("name", "value")

    def __init__(self, name):
        self.name = name
        self.value = 0

    def inc(self, n=1):
        self.value += n


class Gauge(object):
    """Last-write-wins named value (queue depths, ring occupancy...)."""

    __slots__ = ("name", "value")

    def __init__(self, name):
        self.name = name
        self.value = None

    def set(self, v):
        self.value = v


class Histogram(object):
    """Fixed log2-bucket histogram for durations (seconds) and sizes
    (bytes).  Bucket ``e`` holds values in ``(2**(e-1), 2**e]`` (the
    ``math.frexp`` exponent), zeros are counted separately — the bucket
    map is sparse, observation is O(1), and percentiles come from linear
    interpolation inside the covering bucket (bounded by construction to
    one power of two of the truth, clamped to the observed min/max)."""

    __slots__ = ("name", "count", "sum", "min", "max", "_zeros", "_buckets")

    def __init__(self, name):
        self.name = name
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None
        self._zeros = 0
        self._buckets = {}

    def observe(self, v):
        v = float(v)
        if v > 0.0:
            e = math.frexp(v)[1]
            b = self._buckets
            b[e] = b.get(e, 0) + 1
        else:
            self._zeros += 1
        self.count += 1
        self.sum += v
        if self.min is None or v < self.min:
            self.min = v
        if self.max is None or v > self.max:
            self.max = v

    def observe_many(self, values, scale=1.0):
        """Batch observe (the flight-recorder drain): one numpy
        frexp+bincount replaces per-value Python bucketing — the reason
        the always-on per-step telemetry stays inside its <1% budget.
        ``scale`` converts raw units (e.g. ns deltas) in the same
        vectorized pass."""
        n = len(values)
        if not n:
            return
        arr = _np.asarray(values, dtype=_np.float64)
        if scale != 1.0:
            arr = arr * scale
        pos = arr[arr > 0.0]
        if pos.size:
            e = _np.frexp(pos)[1]
            lo = int(e.min())
            b = self._buckets
            for i, cnt in enumerate(_np.bincount(e - lo)):
                if cnt:
                    k = lo + i
                    b[k] = b.get(k, 0) + int(cnt)
        self._zeros += n - int(pos.size)
        self.count += n
        self.sum += float(arr.sum())
        amin, amax = float(arr.min()), float(arr.max())
        if self.min is None or amin < self.min:
            self.min = amin
        if self.max is None or amax > self.max:
            self.max = amax

    def percentile(self, q, _buckets=None):
        """Approximate q-quantile (q in [0, 1]) from the bucket counts."""
        if not self.count:
            return None
        if _buckets is None:
            # atomic copy: observers on other threads (prefetch workers)
            # may insert new bucket keys mid-iteration
            _buckets = dict(self._buckets)
        target = q * self.count
        cum = float(self._zeros)
        if target <= cum and self._zeros:
            return 0.0
        for e in sorted(_buckets):
            n = _buckets[e]
            if target <= cum + n:
                lo, hi = 2.0 ** (e - 1), 2.0 ** e
                v = lo + (target - cum) / n * (hi - lo)
                return min(max(v, self.min), self.max)
            cum += n
        return self.max

    def snapshot(self):
        buckets = dict(self._buckets)  # atomic vs concurrent observes
        return {
            "count": self.count, "sum": self.sum,
            "min": self.min, "max": self.max,
            "p50": self.percentile(0.50, buckets),
            "p90": self.percentile(0.90, buckets),
            "p99": self.percentile(0.99, buckets),
            "buckets": {str(e): n for e, n in sorted(buckets.items())},
            "zeros": self._zeros,
        }


def _get_or_create(table, name, cls):
    obj = table.get(name)
    if obj is None:
        with _reg_lock:
            obj = table.setdefault(name, cls(name))
    return obj


def counter(name):
    """Get-or-create the named Counter (idempotent; hot callers should
    hold the returned object instead of re-resolving the name)."""
    return _get_or_create(_counters, name, Counter)


def gauge(name):
    return _get_or_create(_gauges, name, Gauge)


def histogram(name):
    return _get_or_create(_histograms, name, Histogram)


def _span_hist(name):
    h = _histograms.get(name)
    if h is None:
        h = histogram(name)
        with _reg_lock:
            _span_names.add(name)
    return h


# -- spans -----------------------------------------------------------------
_tls = threading.local()


class span(object):
    """Time one named phase: always feeds the phase histogram ``name``
    (seconds), and while the profiler collects, appends a chrome-tracing
    duration event of category ``cat`` with a ``depth`` arg reflecting
    span nesting on this thread.

    >>> with telemetry.span("data.batchify", cat="data"):
    ...     batch = batchify_fn(samples)
    """

    __slots__ = ("name", "cat", "_t0", "_depth")

    def __init__(self, name, cat="phase"):
        self.name = name
        self.cat = cat

    def __enter__(self):
        self._depth = getattr(_tls, "depth", 0)
        _tls.depth = self._depth + 1
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter_ns()
        _tls.depth = self._depth
        dur_ns = t1 - self._t0
        if not _DISABLED:
            _span_hist(self.name).observe(dur_ns * 1e-9)
        prof = _prof or _profiler()
        # trace events survive MXTPU_TELEMETRY_OFF (profiling is its own
        # explicit opt-in).  Trace-origin guard: a span opened before
        # profiler_set_state("run") must not emit a pre-origin
        # (negative-ts) phantom event.
        if prof.is_running() and self._t0 // 1000 >= (prof._t0_us or 0):
            prof.record_event(self.name, self._t0 // 1000,
                              dur_ns // 1000, cat=self.cat,
                              args={"depth": self._depth})
        return False


def observe_phase(name, seconds):
    """Feed one duration into the span histogram ``name`` without timing
    a block here — for phases measured somewhere this registry can't
    reach: a stream decode worker may be a separate PROCESS whose
    registry dies with it, so the measured duration rides the result
    back and the consumer folds it into THIS process's phase table
    (rendered exactly like a span of the same name)."""
    if not _DISABLED:
        _span_hist(name).observe(seconds)


# -- XLA compile attribution (jax.monitoring bridge) -----------------------
# Monotonic count of backend compiles, read by profiler.instrument to
# attribute steady-state recompiles of an instrumented program to
# count_compile.  Never reset (delta readers depend on monotonicity).
_xla_compiles = 0
_compile_hook_installed = False
_compile_suppress = threading.local()


@contextlib.contextmanager
def suppress_compile_accounting():
    """Mark this thread's backend compiles as intentional background work
    (the AOT twin / hot-swap compiles, executor._twin_hotswap): they are
    counted under ``xla.background_compiles`` instead of bumping the
    monotonic ``_xla_compiles`` that profiler.instrument uses to charge
    recompiles to in-flight steps — a deliberate off-hot-path compile is
    exactly NOT the steady-state recompile that counter exists to catch."""
    prev = getattr(_compile_suppress, "on", False)
    _compile_suppress.on = True
    try:
        yield
    finally:
        _compile_suppress.on = prev


def _on_jax_event(event, duration, **kw):
    if "backend_compile" in event:
        if getattr(_compile_suppress, "on", False):
            counter("xla.background_compiles").inc()
            return
        global _xla_compiles
        _xla_compiles += 1
        counter("xla.compiles").inc()
        histogram("xla.compile_seconds").observe(duration)


def _install_compile_hook():
    """Listen for jax.monitoring's per-compile duration events (the
    log_compiles signal, structured).  Best-effort: jax versions without
    the monitoring module leave the first-call heuristic in charge."""
    global _compile_hook_installed
    if _compile_hook_installed:
        return True
    try:
        from jax import monitoring as _monitoring
        _monitoring.register_event_duration_secs_listener(_on_jax_event)
    except Exception:
        return False
    _compile_hook_installed = True
    return True


def xla_compile_events():
    """Monotonic backend-compile event count (survives reset())."""
    return _xla_compiles


# -- flight recorder -------------------------------------------------------
_FLIGHT_FIELDS = ("step", "t_unix", "dispatch_s", "sync_s",
                  "dispatch_delta", "compile_delta", "skipped", "loss",
                  "faults", "where")
_flight = collections.deque(
    maxlen=max(1, _env_int("MXTPU_FLIGHT_RECORDER_STEPS", 64)))
_step_seq = 0
_last_dispatch = 0
_last_compile = 0
# sites fired since the last step record; bounded (a fault-heavy run
# with no train steps — e.g. pure checkpoint I/O under ckpt.write.*
# rates — must not grow it forever)
_pending_faults = collections.deque(maxlen=256)
_train_hists = {}                # where -> (dispatch hist, sync hist)

# perf_counter↔unix correspondence, so the hot path never calls
# time.time(): records carry perf_counter_ns stamps and the drain
# reconstructs wall-clock time from this one base pair
_unix_base = time.time()
_perf_base = time.perf_counter_ns()

# The per-step hot path appends ONE compact tuple here; histograms, the
# flight ring, and trace events are folded in by _drain_steps in batches
# of _PENDING_MAX (or on any read).  Batching exists for the <1%-of-a-
# fused-step budget: folding touches a dozen Python objects, and doing
# that once per 128 steps with hot caches costs a fraction of doing it
# per step cold (BENCH_MODE=telemetry measures the result).
_pending_steps = []
_PENDING_MAX = 128
_drain_lock = threading.Lock()


def note_train_step(t0_ns, t1_ns, t2_ns=None, skipped=False, loss=None,
                    where="fit_step"):
    """Record one fused train step from three perf_counter_ns stamps:
    program dispatch [t0, t1] and device sync / verdict readback
    [t1, t2] (``t2_ns=None`` for paths that resolve the verdict lazily —
    the Trainer — in which case ``skipped`` is back-filled by
    :func:`mark_last_step_verdict`).

    Hot-path cost is one tuple append plus two profiler counter reads;
    everything else is deferred to the batched drain.  While the
    profiler collects, the drain runs per step so trace events stay
    timely (profiling already pays for accuracy with syncs)."""
    prof = _prof or _profiler()
    if _DISABLED:
        # metrics off, but an explicitly-running profiler still gets
        # its fused-step trace events (the _timed("module_fit_step")
        # signal this layer replaced must survive MXTPU_TELEMETRY_OFF)
        if prof.is_running():
            prof.record_event(where + ".dispatch", t0_ns // 1000,
                              (t1_ns - t0_ns) // 1000, cat="step")
            if t2_ns is not None:
                prof.record_event(where + ".sync", t1_ns // 1000,
                                  (t2_ns - t1_ns) // 1000, cat="step")
        return
    if _pending_faults:
        # popleft-until-empty: a note_fault append landing from another
        # thread (e.g. the prefetch worker) mid-snapshot survives for
        # the next record instead of vanishing
        popped = []
        while True:
            try:
                popped.append(_pending_faults.popleft())
            except IndexError:
                break
        faults = tuple(popped)
    else:
        faults = ()
    p = _pending_steps
    p.append((where, t0_ns, t1_ns, t2_ns, skipped, loss,
              prof._dispatch_count, prof._compile_count, faults))
    # per-step drain only while the profiler actually collects (paused
    # counts as not collecting — no trace events would be emitted, so
    # defeating the batching would buy nothing)
    if len(p) >= _PENDING_MAX or prof.is_running():
        _drain_steps()


def _drain_steps():
    """Fold pending step tuples into the phase histograms, the flight
    ring, and (while profiling) the trace stream.  Runs under a lock —
    callers are the hot path every _PENDING_MAX steps, every reader, and
    the emitter thread."""
    global _step_seq, _last_dispatch, _last_compile
    with _drain_lock:
        batch = list(_pending_steps)
        if not batch:
            return
        del _pending_steps[:len(batch)]
        prof = _prof or _profiler()
        running = prof.is_running()
        # records buffered before the trace started must not leak into
        # it as pre-origin (negative-ts) phantom events
        trace_t0_us = (prof._t0_us or 0) if running else None
        # histogram folds: vectorized per `where` over the whole batch
        # (record layout: where, t0, t1, t2, skipped, loss, d, c, faults)
        wheres = {r[0] for r in batch}
        for w in wheres:
            rs = batch if len(wheres) == 1 else \
                [r for r in batch if r[0] == w]
            pair = _train_hists.get(w)
            if pair is None:
                pair = (_span_hist(w + ".dispatch"),
                        _span_hist(w + ".sync"))
                _train_hists[w] = pair
            pair[0].observe_many([r[2] - r[1] for r in rs], scale=1e-9)
            pair[1].observe_many([r[3] - r[2] for r in rs
                                  if r[3] is not None], scale=1e-9)
        # ring fold: records past ring capacity would be appended then
        # immediately evicted — advance the counters over them instead
        seq, last_d, last_c = _step_seq, _last_dispatch, _last_compile
        skip = len(batch) - _flight.maxlen
        if skip > 0 and not running:
            seq += skip
            last_d, last_c = batch[skip - 1][6], batch[skip - 1][7]
            batch = batch[skip:]
        append = _flight.append
        t_off = _unix_base - _perf_base * 1e-9
        for (where, t0, t1, t2, skipped, loss, d, c, faults) in batch:
            sync_s = (t2 - t1) * 1e-9 if t2 is not None else None
            append([seq, t_off + t0 * 1e-9, (t1 - t0) * 1e-9, sync_s,
                    d - last_d, c - last_c, skipped, loss, faults,
                    where])
            seq += 1
            last_d, last_c = d, c
            if running and t0 // 1000 >= trace_t0_us:
                prof.record_event(where + ".dispatch", t0 // 1000,
                                  (t1 - t0) // 1000, cat="step")
                if t2 is not None:
                    prof.record_event(where + ".sync", t1 // 1000,
                                      (t2 - t1) // 1000, cat="step")
        _step_seq, _last_dispatch, _last_compile = seq, last_d, last_c


def _rebaseline(dispatch=0, compile_=0):
    """Settle pending records against the old counters, then restart the
    flight-recorder deltas from the given values — profiler.
    reset_step_stats calls this so the two resets compose in either
    order."""
    global _last_dispatch, _last_compile
    _drain_steps()
    with _drain_lock:
        _last_dispatch = dispatch
        _last_compile = compile_


def mark_last_step_verdict(ok):
    """Back-fill the newest flight record's skipped flag from the
    divergence-guard verdict — the Trainer records its step with
    ``skipped=None`` (pending) and resolves one step late by design
    (PERF.md "Divergence guard"), always before the next record is
    appended.  A crash in between leaves the honest ``None``
    ("verdict unknown"), never a false ``ok``."""
    if _DISABLED:
        return
    skipped = not ok
    # back-fill the NEWEST pending (None) record.  It usually still sits
    # in _pending_steps (the Trainer resolves every step, and forcing a
    # ring drain here would defeat the batching the <1% budget rests
    # on), else in the drained ring — a Module.fit_step record may land
    # in between, so scan tails, never touching resolved records.
    # (Two Trainers with simultaneously pending verdicts in one process
    # could still cross-attribute; verdicts resolve in step order, so
    # the window is one record and the skip COUNT stays exact.)
    # Under _drain_lock: concurrent drains/resets mutate both
    # containers, and deque iteration raises on mutation mid-scan.
    with _drain_lock:
        for i in range(len(_pending_steps) - 1, -1, -1):
            rec = _pending_steps[i]
            if rec[4] is None:
                _pending_steps[i] = rec[:4] + (skipped,) + rec[5:]
                return
        for rec in reversed(_flight):
            if rec[6] is None:
                rec[6] = skipped
                return


def note_fault(site):
    """Called by fault.trigger when a site fires: per-site counter (the
    registry stays live even when hot-path recording is off) plus
    attribution of the firing to the next flight-recorder step record
    (gated — nothing drains the pending list while recording is off,
    and stale firings must not be dumped onto a later step)."""
    counter("fault.fire.%s" % site).inc()
    if not _DISABLED:
        _pending_faults.append(site)


def flight_records():
    """The ring as a list of dicts, oldest first."""
    _drain_steps()
    return [dict(zip(_FLIGHT_FIELDS, rec)) for rec in list(_flight)]


def flight_capacity():
    return _flight.maxlen


def flight_records_since(step, max_records=None):
    """Non-destructive cursor slice over the flight ring for the RPC
    telemetry pull: ``(records, evicted, next_step, more)`` with the
    same contract as :func:`request_events_since`, keyed on the
    monotonic per-process ``step`` field.  ``step=None`` starts at the
    oldest surviving record."""
    _drain_steps()
    with _drain_lock:
        oldest = _flight[0][0] if _flight else _step_seq
        if step is None:
            step = oldest
        evicted = max(0, oldest - step)
        recs = [r for r in _flight if r[0] >= step]
        more = False
        if max_records is not None and len(recs) > max_records:
            recs = recs[:max_records]
            more = True
        next_step = (recs[-1][0] + 1) if recs else max(step, oldest)
        return ([dict(zip(_FLIGHT_FIELDS, r)) for r in recs],
                evicted, next_step, more)


# -- request-scope tracing (the serving plane, OBSERVABILITY.md §12) -------
# One bounded ring of per-request lifecycle events, the serving twin of
# the per-step flight ring: the hot path (a decode step's token batch)
# is ONE tuple append; the batched drain assigns a monotonic per-process
# ``seq`` and folds into the ring.  The periodic emitter ships each
# line's newly-drained events (a cursor over ``seq``), so a replica's
# stream accumulates the complete lifecycle record while every line
# stays bounded; evicting a never-emitted event is counted, never
# silent.  ``tools/perf_probe/serve_report.py`` reconstructs per-request
# lifecycles (and the fleet view) from these events.
_REQ_RING_CAP = max(64, _env_int("MXTPU_REQUEST_TRACE_EVENTS", 8192))
_req_ring = collections.deque(maxlen=_REQ_RING_CAP)
_req_seq = 0            # next event sequence number (monotonic)
# Per-consumer drain cursors (ISSUE 18): consumer name -> [next_seq,
# dropped].  The file emitter, the postmortem drain, and the RPC
# telemetry pull each hold their own cursor, so each sees every event
# exactly once without stealing another consumer's deliveries.  The
# "emitter" cursor is pre-registered at seq 0 so a process with no
# stream file still counts every never-shipped eviction
# (``serving.trace_dropped`` keeps its ISSUE-13 meaning).
_req_cursors = {"emitter": [0, 0]}
_pending_req = []
_REQ_PENDING_MAX = 256
_trace_seq = itertools.count()
# process-unique trace-id base: pid alone repeats across restart
# attempts, and a survivor's stream must never collide trace ids with
# its predecessor's (serve_report merges both)
_TRACE_BASE = "%x.%x" % (os.getpid(),
                         int(_unix_base * 1e3) & 0xffffffff)


def mint_trace():
    """A new process-unique request trace id (``Router.submit`` /
    ``ServingEngine.submit`` mint one per request; everything the
    request experiences — admission, prefill, every decode token, a
    failover re-decode on another replica — is recorded under it)."""
    return "%s-%x" % (_TRACE_BASE, next(_trace_seq))


def note_request_event(trace, event, t_ns=None, args=None):
    """Record one request-lifecycle event.  Hot-path discipline matches
    :func:`note_train_step`: one tuple append, everything else deferred
    to the batched drain (``BENCH_MODE=serve`` asserts the per-decode-
    step budget).  ``trace=""`` marks an engine-scope event (a hot-swap
    pause naming the resident traces in ``args``); ``t_ns`` is a
    ``perf_counter_ns`` stamp (defaults to now — pass the step's
    existing stamp on hot paths to skip the clock read)."""
    if _DISABLED:
        return
    p = _pending_req
    p.append((trace, event,
              t_ns if t_ns is not None else time.perf_counter_ns(),
              args))
    if len(p) >= _REQ_PENDING_MAX:
        _drain_req_events()


def _req_cursor(consumer):
    """The named consumer's ``[next_seq, dropped]`` cell (callers hold
    ``_drain_lock``).  A new consumer registers at the OLDEST seq the
    ring still holds: it can drain everything that survives, and events
    evicted before it existed were never its loss to declare."""
    cur = _req_cursors.get(consumer)
    if cur is None:
        cur = _req_cursors[consumer] = [
            _req_ring[0][0] if _req_ring else _req_seq, 0]
    return cur


def _drain_req_events():
    global _req_seq
    with _drain_lock:
        batch = list(_pending_req)
        if not batch:
            return
        del _pending_req[:len(batch)]
        ring = _req_ring
        seq = _req_seq
        dropped = 0
        cursors = list(_req_cursors.values())
        t_off = _unix_base - _perf_base * 1e-9
        for (trace, event, t, args) in batch:
            if len(ring) == ring.maxlen:
                ev_seq = ring[0][0]
                missed = False
                for cur in cursors:
                    if ev_seq >= cur[0]:
                        cur[1] += 1     # evicting an event this consumer
                        missed = True   # never drained
                if missed:
                    dropped += 1
            ring.append((seq, t_off + t * 1e-9, trace, event, args))
            seq += 1
        _req_seq = seq
        if dropped:
            # counted once per evicted-before-anyone-shipped-it event,
            # however many consumers missed it (each cursor still carries
            # its own per-consumer count)
            counter("serving.trace_dropped").inc(dropped)


def _req_dicts(recs):
    return [{"seq": s, "t": t, "trace": tr, "event": ev,
             "args": args or {}} for (s, t, tr, ev, args) in recs]


def request_events():
    """The whole request-event ring as dicts, oldest first (postmortems
    and tests; does not advance the emitter cursor)."""
    _drain_req_events()
    with _drain_lock:
        return _req_dicts(list(_req_ring))


def consume_request_events(consumer="emitter"):
    """``(new_events, dropped)`` since this CONSUMER's last consume —
    the emitter's per-line payload.  Advances the consumer's own cursor,
    so each event ships exactly once per consumer across the stream's
    lines; ``dropped`` counts events evicted from the ring before this
    consumer could drain them (burst faster than its interval — the
    reader must know the record has a gap).  Distinct consumer names
    never steal each other's events (ISSUE 18: the file emitter and the
    RPC telemetry pull run concurrently against one ring)."""
    _drain_req_events()
    with _drain_lock:
        cur = _req_cursor(consumer)
        evs = [r for r in _req_ring if r[0] >= cur[0]]
        dropped, cur[1] = cur[1], 0
        cur[0] = _req_seq
        return _req_dicts(evs), dropped


def request_events_since(seq, max_events=None):
    """Non-destructive cursor slice for the RPC telemetry pull:
    ``(events, evicted, next_seq, more)`` — every surviving event with
    ``seq >= seq`` (oldest first, at most ``max_events``), the count of
    events the ring evicted after the client's cursor but before this
    pull could see them (declared loss, never silent), the cursor to
    present next, and whether more events remain right now (bounded
    chunking: the caller re-pulls instead of one reply stalling the
    single-threaded RPC/decode loop).  ``seq=None`` starts at the oldest
    surviving event with nothing declared lost.  The server holds no
    per-client state — the client-held cursor makes a re-pull after a
    dropped reply idempotent."""
    _drain_req_events()
    with _drain_lock:
        oldest = _req_ring[0][0] if _req_ring else _req_seq
        if seq is None:
            seq = oldest
        evicted = max(0, oldest - seq)
        evs = [r for r in _req_ring if r[0] >= seq]
        more = False
        if max_events is not None and len(evs) > max_events:
            evs = evs[:max_events]
            more = True
        next_seq = (evs[-1][0] + 1) if evs else max(seq, oldest)
        return _req_dicts(evs), evicted, next_seq, more


def count_token_events(events):
    """Traced token total over request-event dicts: singular ``token``
    events (prefill first tokens) plus len-weighted batched ``tokens``
    events (decode steps).  THE token-accounting law's left-hand side —
    one definition, shared by the bench probe and the law tests, equal
    to the ``serving.tokens`` counter delta bit-exactly."""
    n = 0
    for e in events:
        ev = e.get("event")
        if ev == "token":
            n += 1
        elif ev == "tokens":
            n += len((e.get("args") or {}).get("traces") or ())
    return n


def _unconsume_request_events(evs, dropped, consumer="emitter"):
    """Roll a failed emit's consume back: the events never reached the
    stream, so the consumer's cursor returns to the first unshipped seq
    and its drop count is restored — the next successful line carries
    them.  (Events the ring evicts while the cursor is transiently
    advanced escape the drop accounting — a write failing in the same
    instant the ring overflows — which is as far as best-effort
    telemetry reaches.)"""
    with _drain_lock:
        cur = _req_cursor(consumer)
        if evs:
            cur[0] = min(cur[0], evs[0]["seq"])
        if dropped:
            cur[1] += dropped


# -- alert rules (ISSUE 18) ------------------------------------------------
# Small declarative alerting over the live registry: a rule watches one
# metric (counter delta, gauge predicate, or counter-delta ratio) and,
# when it holds, emits a typed trace-less ``alert`` request event into
# the same stream every consumer already drains — the file emitter, the
# RPC telemetry pull, and postmortems all carry alerts for free, and
# ``serve_report`` / ``fleet_top`` render them.  Evaluated on drain:
# every ``report()`` (so every emitted line, every pull, every
# postmortem) runs :func:`check_alerts` first.

_ALERT_OPS = {
    ">": lambda a, b: a > b, ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b, "<=": lambda a, b: a <= b,
    "==": lambda a, b: a == b, "!=": lambda a, b: a != b,
}


class AlertRule(object):
    """One declarative alert rule.

    Kinds:

    - ``counter_delta`` — fires when the counter rose by more than
      ``threshold`` (default 0) since the previous evaluation; the
      firing's value is the delta.
    - ``gauge`` — fires while ``gauge <op> threshold`` holds.  A
      ``metric`` ending in ``.*`` watches every registered gauge under
      that prefix (one independent firing per matching name — e.g.
      ``rpc.breaker.*`` alerts per replica).
    - ``ratio`` — numerator/denominator counter DELTAS since the last
      evaluation (``metric`` / ``metric2``); fires when the denominator
      moved and the ratio satisfies ``<op> threshold``.

    ``window_s`` rate-limits firings: once a rule fires for a metric it
    stays quiet for that metric until the window elapses — a
    still-held gauge predicate re-alerts every window (a breaker still
    open a minute later should say so again), a counter burst within
    one window alerts once."""

    __slots__ = ("name", "kind", "metric", "metric2", "op", "threshold",
                 "severity", "window_s", "_prev", "_last_fired")

    def __init__(self, name, metric, kind="gauge", op=">", threshold=0,
                 metric2=None, severity="warn", window_s=60.0):
        if kind not in ("counter_delta", "gauge", "ratio"):
            raise ValueError("unknown alert kind: %r" % (kind,))
        if op not in _ALERT_OPS:
            raise ValueError("unknown alert op: %r" % (op,))
        if kind == "ratio" and not metric2:
            raise ValueError("ratio rules need metric2")
        self.name = name
        self.kind = kind
        self.metric = metric
        self.metric2 = metric2
        self.op = op
        self.threshold = threshold
        self.severity = severity
        self.window_s = window_s
        self._prev = {}        # metric name -> last counter value(s)
        self._last_fired = {}  # metric name -> monotonic fire time

    def _metric_names(self):
        if self.kind == "gauge" and self.metric.endswith(".*"):
            pre = self.metric[:-1]      # keep the trailing dot
            with _reg_lock:
                return [n for n in _gauges if n.startswith(pre)]
        return [self.metric]

    def _reset_state(self):
        self._prev.clear()
        self._last_fired.clear()

    def evaluate(self, now):
        """``[(metric_name, value), ...]`` firings this evaluation.
        Caller holds ``_alert_lock`` (rule state is mutated)."""
        fired = []
        op = _ALERT_OPS[self.op]
        for name in self._metric_names():
            if self.kind == "gauge":
                g = _gauges.get(name)
                v = None if g is None else g.value
                hold = v is not None and op(v, self.threshold)
                val = v
            elif self.kind == "counter_delta":
                c = _counters.get(name)
                v = 0 if c is None else c.value
                delta = v - self._prev.get(name, 0)
                self._prev[name] = v
                hold = delta > self.threshold
                val = delta
            else:  # ratio of deltas
                c1 = _counters.get(name)
                c2 = _counters.get(self.metric2)
                v1 = 0 if c1 is None else c1.value
                v2 = 0 if c2 is None else c2.value
                key = (name, self.metric2)
                p1, p2 = self._prev.get(key, (0, 0))
                d1, d2 = v1 - p1, v2 - p2
                self._prev[key] = (v1, v2)
                hold = d2 > 0 and op(d1 / d2, self.threshold)
                val = (d1 / d2) if d2 > 0 else None
            if not hold:
                continue
            last = self._last_fired.get(name)
            if last is not None and now - last < self.window_s:
                continue
            self._last_fired[name] = now
            fired.append((name, val))
        return fired


_alert_rules = []
_alert_lock = threading.Lock()


def add_alert_rule(name, metric, kind="gauge", op=">", threshold=0,
                   metric2=None, severity="warn", window_s=60.0):
    """Install (or replace, by name) one alert rule; returns it."""
    rule = AlertRule(name, metric, kind=kind, op=op, threshold=threshold,
                     metric2=metric2, severity=severity, window_s=window_s)
    with _alert_lock:
        _alert_rules[:] = [r for r in _alert_rules if r.name != name]
        _alert_rules.append(rule)
    return rule


def alert_rules():
    """The installed rules (live objects; treat as read-only)."""
    with _alert_lock:
        return list(_alert_rules)


def clear_alert_rules():
    with _alert_lock:
        del _alert_rules[:]


def install_default_alert_rules():
    """The stock fleet-health rules (OBSERVABILITY.md §14); installed at
    import, idempotent (add_alert_rule replaces by name)."""
    add_alert_rule("slo_shed_engaged", "serving.shed",
                   kind="counter_delta", severity="warn", window_s=30.0)
    add_alert_rule("watchdog_stall", "watchdog.stalls",
                   kind="counter_delta", severity="critical",
                   window_s=30.0)
    add_alert_rule("breaker_open", "rpc.breaker.*", kind="gauge",
                   op=">=", threshold=2, severity="critical",
                   window_s=30.0)
    add_alert_rule("replica_fenced", "rpc.confirmations.fence_expiry",
                   kind="counter_delta", severity="critical",
                   window_s=30.0)
    add_alert_rule("fenced_writeback", "rpc.fenced_results",
                   kind="counter_delta", severity="warn", window_s=30.0)
    add_alert_rule("goodput_collapse", "serving.goodput",
                   kind="ratio", metric2="serving.tokens", op="<",
                   threshold=0.5, severity="warn", window_s=30.0)
    add_alert_rule("orphan_reclaim", "serving.stream.abandoned",
                   kind="counter_delta", severity="warn", window_s=30.0)


def check_alerts(now=None):
    """Evaluate every installed rule against the live registry; each
    firing increments ``telemetry.alerts`` and records a trace-less
    ``alert`` request event (``args`` = rule/severity/metric/value) that
    rides the normal drain to every consumer.  Returns the fired args
    dicts.  Called from :func:`report` so every emitted line, RPC pull,
    and postmortem evaluates on drain; replicas also call it
    periodically from their serve loop."""
    if now is None:
        now = time.monotonic()
    fired = []
    with _alert_lock:
        for rule in _alert_rules:
            for (mname, val) in rule.evaluate(now):
                args = {"rule": rule.name, "severity": rule.severity,
                        "metric": mname}
                if val is not None:
                    args["value"] = (round(val, 6)
                                     if isinstance(val, float) else val)
                fired.append(args)
    for args in fired:
        # counter always counts (registry stays live under
        # MXTPU_TELEMETRY_OFF); the event records only while enabled
        counter("telemetry.alerts").inc()
        note_request_event("", "alert", args=args)
    return fired


# -- reporting -------------------------------------------------------------
def identity():
    """Who this stream belongs to inside the job: the elastic launch
    contract (world_size / rank / slot / attempt, re-read from env so a
    post-reshard process stamps its NEW membership) plus the pid.  The
    job aggregator keys every line by this block — a re-ranked survivor
    keeps its slot while its rank shifts, and the attempt field is what
    segments a merged timeline at elastic transitions."""
    try:
        from . import elastic as _elastic
        mem = _elastic.membership()
        return {"world_size": mem["world_size"], "rank": mem["rank"],
                "slot": mem["slot"], "attempt": mem["attempt"],
                "pid": os.getpid()}
    except Exception:
        # interpreter teardown: a final emitter line / late postmortem
        # must still be a complete document
        return {"world_size": None, "rank": None, "slot": None,
                "attempt": None, "pid": os.getpid()}


def clock_anchor():
    """The monotonic↔unix correspondence of this process: every
    perf_counter_ns stamp in its records maps to wall-clock time as
    ``unix + (perf_ns_stamp - perf_ns) * 1e-9`` — the base pair the
    flight recorder already uses for ``t_unix``.  Published on every
    report line so a cross-rank trace merge shares one time axis without
    trusting each rank's trace-local origin."""
    return {"unix": _unix_base, "perf_ns": _perf_base,
            "mono_ns": time.monotonic_ns() - time.perf_counter_ns()}


def report():
    """One JSON-able snapshot of everything: counters, gauges, phase
    histograms (from spans / train steps), free histograms, profiler
    step_stats, flight-ring occupancy, and the job-scope identity +
    clock anchor (schema mxtpu-telemetry-2).  This is the emitter's line
    format and StepStatsMonitor's data source.  Alert rules are
    evaluated first ("on drain"), so the snapshot and any consumer
    draining events right after it see this evaluation's firings."""
    check_alerts()
    _drain_steps()
    with _reg_lock:
        counters = {n: c.value for n, c in _counters.items()}
        gauges = {n: g.value for n, g in _gauges.items()}
        hists = dict(_histograms)
        spans = set(_span_names)
    doc = {
        "schema": SCHEMA_REPORT,
        "time_unix": time.time(),
        "pid": os.getpid(),
        "identity": identity(),
        "clock": clock_anchor(),
        "counters": counters,
        "gauges": gauges,
        "phases": {n: h.snapshot() for n, h in hists.items()
                   if n in spans},
        "histograms": {n: h.snapshot() for n, h in hists.items()
                       if n not in spans},
        "step_stats": _profiler().step_stats(),
        "flight": {"len": len(_flight), "maxlen": _flight.maxlen},
    }
    try:
        # the periodic serving status line (ISSUE 13): every report from
        # a process with live engines says what they are serving right
        # now — occupancy, free pages, SLO state, current weights epoch.
        # sys.modules-gated exactly like the postmortem block: a
        # training process must not import the serving stack for this.
        eng_mod = sys.modules.get("mxnet_tpu.serving.engine")
        if eng_mod is not None:
            snaps = eng_mod.live_snapshot()
            if snaps:
                doc["serving"] = snaps
    except Exception:
        pass  # a half-dead engine must never take a report down
    return doc


_PULL_EVENTS_DEFAULT = max(1, _env_int("MXTPU_TELEMETRY_PULL_EVENTS",
                                       2048))


def pull_snapshot(req_seq=None, step_seq=None, max_events=None):
    """One telemetry-pull payload (ISSUE 18): ``(line_doc, cursor,
    more)``.  ``line_doc`` is a full :func:`report` document on the
    ``mxtpu-telemetry-2`` schema, extended with the request events and
    flight records newer than the client-held cursor —
    ``req_events``/``req_dropped`` exactly as the file emitter writes
    them (``req_dropped`` here = events evicted past the CLIENT's
    cursor, declared per pull), plus ``last_steps``/``steps_dropped``
    for the flight-ring slice — so a collector can append the line
    verbatim to a ``stream-*.jsonl`` file and every existing report
    reads it unchanged.  ``cursor`` is ``{"req_seq", "step_seq"}`` to
    present next; ``more`` says a chunk boundary was hit (``max_events``
    bounds BOTH slices; default ``MXTPU_TELEMETRY_PULL_EVENTS``) and the
    client should pull again.  Purely read-only on the server: no
    consumer cursor moves, so a lost reply costs nothing — the client
    re-pulls with its old cursor."""
    if max_events is None:
        max_events = _PULL_EVENTS_DEFAULT
    doc = report()
    evs, evicted, next_seq, more_ev = request_events_since(
        req_seq, max_events)
    recs, steps_dropped, next_step, more_st = flight_records_since(
        step_seq, max_events)
    if evs:
        doc["req_events"] = evs
    if evicted:
        doc["req_dropped"] = evicted
    if recs:
        doc["last_steps"] = recs
    if steps_dropped:
        doc["steps_dropped"] = steps_dropped
    cursor = {"req_seq": next_seq, "step_seq": next_step}
    doc["pull"] = dict(cursor, more=bool(more_ev or more_st))
    return doc, cursor, bool(more_ev or more_st)


def reset():
    """Clear every metric, the flight ring, and the step sequence (tests
    and benches; the monotonic XLA compile-event count is exempt)."""
    global _step_seq, _last_dispatch, _last_compile, _dumped
    # _drain_lock around the WHOLE reset: a concurrent emitter-thread
    # drain must neither fold pre-reset pending records into the just-
    # zeroed histograms nor re-append them into the just-cleared ring.
    # Lock order _drain_lock -> _reg_lock matches _drain_steps (via
    # _span_hist); nothing takes them in the reverse order.
    global _req_seq
    with _drain_lock:
        del _pending_steps[:]
        del _pending_req[:]
        _pending_faults.clear()
        _req_ring.clear()
        _req_seq = 0
        _req_cursors.clear()
        _req_cursors["emitter"] = [0, 0]
        with _reg_lock:
            # zero IN PLACE: hot callers hold metric objects (counter()'s
            # documented contract), and clearing the dicts would orphan
            # those handles — their post-reset increments would vanish
            for c in _counters.values():
                c.value = 0
            for g in _gauges.values():
                g.value = None
            for h in _histograms.values():
                h.count = 0
                h.sum = 0.0
                h.min = None
                h.max = None
                h._zeros = 0
                h._buckets = {}
        _train_hists.clear()
        _flight.clear()
        _step_seq = 0
        prof = _profiler()
        _last_dispatch = prof._dispatch_count
        _last_compile = prof._compile_count
    # alert-rule deltas baseline against the just-zeroed counters (a
    # stale _prev would read the first post-reset increments as a
    # negative delta and go quiet); rate-limit windows re-arm too
    with _alert_lock:
        for r in _alert_rules:
            r._reset_state()
    _dumped = False


# -- postmortem ------------------------------------------------------------
_dumped = False


def dump_postmortem(reason, path=None):
    """Write the crash-postmortem JSON: the full report() plus the last-K
    step records and per-site fault firings, atomically (a crash during
    the dump must not leave a torn postmortem — and without the
    checkpoint layer's fault-injection sites, which must neither tear
    this record nor have their budgets consumed by it).

    Without an explicit ``path`` the file goes to
    ``$MXTPU_POSTMORTEM_DIR/postmortem-<pid>.json``; unset dir means
    postmortems are off and None is returned.  Only the first implicit
    dump per process wins (excepthook fires before atexit; both route
    here)."""
    global _dumped
    implicit = path is None
    if implicit:
        d = os.environ.get("MXTPU_POSTMORTEM_DIR")
        if not d or _dumped:
            return None
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, "postmortem-%d.json" % os.getpid())
    doc = report()
    doc["schema"] = SCHEMA_POSTMORTEM
    doc["reason"] = reason
    from . import fault as _fault
    doc["fault_fires"] = _fault.fire_counts()
    doc["last_steps"] = flight_records()
    recs = request_events()
    if recs:
        # the request-scope ring (ISSUE 13): a dying replica's record
        # carries the recent per-request lifecycle events the same way
        # it carries its per-step ring — serve_report dedups against
        # already-emitted stream lines by (pid, seq)
        doc["request_trace"] = recs
    try:
        # hang-defense context: lease ages/timeouts at the moment of
        # death — for a watchdog stall this names the wedged phase
        from . import watchdog as _watchdog
        doc["watchdog"] = _watchdog.snapshot()
    except Exception:
        pass  # interpreter teardown
    try:
        # elastic context: world_size/rank/slot/attempt at the moment of
        # death — a postmortem from a resharded job must say which
        # membership it died under (ROBUSTNESS.md §9)
        from . import elastic as _elastic
        doc["membership"] = _elastic.snapshot()
    except Exception:
        pass  # interpreter teardown
    try:
        # serving context (ISSUE 11): a dying/stalled REPLICA's record
        # must say what it was serving — resident slots, queue depth,
        # page accounting.  sys.modules-gated: a training process that
        # never imported the serving stack must not start importing the
        # jax-adjacent engine module mid-crash.
        eng_mod = sys.modules.get("mxnet_tpu.serving.engine")
        if eng_mod is not None:
            snaps = eng_mod.live_snapshot()
            if snaps:
                doc["serving"] = snaps
    except Exception:
        pass  # the postmortem must never fail on a half-dead engine
    # the plain writer: a ckpt.write.* fault armed for the checkpoint
    # layer must not fire here and tear the record of the crash itself
    from .checkpoint import _plain_atomic_write
    _plain_atomic_write(path, json.dumps(doc, indent=1).encode("utf-8"))
    if implicit:
        # explicit-path dumps (health snapshots) must not suppress the
        # one implicit crash/atexit postmortem this process gets
        _dumped = True
    return path


_orig_excepthook = None
_hooks_installed = False


def _excepthook(tp, val, tb):
    try:
        dump_postmortem("%s: %s" % (tp.__name__, val))
    except Exception:
        pass  # the postmortem must never mask the real crash
    (_orig_excepthook or sys.__excepthook__)(tp, val, tb)


def _at_exit():
    stop_emitter()
    try:
        skipped = _profiler().step_stats()["skipped_steps"]
        if skipped and not _dumped:
            dump_postmortem(
                "atexit: run ended with %d divergence-guard skipped "
                "steps" % skipped)
    except Exception:
        pass


def install_crash_hooks():
    """Chain the postmortem dump into sys.excepthook (covers unhandled
    MXNetError — e.g. the divergence guard's K-consecutive-skips raise —
    and every other crash) and register the atexit skipped-steps dump.
    Idempotent; installed at import."""
    global _orig_excepthook, _hooks_installed
    if _hooks_installed:
        return
    _hooks_installed = True
    _orig_excepthook = sys.excepthook
    sys.excepthook = _excepthook
    atexit.register(_at_exit)


# -- periodic JSON-lines emitter -------------------------------------------
_emitter = None
# serializes line emission: the periodic thread, the stop-path final
# line, and any future explicit flush must never interleave their bytes
# in the stream file (a report line easily exceeds stdio's buffer, so
# two concurrent buffered writers WOULD interleave mid-line)
_emit_lock = threading.Lock()


def _parse_emitter_spec(spec):
    """``path[:interval]`` — a trailing ``:<float>`` is the period in
    seconds (default 10); everything else is the path (so paths with
    colons still work as long as the last segment isn't a number)."""
    path, sep, tail = spec.rpartition(":")
    if sep:
        try:
            return path, max(0.05, float(tail))
        except ValueError:
            pass
    return spec, 10.0


def _emit_line(path, final=False, lock_timeout=None):
    """Append one report line as a SINGLE ``os.write`` on an O_APPEND
    fd: all-or-nothing against a crash (``os._exit``, SIGKILL) landing
    mid-line, where a buffered ``f.write`` flushes in stdio-buffer-sized
    chunks and a death between chunks leaves a torn line the reader must
    skip.  The final line (stop/atexit path) carries the flight ring —
    the same last-K per-step records a crash postmortem gets — plus a
    ``final`` marker, so the job aggregator can trace a cleanly-exited
    rank's recent steps too.

    ``lock_timeout`` bounds the ``_emit_lock`` acquire — the
    stop_emitter fallback runs at atexit and must skip its line rather
    than hang shutdown behind a thread wedged mid-write (e.g. os.write
    to a hung mount) still holding the lock."""
    try:
        doc = report()
        if final:
            doc["final"] = True
            doc["last_steps"] = flight_records()
        if not _emit_lock.acquire(
                timeout=-1 if lock_timeout is None else lock_timeout):
            return
        evs = dropped = None
        try:
            # request-scope events recorded since the previous line:
            # the stream accumulates the full lifecycle record one
            # bounded payload at a time (each event ships exactly once;
            # evictions that outran the emitter are declared, never
            # silent).  Consumed only once the lock is HELD — and
            # rolled back if the write fails below — so a skipped or
            # failed line never silently swallows the cursor advance.
            evs, dropped = consume_request_events()
            if evs:
                doc["req_events"] = evs
            if dropped:
                doc["req_dropped"] = dropped
            data = (json.dumps(doc) + "\n").encode("utf-8")
            fd = os.open(path, os.O_WRONLY | os.O_APPEND | os.O_CREAT,
                         0o644)
            try:
                os.write(fd, data)
            finally:
                os.close(fd)
        except Exception:
            _unconsume_request_events(evs, dropped)
            raise
        finally:
            _emit_lock.release()
    except Exception:
        pass  # telemetry must never take the run down


def start_emitter(path, interval=10.0):
    """Append one report() line to ``path`` every ``interval`` seconds
    from a daemon thread (plus a final line on stop/exit)."""
    global _emitter
    stop_emitter()
    stop = threading.Event()
    state = {"final": False}

    def loop():
        while not stop.wait(interval):
            _emit_line(path)
        # final line so short runs still leave a trace; the flag keeps
        # the stop path from double-writing it when the join times out —
        # set only AFTER the write returns, so a thread wedged INSIDE
        # its final flush (report() blocked on a lock, os.write to a
        # hung mount) still looks unfinished to stop_emitter's fallback
        _emit_line(path, final=True)
        state["final"] = True

    t = threading.Thread(target=loop, daemon=True,
                         name="mxtpu-telemetry-emitter")
    t.start()
    _emitter = (t, stop, path, state)
    return t


def stop_emitter():
    global _emitter
    if _emitter is None:
        return
    t, stop, path, state = _emitter
    _emitter = None
    stop.set()
    t.join(timeout=5.0)
    if t.is_alive() and not state["final"]:
        # emitter thread wedged mid-report (it never reached its final
        # flush): write the final line from the caller — bounded lock
        # acquire, because the wedged thread may be stuck INSIDE a
        # write still holding _emit_lock, and this path runs at atexit
        # where blocking forever would convert a lost final line into a
        # hung shutdown.  If the lock does come, the two lines land
        # whole, never interleaved.
        _emit_line(path, final=True, lock_timeout=2.0)


def _maybe_start_emitter():
    spec = os.environ.get("MXTPU_TELEMETRY")
    if not spec:
        return
    path, interval = _parse_emitter_spec(spec)
    if not path:
        # telemetry must never take the run down — and this runs at
        # import time, where a raise would kill every process in the env
        import logging
        logging.warning(
            "mxnet_tpu: bad MXTPU_TELEMETRY spec %r (want "
            "path[:interval]); emitter disabled", spec)
        return
    start_emitter(path, interval)


install_crash_hooks()
_install_compile_hook()
install_default_alert_rules()
_maybe_start_emitter()
