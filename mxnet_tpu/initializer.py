"""Weight initializers.

TPU-native port of the reference initializer registry
(/root/reference/python/mxnet/initializer.py:53-676): the same
attribute-driven dispatch (``_weight`` → weight init, ``_bias`` → zero,
``_gamma`` → one, ...), the same classes (Uniform/Normal/Orthogonal/Xavier/
MSRAPrelu/Bilinear/LSTMBias/One/Zero/Constant), and the Mixed/Load helpers.
Randomness draws from the global functional key chain (mxnet_tpu.random).
"""
from __future__ import annotations

import json
import re

import numpy as _np

from . import random as _random
from .ndarray.ndarray import NDArray

__all__ = ["InitDesc", "Initializer", "Uniform", "Normal", "Orthogonal",
           "Xavier", "MSRAPrelu", "Bilinear", "LSTMBias", "FusedRNN",
           "One", "Zero", "Constant", "Mixed", "Load", "register", "create"]

_INIT_REGISTRY = {}


def register(klass):
    _INIT_REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(name, **kwargs):
    """Resolve an initializer by registered name (reference registry.py)."""
    if isinstance(name, Initializer):
        return name
    key = str(name).lower()
    # reference registry aliases (initializer.py @init.register aliases)
    key = {"zeros": "zero", "ones": "one"}.get(key, key)
    if key not in _INIT_REGISTRY:
        raise ValueError(
            "Unknown initializer %r. Registered: %s"
            % (name, sorted(_INIT_REGISTRY)))
    return _INIT_REGISTRY[key](**kwargs)


class InitDesc(str):
    """Name + attrs descriptor handed to initializers (reference :53)."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Initializer:
    """Base initializer with the reference's name-pattern dispatch."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, arr):
        if not isinstance(desc, str):
            raise TypeError("desc must be str/InitDesc")
        if isinstance(desc, InitDesc) and desc.global_init is None:
            desc.global_init = self
        init = desc.attrs.get("__init__", "") if isinstance(desc, InitDesc) \
            else ""
        if init:
            klass, kwargs = json.loads(init)
            _INIT_REGISTRY[klass.lower()](**kwargs)._init_weight(desc, arr)
            return
        name = desc.lower()
        if name.endswith("upsampling"):
            self._init_bilinear(desc, arr)
        elif name.endswith("bias"):
            self._init_bias(desc, arr)
        elif name.endswith("gamma"):
            self._init_gamma(desc, arr)
        elif name.endswith("beta"):
            self._init_beta(desc, arr)
        elif name.endswith("weight"):
            self._init_weight(desc, arr)
        elif name.endswith("parameters"):  # fused RNN packed weights
            self._init_weight(desc, arr)
        elif name.endswith("moving_mean") or name.endswith("running_mean"):
            self._init_zero(desc, arr)
        elif name.endswith("moving_var") or name.endswith("running_var"):
            self._init_one(desc, arr)
        elif name.endswith("moving_inv_var"):
            self._init_zero(desc, arr)
        elif name.endswith("moving_avg"):
            self._init_zero(desc, arr)
        else:
            self._init_default(desc, arr)

    # -- family defaults ---------------------------------------------------
    def _init_bilinear(self, name, arr):
        shape = arr.shape
        weight = _np.zeros(_np.prod(shape), dtype="float32")
        f = _np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(_np.prod(shape)):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        self._set(arr, weight.reshape(shape))

    def _init_zero(self, name, arr):
        self._set(arr, _np.zeros(arr.shape, dtype="float32"))

    def _init_one(self, name, arr):
        self._set(arr, _np.ones(arr.shape, dtype="float32"))

    def _init_bias(self, name, arr):
        self._init_zero(name, arr)

    def _init_gamma(self, name, arr):
        self._init_one(name, arr)

    def _init_beta(self, name, arr):
        self._init_zero(name, arr)

    def _init_weight(self, name, arr):
        raise NotImplementedError("Must override it")

    def _init_default(self, name, arr):
        raise ValueError(
            "Unknown initialization pattern for %s. Default initialization "
            "is now limited to \"weight\", \"bias\", \"gamma\" (1.0), and "
            "\"beta\" (0.0)." % name)

    # -- helpers -----------------------------------------------------------
    @staticmethod
    def _set(arr, value):
        if isinstance(arr, NDArray):
            arr[:] = _to_nd(value, arr)
        else:
            arr[:] = value

    @staticmethod
    def _rand_normal(shape, sigma):
        import jax
        key = _random.next_key()
        return _np.asarray(jax.random.normal(key, shape)) * sigma

    @staticmethod
    def _rand_uniform(shape, scale):
        import jax
        key = _random.next_key()
        return _np.asarray(jax.random.uniform(
            key, shape, minval=-scale, maxval=scale))


def _to_nd(value, like):
    from . import nd
    return nd.array(_np.asarray(value, dtype=_np.float32))


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, name, arr):
        self._set(arr, self._rand_uniform(arr.shape, self.scale))


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, name, arr):
        self._set(arr, self._rand_normal(arr.shape, self.sigma))


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, name, arr):
        nout = arr.shape[0]
        nin = int(_np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = _np.random.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = _np.random.normal(0.0, 1.0, (nout, nin))
        u, _, v = _np.linalg.svd(tmp, full_matrices=False)
        res = u if u.shape == tmp.shape else v
        self._set(arr, (self.scale * res).reshape(arr.shape))


@register
class Xavier(Initializer):
    """The reference's default for conv/FC nets (initializer.py:431)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise ValueError("Xavier initializer cannot be applied to "
                             "vector %s. It requires at least 2D." % name)
        if len(shape) > 2:
            hw_scale = _np.prod(shape[2:])
        fan_in = shape[1] * hw_scale
        fan_out = shape[0] * hw_scale
        factor = 1.0
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise ValueError("Incorrect factor type")
        scale = _np.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            self._set(arr, self._rand_uniform(shape, scale))
        elif self.rnd_type == "gaussian":
            self._set(arr, self._rand_normal(shape, scale))
        else:
            raise ValueError("Unknown random type")


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    def _init_weight(self, name, arr):
        self._init_bilinear(name, arr)


@register
class LSTMBias(Initializer):
    """Forget-gate bias init (reference initializer.py:620)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        b = _np.zeros(arr.shape, dtype="float32")
        num_hidden = arr.shape[0] // 4
        b[num_hidden:2 * num_hidden] = self.forget_bias  # i, f, g, o order
        self._set(arr, b)


@register
class One(Initializer):
    def _init_weight(self, name, arr):
        self._init_one(name, arr)


@register
class Zero(Initializer):
    def _init_weight(self, name, arr):
        self._init_zero(name, arr)


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, name, arr):
        self._set(arr, _np.full(arr.shape, self.value, dtype="float32"))


@register
class FusedRNN(Initializer):
    """Initialize a fused-RNN packed parameter blob
    (reference initializer.py:FusedRNN).

    Unpacks the blob via FusedRNNCell.unpack_weights, applies ``init`` to
    the per-gate weights, zeros biases, sets the LSTM i2h forget-gate bias
    to ``forget_bias``, and packs back — so fused and unfused stacks start
    from equivalent states.
    """

    def __init__(self, init, num_hidden, num_layers, mode,
                 bidirectional=False, forget_bias=1.0):
        if isinstance(init, Initializer):
            init_str = init.dumps()
        else:
            init_str = init  # None or dumps() JSON
        super().__init__(init=init_str, num_hidden=num_hidden,
                         num_layers=num_layers, mode=mode,
                         bidirectional=bidirectional,
                         forget_bias=forget_bias)
        self._init = init if isinstance(init, Initializer) else (
            None if init is None else
            _INIT_REGISTRY[json.loads(init)[0].lower()](
                **json.loads(init)[1]))
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        from .rnn.rnn_cell import FusedRNNCell
        cell = FusedRNNCell(self._num_hidden, num_layers=self._num_layers,
                            mode=self._mode,
                            bidirectional=self._bidirectional,
                            forget_bias=self._forget_bias, prefix="")
        args = cell.unpack_weights({"parameters": arr})
        inner = self._init
        if inner is None and isinstance(desc, InitDesc) \
                and desc.global_init is not None:
            inner = desc.global_init
        if inner is None:
            inner = Uniform(0.1)
        for name in args:
            desc_i = InitDesc(name, global_init=None)
            if name.endswith("weight"):
                inner._init_weight(desc_i, args[name])
            elif name.endswith("bias"):
                self._init_zero(desc_i, args[name])
                if self._mode == "lstm" and name.endswith("i2h_f_bias"):
                    self._set(args[name], _np.full(
                        args[name].shape, self._forget_bias,
                        dtype="float32"))
        packed = cell.pack_weights(args)
        self._set(arr, packed["parameters"].asnumpy())


class Mixed:
    """Pattern → initializer dispatch (reference initializer.py:226)."""

    def __init__(self, patterns, initializers):
        if len(patterns) != len(initializers):
            raise ValueError("patterns and initializers must have the same "
                             "length")
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise ValueError("Parameter name %s did not match any pattern"
                         % name)


class Load:
    """Init from a saved param dict, falling back to default_init."""

    def __init__(self, param, default_init=None, verbose=False):
        if isinstance(param, str):
            from .ndarray import load as nd_load
            param = nd_load(param)
        self.param = {k[4:] if k.startswith(("arg:", "aux:")) else k: v
                      for k, v in param.items()}
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr):
        if name in self.param:
            if self.param[name].shape != arr.shape:
                raise ValueError("Parameter %s cannot be initialized from "
                                 "loading. Shape mismatch, target %s vs "
                                 "loaded %s" % (name, arr.shape,
                                                self.param[name].shape))
            arr[:] = self.param[name]
        else:
            if self.default_init is None:
                raise ValueError("Cannot Initialize parameter %s" % name)
            self.default_init(name, arr)
