"""Runtime kernel compilation (`mx.rtc`).

The reference compiled CUDA C strings at runtime (python/mxnet/rtc.py:
``Rtc(name, inputs, outputs, kernel_body)`` then ``push(ins, outs,
grid, block)``).  On TPU the compiler is XLA, so the TPU-native
equivalent compiles *JAX source* at runtime:

- :class:`Rtc` keeps the reference signature: the kernel body is a
  Python/`jnp` block that reads the declared input names and assigns the
  declared output names.  ``push`` jit-compiles it once per shape
  signature and writes the results into the output NDArrays.  The
  ``grid``/``block`` arguments are accepted for signature parity and
  ignored — XLA owns the schedule.
- :class:`PallasRtc` is the hand-scheduled tier: the source defines a
  Pallas kernel function (operating on ``Ref`` blocks) that is staged
  through ``pl.pallas_call`` — the actual analogue of writing a CUDA
  kernel, on the TPU's own kernel language.  Off-TPU it runs in the
  Pallas interpreter.

Both compile USER-SUPPLIED SOURCE, exactly like the reference's nvrtc
path — only use with trusted input.
"""
from __future__ import annotations

import textwrap

from .base import MXNetError
from .ndarray.ndarray import NDArray

__all__ = ["Rtc", "PallasRtc"]


def _names_of(pairs):
    """Reference Rtc takes [(name, ndarray), ...]; also accept plain
    name lists."""
    out = []
    for p in pairs:
        out.append(p[0] if isinstance(p, (tuple, list)) else p)
    return out


class Rtc:
    """Runtime-compiled elementwise/tensor kernel from JAX source.

    ::

        rtc = mx.rtc.Rtc("axpy", [("x", x), ("a", a)], [("y", y)],
                         "y = a * x + jnp.sin(x)")
        rtc.push([x, a], [y])

    The body sees ``jnp``, ``lax``, ``np`` and the named inputs; it must
    assign every declared output name.
    """

    def __init__(self, name, inputs, outputs, kernel):
        self.name = name
        self._input_names = _names_of(inputs)
        self._output_names = _names_of(outputs)
        self._source = textwrap.dedent(kernel)
        self._jitted = None
        code = compile(self._source, "<rtc:%s>" % name, "exec")

        def run(*arrays):
            import jax.numpy as jnp
            from jax import lax
            import numpy as np
            ns = {"jnp": jnp, "lax": lax, "np": np}
            ns.update(zip(self._input_names, arrays))
            exec(code, ns)
            missing = [o for o in self._output_names if o not in ns]
            if missing:
                raise MXNetError(
                    "rtc kernel %r did not assign output(s) %s"
                    % (name, missing))
            return tuple(ns[o] for o in self._output_names)

        self._run = run

    def push(self, inputs, outputs, grid_dims=None, block_dims=None):
        """Run the kernel: reads ``inputs``, writes into ``outputs``
        (reference rtc.py:push; grid/block are ignored — XLA schedules).
        """
        del grid_dims, block_dims
        if self._jitted is None:
            import jax
            self._jitted = jax.jit(self._run)
        raws = [x._data if isinstance(x, NDArray) else x for x in inputs]
        results = self._jitted(*raws)
        for dst, res in zip(outputs, results):
            dst._set_data(res.astype(dst._data.dtype))
        return outputs


class PallasRtc:
    """Runtime-compiled Pallas TPU kernel.

    The source must define a function named ``kernel`` taking Pallas
    refs — inputs first, outputs last::

        src = '''
        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...] * 2.0
        '''
        k = mx.rtc.PallasRtc("double", src)
        y = k(x)                       # same shape/dtype as x by default

    ``out_shape`` (shape tuple or jax.ShapeDtypeStruct) overrides the
    default same-as-first-input output.  ``grid``/``in_specs``/
    ``out_specs`` pass straight through to ``pl.pallas_call`` for blocked
    kernels.  On non-TPU backends the kernel runs in the Pallas
    interpreter, so unit tests run anywhere.
    """

    def __init__(self, name, source, out_shape=None, grid=None,
                 in_specs=None, out_specs=None):
        self.name = name
        self._source = textwrap.dedent(source)
        ns = {}
        exec(compile(self._source, "<pallas_rtc:%s>" % name, "exec"), ns)
        if "kernel" not in ns:
            raise MXNetError(
                "PallasRtc source for %r must define a function named "
                "'kernel'" % name)
        self._kernel = ns["kernel"]
        self._out_shape = out_shape
        self._grid = grid
        self._in_specs = in_specs
        self._out_specs = out_specs
        self._compiled = {}

    def _build(self, raws):
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        if self._out_shape is None:
            out = jax.ShapeDtypeStruct(raws[0].shape, raws[0].dtype)
        elif hasattr(self._out_shape, "shape"):
            out = self._out_shape
        else:
            out = jax.ShapeDtypeStruct(tuple(self._out_shape),
                                       raws[0].dtype)
        interpret = jax.devices()[0].platform != "tpu"
        kwargs = {}
        if self._grid is not None:
            kwargs["grid"] = self._grid
        if self._in_specs is not None:
            kwargs["in_specs"] = self._in_specs
        if self._out_specs is not None:
            kwargs["out_specs"] = self._out_specs
        call = pl.pallas_call(self._kernel, out_shape=out,
                              interpret=interpret, **kwargs)
        return jax.jit(call)

    def __call__(self, *inputs):
        raws = [x._data if isinstance(x, NDArray) else x for x in inputs]
        key = tuple((tuple(r.shape), str(r.dtype)) for r in raws)
        if key not in self._compiled:
            self._compiled[key] = self._build(raws)
        out = self._compiled[key](*raws)
        if any(isinstance(x, NDArray) for x in inputs):
            ctx = next(x._ctx for x in inputs if isinstance(x, NDArray))
            return NDArray(out, ctx)
        return out
